"""NameNode: the metadata plane.

Re-expression of the reference's NameNode stack — FSNamesystem (namespace +
lease manager, FSNamesystem.java, 8 kLoC), FSDirectory (INode tree),
BlockManager (block->location map, replication scheduling,
BlockManager.java:158), DatanodeManager + HeartbeatManager
(HeartbeatManager.java:44 dead-node detection), NameNodeRpcServer — collapsed
into one clean daemon with the same responsibilities:

- namespace ops (mkdir/create/addBlock/complete/delete/rename/listing)
- per-file **reduction scheme** attribute, chosen at create time: the explicit
  policy that replaces the reference's hardcoded ``compressor`` static
  (DataNode.java:438) and MapReduce-header sniffing (BlockReceiver.java:800-820)
- lease management with expiry recovery (LeaseManager analog)
- block map rebuilt from block reports; never persisted (HDFS invariant)
- heartbeat-driven command delivery: replicate / invalidate
  (DNA_TRANSFER / DNA_INVALIDATE, §3.5 of SURVEY.md)
- durability via EditLog + fsimage (server/editlog.py)
- observer read plane: a third role serving the read-only RPC set with a
  bounded-staleness guarantee and an msync barrier — the
  ObserverReadProxyProvider.java:60 / GlobalStateIdContext.java:40 state-id
  protocol re-expressed on the msgpack reply envelope

Locking: one namesystem lock (the reference's FSNamesystem global lock) —
correct first, sharded later if metadata ops ever become the bottleneck.
"""

from __future__ import annotations

import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any

from hdrf_tpu.config import NameNodeConfig
from hdrf_tpu.proto.rpc import RpcError, RpcServer
from hdrf_tpu.server import permissions as perm
from hdrf_tpu.server.editlog import EditLog
from hdrf_tpu.server.permissions import Attrs, DirNode
from hdrf_tpu.utils import (fault_injection, flight_archive,
                            flight_recorder, lockprof, log, metrics, outlier,
                            retry, tenants, tracing)
from hdrf_tpu.utils.watchdog import StallWatchdog

_M = metrics.registry("namenode")


@dataclass
class FileNode:
    replication: int
    scheme: str
    blocks: list[int] = field(default_factory=list)  # block ids, or group
    # leader ids for EC files (groups resolved via NameNode._groups)
    complete: bool = False
    mtime: float = 0.0
    ec: str | None = None  # EC policy name ("rs-6-3-64k") or None
    attrs: Attrs = field(default_factory=lambda: Attrs(
        "hdrf", "supergroup", 0o644))
    # stable inode id (INodeId analog): assigned at creation from a
    # journaled counter, persisted through fsimage and snapshot freezes —
    # what lets snapshot diff distinguish a rename from delete+create
    inode_id: int = 0


@dataclass
class BlockInfo:
    block_id: int
    gen_stamp: int
    length: int  # logical; -1 until the client reports it at complete()
    path: str
    # serving replicas: DNs holding the CURRENT generation
    locations: set[str] = field(default_factory=set)  # dn_ids
    # the allocation's intended pipeline (BlockInfoUnderConstruction
    # .expectedLocations): lease recovery queries these DNs DIRECTLY
    # instead of racing their asynchronous IBRs — soft state, rebuilt
    # from reports after an NN restart
    expected: list[str] = field(default_factory=list)
    # every live replica ever reported, any generation: dn_id ->
    # (gen_stamp, length).  This is what lease recovery consults — an IBR
    # must never fix a UC block's length (first-reporter-wins would violate
    # the min-CRC-verified-prefix invariant), and a stale-generation replica
    # that is the block's only copy must never be destroyed (the reference's
    # commitBlockSynchronization restamps it instead).
    reported: dict[str, tuple[int, int]] = field(default_factory=dict)
    # per-replica storage type from reports (DatanodeStorageInfo analog):
    # dn_id -> "DISK"/"SSD"/...; absent for DNs that report untyped
    storage_of: dict[str, str] = field(default_factory=dict)


@dataclass
class SymNode:
    """Symbolic link inode (INodeSymlink analog).  Resolution is
    CLIENT-side, as in the reference: the NN answers a path touching a
    symlink with a SymlinkRedirect error carrying the resolved path, and
    the client retries (UnresolvedPathException / FileContext retry)."""

    target: str
    attrs: Attrs = field(default_factory=lambda: Attrs(
        "hdrf", "supergroup", 0o777))
    inode_id: int = 0


class SymlinkRedirect(Exception):
    """Raised mid-resolution; the message IS the resolved path."""


def _frozen_inode_id(node: list) -> int:
    """Inode id embedded in a frozen-tree node (0 = pre-inode-id legacy)."""
    if node[0] == "f":
        return node[8] if len(node) > 8 else 0
    return node[3] if len(node) > 3 else 0   # "d" and "l" share the slot


def _index_frozen(tree: list) -> dict:
    """Flatten a frozen tree into {identity_key: record}.  The key is the
    inode id when present; legacy id-0 nodes fall back to path identity
    (diff then degrades to delete+create for their renames — exactly the
    pre-inode-id information content)."""
    idx: dict = {}

    def walk(node: list, path: str, parent_key, name: str):
        nid = _frozen_inode_id(node)
        key = nid if nid else f"p:{path or '/'}"
        rec = {"path": path or "/", "parent": parent_key, "name": name,
               "kind": node[0]}
        if node[0] == "d":
            # content signature = attrs only; membership is tracked via
            # the children map (a changed map marks the DIR as modified,
            # HDFS's "containing directory is reported modified" rule)
            rec["sig"] = repr(node[2] if len(node) > 2 else None)
            kids = {}
            for cname, child in node[1].items():
                ck = walk(child, f"{path}/{cname}", key, cname)
                kids[cname] = ck
            rec["children"] = kids
        else:
            rec["sig"] = repr(node[:8] if node[0] == "f" else node[:3])
        idx[key] = rec
        return key

    walk(tree, "", None, "")
    return idx


def _diff_trees(a: list, b: list) -> list[dict]:
    """SnapshotDiffInfo's delta computation over two frozen trees: a node
    present in both counts as RENAMEd iff its (parent, name) changed, and
    MODIFYd iff its content signature (or, for dirs, child membership)
    changed; unmatched nodes are CREATE/DELETE."""
    ia, ib = _index_frozen(a), _index_frozen(b)
    entries: list[dict] = []
    for k, rb in ib.items():
        ra = ia.get(k)
        if ra is None:
            entries.append({"type": "CREATE", "path": rb["path"]})
            continue
        if (ra["parent"], ra["name"]) != (rb["parent"], rb["name"]):
            entries.append({"type": "RENAME", "path": ra["path"],
                            "target": rb["path"]})
        changed = ra["sig"] != rb["sig"] or (
            rb["kind"] == "d" and ra.get("children") != rb.get("children"))
        if changed:
            entries.append({"type": "MODIFY", "path": rb["path"]})
    for k, ra in ia.items():
        if k not in ib:
            entries.append({"type": "DELETE", "path": ra["path"]})
    entries.sort(key=lambda e: (e["path"], e["type"]))
    return entries


@dataclass
class GroupInfo:
    """EC block group: k+m internal blocks striped over distinct DNs
    (the BlockInfoStriped / block-group analog)."""
    group_id: int              # == bids[0]
    bids: list[int]
    logical_len: int = -1      # group's logical bytes; -1 until complete()


@dataclass
class DatanodeInfo:
    dn_id: str
    addr: tuple[str, int]  # data-transfer endpoint
    last_heartbeat: float = 0.0
    blocks: set[int] = field(default_factory=set)
    commands: list[dict] = field(default_factory=list)  # queued for next heartbeat
    stats: dict = field(default_factory=dict)
    sc_path: str | None = None  # short-circuit unix socket (co-located reads)
    rack: str = "/default-rack"
    storage_type: str = "DISK"  # primary StorageType (first volume's)
    # every type this DN has a volume of (multi-volume DNs report a list;
    # the reference models this as one DatanodeStorageInfo per storage)
    storage_types: tuple = ("DISK",)
    cached: set[int] = field(default_factory=set)  # pinned block ids


class LeaseManager:
    """File-write leases (LeaseManager analog): one writer per file, renewed
    by client heartbeat, expired leases recovered by the monitor."""

    def __init__(self, expiry_s: float = 60.0):
        self.expiry_s = expiry_s
        self._leases: dict[str, tuple[str, float]] = {}  # path -> (client, deadline)

    def clear(self) -> None:
        """Demotion hygiene: a standby holds no leases (the active owns
        lease management; stale entries would block creates after a later
        promotion)."""
        self._leases.clear()

    def check_available(self, path: str, client: str) -> None:
        """Raise iff another client holds a live lease (non-mutating — safe
        to call before the op is durably logged)."""
        holder = self._leases.get(path)
        if holder and holder[0] != client and holder[1] > time.monotonic():
            raise PermissionError(f"{path} leased by {holder[0]}")

    def acquire(self, path: str, client: str) -> None:
        self.check_available(path, client)
        self._leases[path] = (client, time.monotonic() + self.expiry_s)

    def check(self, path: str, client: str) -> None:
        holder = self._leases.get(path)
        if holder is None or holder[0] != client:
            raise PermissionError(f"{client} does not hold the lease on {path}")

    def release(self, path: str, client: str) -> None:
        self.check(path, client)
        del self._leases[path]

    def renew_all(self, client: str) -> None:
        now = time.monotonic()
        for path, (holder, _) in list(self._leases.items()):
            if holder == client:
                self._leases[path] = (client, now + self.expiry_s)

    def expired(self) -> list[str]:
        now = time.monotonic()
        return [p for p, (_, dl) in self._leases.items() if dl <= now]

    def force_expire(self, path: str) -> None:
        """Mark ``path``'s lease expired NOW (recoverLease): the recovery
        monitor keeps retrying finalization each tick until the file closes,
        while an expired lease no longer blocks other writers.  The holder
        becomes the recovery placeholder UNCONDITIONALLY — keeping the
        original writer's name would let a still-alive writer's renew_all
        resurrect the lease and silently cancel the forced recovery."""
        self._leases[path] = ("<recovery>", 0.0)

    def drop(self, path: str) -> None:
        self._leases.pop(path, None)

    def drop_subtree(self, prefix: str) -> None:
        """Release leases on ``prefix`` and everything under it (directory
        delete must not leave stale leases blocking re-creation)."""
        p = prefix.rstrip("/")
        for path in list(self._leases):
            if path == p or path.startswith(p + "/"):
                del self._leases[path]


class StandbyError(Exception):
    """Mutating RPC hit a standby NameNode (StandbyException analog) — the
    HA client proxy fails over to the next NN on this."""


class ObserverStaleError(Exception):
    """Observer read refused because the tailer hasn't caught up — either
    to the caller's piggybacked state-id within ``observer_wait_s``, or at
    all within the hard ``observer_max_lag_s`` staleness bound
    (ObserverRetryOnActiveException analog).  The HA client proxy bounces
    the read to the active on this; it is counted, never silently stale."""


# The read-only RPC set an observer serves (ClientProtocol methods marked
# @ReadOnly in the reference; GlobalStateIdContext.isCoordinatedCall
# analog).  Everything else — mutations, admin transitions — is refused
# with StandbyError unless it is DN-protocol/HA plumbing (_AUTH_EXEMPT):
# observers consume DN registrations/heartbeats/block reports so their
# block map stays warm enough to answer get_block_locations.
_OBSERVER_READS = frozenset({
    "get_block_locations", "stat", "listing", "ec_status",
    "content_summary", "get_xattrs", "get_acl", "get_storage_policy",
    "list_snapshots", "snapshot_diff", "list_cache_pools",
    "list_cache_directives", "list_encryption_zones", "get_ez",
    "datanode_report", "cluster_status", "decommission_status",
    "slow_nodes_report", "slow_peers", "policy_violations",
    "datanode_blocks", "get_events", "fsck", "metrics", "contention",
    "flight_timeseries", "flight_query", "trace_spans",
    "check_delegation_token", "msync", "ha_state",
})


class NameNode:
    def __init__(self, config: NameNodeConfig | None = None):
        self.config = config or NameNodeConfig()
        self.role = self.config.role  # "active" | "standby" | "observer"
        # Observer staleness bookkeeping: monotonic time of the last
        # successful tail pass (lag_s = now - this on non-active roles)
        # and the highest client state-id ever presented — the demand-side
        # txid horizon that observer_lag_txids is measured against (an
        # observer can't cheaply see the journal end, but it knows what
        # clients have proven to exist).
        self._tail_ok_t = time.monotonic()
        self._max_seen_sid = 0
        # The FSNamesystem lock analog — instrumented (utils/lockprof.py):
        # per-RPC-method wait/hold books, saturation, long-hold stacks.
        self._lock = lockprof.InstrumentedRLock(
            "nn_lock", registry=_M,
            long_hold_s=self.config.lock_long_hold_s)
        # The superuser is the NN process owner (dfs.permissions.superusergroup
        # / UGI of the NN, FSPermissionChecker semantics); in-process callers
        # (no wire identity) also act as superuser.
        import getpass

        self._superuser = getpass.getuser()
        # namespace: nested DirNode tree; leaves are FileNode
        self._root: DirNode = DirNode(
            attrs=Attrs(self._superuser, "supergroup", 0o755))
        # inode ids: deterministic across replay (assignment order follows
        # the edit log), persisted in the fsimage; root is always 0
        self._next_inode = 1
        self._blocks: dict[int, BlockInfo] = {}
        self._groups: dict[int, GroupInfo] = {}  # EC group_id -> group
        self._datanodes: dict[str, DatanodeInfo] = {}
        self._leases = LeaseManager()
        self._pending_repl: dict[int, float] = {}  # block_id -> retry deadline
        self._under_replicated = 0  # cached by _check_replication
        # balancer moves in flight: block -> {"from", "to", "deadline"}
        self._pending_moves: dict[int, dict] = {}
        self._pending_ibr: dict[int, list] = {}    # standby: IBRs ahead of tail
        self._alloc_charge: dict[int, tuple[str, int]] = {}  # bid -> (path, bytes)
        self._events: list[dict] = []   # inotify ring (active only)
        self._events_cap = 10_000
        self._decommissioning: set[str] = set()
        self._safemode_forced = False
        # auto safemode on startup when a non-empty namespace was loaded:
        # hold mutations until enough replicas have reported in
        self._safemode_auto = False
        self._events_trimmed = 0        # events up to this seq were dropped
        self._pending_space: dict[str, int] = {}   # quota root -> charged bytes
        self._pending_recovery: dict[int, float] = {}  # bid -> retry deadline
        self._recovery_grace: dict[int, float] = {}    # bid -> IBR-wait deadline
        # EC cold tier: blocks demoted to (k+m)/k stripes (editlog-durable
        # "ec_demote" records; demoted blocks want ONE full replica, the
        # stripe owner).  Stripe groups are SOFT state — the WAL-durable
        # copy lives in each owner DN's chunk index; this cache is rebuilt
        # from stripe_complete RPCs + heartbeat manifest reports.
        self._ec_demoted: set[int] = set()
        self._stripe_groups: dict[tuple[str, int], dict] = {}
        self._pending_demote: dict[int, float] = {}       # bid -> deadline
        self._pending_stripe_repair: dict[tuple[str, int], float] = {}
        # scrub-confirmed corrupt stripes on LIVE holders (rpc_bad_stripe):
        # (owner, cid) -> stripe indices needing re-decode.  The stripe-
        # repair monitor unions these into its dead-holder `missing` set so
        # one scheduler handles both loss modes; cleared when the owner's
        # stripe_complete report lands (repair done) or the group vanishes.
        self._corrupt_stripes: dict[tuple[str, int], set[int]] = {}
        # last invariant-census result (_check_fsck monitor pass) — what
        # rpc_cluster_status and the gateway /health verdict read without
        # re-walking the block map per page load
        self._last_fsck: dict | None = None
        # Stripe manifests journaled at demote/repair time (editlog +
        # fsimage durable, unlike the soft _stripe_groups cache) so
        # owner-loss repair can rebuild a container's stripes after the
        # owner DN — and its WAL-durable chunk index — is gone for good.
        self._stripe_manifests: dict[tuple[str, int], dict] = {}
        # Coded mirror plane: blocks where some DN holds only a k-of-n
        # SEGMENT of the reduced payload (server/mirror_plane.py), not a
        # full replica.  bid -> dn_id -> first-seen monotonic time.  These
        # never count toward info.locations; the reconciliation monitor
        # upgrades them to full replicas in the background.
        self._partial_replicas: dict[int, dict[str, float]] = {}
        self._pending_partial: dict[int, float] = {}      # bid -> retry deadline
        # Snapshots: frozen subtree images per snapshottable dir
        # (namenode/snapshot analog; blocks are immutable once complete, so a
        # structural freeze IS a consistent point-in-time view).
        self._snapshottable: set[str] = set()
        self._snapshots: dict[str, dict[str, dict]] = {}  # dir -> name -> tree
        self._quotas: dict[str, tuple[int, int]] = {}  # dir -> (ns, space)
        # Encryption zones (EncryptionZoneManager.java:71 analog): zone
        # root -> key name; zone keys live WITH the metadata (the owned
        # KeyProvider replacing the reference's external KMS — key custody
        # equals metadata custody here, documented trade).
        self._ezones: dict[str, str] = {}
        self._ezkeys: dict[str, bytes] = {}
        # Centralized cache management (CacheManager.java:103 analog):
        # pools bound directives; directives pin paths' blocks in DN RAM.
        self._cache_pools: dict[str, dict] = {}   # name -> {owner, limit}
        self._cache_dirs: dict[int, dict] = {}    # id -> {path, pool}
        self._next_cache_id = 1
        self._pending_cache: dict[tuple[int, str], float] = {}
        # Cached usage per quota root: [entries, bytes]; None = recompute on
        # next check (the reference maintains counts on the quota INode for
        # the same reason: O(subtree) walks per create don't scale).
        self._qusage: dict[str, list | None] = {}
        # block ids live in this NN's block-pool range (federation):
        # (pool_index << 48) | seq — disjoint across nameservices
        self._pool_base = self.config.block_pool_index << 48
        self._next_block_id = self._pool_base + 1
        self._gen_stamp = 1
        from hdrf_tpu.security import (BlockTokenSecretManager,
                                       DelegationTokenManager)
        self._tokens = (BlockTokenSecretManager()
                        if self.config.block_tokens else None)
        self._dtokens = DelegationTokenManager()
        # layout check/upgrade before the edit log opens the meta dir
        # (Storage.analyzeStorage; a future-layout dir refuses to load)
        from hdrf_tpu.storage import version as storage_version

        storage_version.ensure_layout(self.config.meta_dir, "namenode",
                                      storage_version.NN_UPGRADERS)
        self._editlog = EditLog(self.config.meta_dir,
                                self.config.editlog_checkpoint_every,
                                journal_addrs=self.config.journal_addrs)
        self._load()
        self._load_decommissioning()
        self._safemode_auto = bool(self._blocks) and self.role == "active"
        # Group commit (FSEditLog.logSync design): rpc_* handlers buffer
        # edits under the namesystem lock and sync AFTER releasing it, so
        # one fsync / quorum round covers every concurrent handler's
        # records.  Wrapping the bound methods (instance attrs shadow the
        # class) covers the RPC server and direct in-process callers alike.
        self._sync_ctx = threading.local()
        for _name in dir(type(self)):
            if _name.startswith("rpc_"):
                setattr(self, _name, self._sync_wrap(getattr(self, _name)))
        # Stall watchdog over in-flight RPC handlers (the VM's write-burst
        # throttling can wedge any fsync-bearing handler ~35 s — PERF_NOTES
        # round 4); optional per-daemon status HTTP endpoint (HttpServer2).
        self.watchdog = StallWatchdog("namenode",
                                      budget_s=self.config.stall_budget_s,
                                      registry=_M, lock=self._lock)
        self._rpc = RpcServer(self.config.host, self.config.port, self,
                              "namenode", watchdog=self.watchdog,
                              max_handlers=self.config.rpc_max_handlers)
        # Cluster-level flight recorder (utils/flight_recorder.py): exists
        # even without a status port — the gateway pulls its ring over the
        # flight_timeseries RPC.  Optionally archive-backed so the curve
        # survives NN restarts (utils/flight_archive.py).
        self.flight_archive = None
        if self.config.flight_archive_dir:
            arch_dir = self.config.flight_archive_dir
            if not os.path.isabs(arch_dir):
                arch_dir = os.path.join(self.config.meta_dir, arch_dir)
            self.flight_archive = flight_archive.FlightArchive(
                arch_dir,
                max_bytes=self.config.flight_archive_max_mb << 20)
        self.flight = flight_recorder.FlightRecorder(
            "namenode", self._flight_sample,
            interval_s=self.config.flight_interval_s,
            capacity=self.config.flight_capacity,
            archive=self.flight_archive)
        self._status = None
        if self.config.status_port is not None:
            from hdrf_tpu.server.status_http import StatusHttpServer

            self._status = StatusHttpServer("namenode",
                                            host=self.config.host,
                                            port=self.config.status_port,
                                            watchdog=self.watchdog,
                                            recorder=self.flight,
                                            contention=self.rpc_contention)
        self._monitor_stop = threading.Event()
        self._monitor: threading.Thread | None = None
        self._logger = log.get_logger("namenode")

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "NameNode":
        self._rpc.start()
        self.watchdog.start()
        if self.config.flight_interval_s > 0:
            self.flight.start()
        if self._status is not None:
            self._status.start()
        target = (self._monitor_loop if self.role == "active"
                  else self._tailer_loop)
        self._monitor = threading.Thread(target=target, name="nn-monitor",
                                         daemon=True)
        self._monitor.start()
        self._logger.info("namenode started", role=self.role,
                       addr=f"{self.addr[0]}:{self.addr[1]}",
                       blocks=len(self._blocks))
        return self

    def stop(self) -> None:
        self._monitor_stop.set()
        self.flight.stop()
        if self.flight_archive is not None:
            self.flight_archive.close()
        self.watchdog.stop()
        if self._status is not None:
            self._status.stop()
        if self._monitor:
            self._monitor.join(timeout=5)
        self._rpc.stop()
        self._editlog.close()

    @property
    def addr(self) -> tuple[str, int]:
        return self._rpc.addr

    # ---------------------------------------------------------- persistence

    def _load(self) -> None:
        snap = self._editlog.load_image()
        if snap is not None:
            self._restore(snap)
        if self.role != "active":
            from hdrf_tpu.server.editlog import JournalGapError

            # standby/observer: tail-only — never truncate or append to the
            # active's journal, and never apply past the committed floor
            try:
                self._editlog.replay(self._apply_tolerant, readonly=True)
            except JournalGapError:
                # quorum purged past our (possibly absent) image: the tailer
                # loop bootstraps a newer image from the active peer
                pass
        else:
            # Claim BEFORE replaying: the claim fences older writers and (in
            # quorum mode) runs segment recovery, so the replay reads a
            # consistent, committed log.  Replaying first could apply a
            # minority-only record that recovery then drops.
            self._editlog.claim_epoch()
            self._editlog.replay(self._apply_tolerant)
            self._editlog.open_for_append(self._snapshot)

    def _reload_image(self, snap: dict) -> None:
        """Standby-side fsimage reload (the active checkpointed): _restore
        rebuilds BlockInfos with empty location sets, so re-seed them from
        the DN-report-built map — the warm block map is the whole point of
        a hot standby."""
        old_locs = {bid: info.locations for bid, info in self._blocks.items()}
        self._restore(snap)
        for bid, locs in old_locs.items():
            info = self._blocks.get(bid)
            if info is not None:
                info.locations |= locs

    def _apply_tolerant(self, rec: list) -> None:
        """Replay-path apply: a record that no longer applies (e.g. the WAL
        tail diverged because an append failed mid-crash) is skipped with a
        count rather than crash-looping the NameNode on startup."""
        try:
            self._apply(rec)
        except Exception:  # noqa: BLE001 — startup must make progress
            _M.incr("replay_records_skipped")

    def _alloc_inode(self) -> int:
        i = self._next_inode
        self._next_inode += 1
        return i

    def _snapshot(self) -> dict:
        def walk(node: dict) -> dict:
            out = {}
            for name, child in node.items():
                if isinstance(child, FileNode):
                    out[name] = ["f", child.replication, child.scheme,
                                 child.blocks, child.complete, child.mtime,
                                 child.ec, child.attrs.pack(),
                                 child.inode_id]
                elif isinstance(child, SymNode):
                    out[name] = ["l", child.target, child.attrs.pack(),
                                 child.inode_id]
                else:
                    out[name] = ["d", walk(child),
                                 child.attrs.pack()
                                 if isinstance(child, DirNode) else None,
                                 getattr(child, "inode_id", 0)]
            return out

        return {
            "tree": walk(self._root),
            "next_inode": self._next_inode,
            "root_attrs": self._root.attrs.pack(),
            "blocks": {b.block_id: [b.gen_stamp, b.length, b.path]
                       for b in self._blocks.values()},
            "groups": {g.group_id: [g.bids, g.logical_len]
                       for g in self._groups.values()},
            "next_block_id": self._next_block_id,
            "gen_stamp": self._gen_stamp,
            "snapshottable": sorted(self._snapshottable),
            "snapshots": self._snapshots,
            "quotas": {p: list(q) for p, q in self._quotas.items()},
            "ezones": dict(self._ezones),
            "ezkeys": {k: bytes(v) for k, v in self._ezkeys.items()},
            "cache_pools": self._cache_pools,
            "cache_dirs": {i: [d["path"], d["pool"]]
                           for i, d in self._cache_dirs.items()},
            "next_cache_id": self._next_cache_id,
            "dtokens": self._dtokens.snapshot(),
            "ec_demoted": sorted(self._ec_demoted),
            "stripe_manifests": [[owner, cid, man] for (owner, cid), man
                                 in sorted(self._stripe_manifests.items())],
        }

    def _restore(self, snap: dict) -> None:
        def walk(m: dict) -> DirNode:
            out = DirNode()
            for name, v in m.items():
                if v[0] == "f":
                    out[name] = FileNode(
                        v[1], v[2], list(v[3]), v[4], v[5],
                        v[6] if len(v) > 6 else None,
                        Attrs.unpack(v[7] if len(v) > 7 else None,
                                     mode=0o644),
                        inode_id=v[8] if len(v) > 8 else 0)
                elif v[0] == "l":
                    out[name] = SymNode(v[1], Attrs.unpack(v[2]),
                                        inode_id=v[3] if len(v) > 3 else 0)
                else:
                    d = walk(v[1])
                    d.attrs = Attrs.unpack(v[2] if len(v) > 2 else None)
                    d.inode_id = v[3] if len(v) > 3 else 0
                    out[name] = d
            return out

        self._root = walk(snap["tree"])
        self._next_inode = snap.get("next_inode", 1)
        self._root.attrs = Attrs.unpack(
            snap.get("root_attrs"), owner=self._superuser)
        self._blocks = {bid: BlockInfo(bid, gs, ln, path)
                        for bid, (gs, ln, path) in snap["blocks"].items()}
        self._groups = {gid: GroupInfo(gid, list(bids), ln)
                        for gid, (bids, ln) in snap.get("groups", {}).items()}
        self._snapshottable = set(snap.get("snapshottable", []))
        self._snapshots = snap.get("snapshots", {})
        self._quotas = {p: tuple(q)
                        for p, q in snap.get("quotas", {}).items()}
        self._next_block_id = snap["next_block_id"]
        self._gen_stamp = snap["gen_stamp"]
        self._ezones = dict(snap.get("ezones", {}))
        self._ezkeys = {k: bytes(v)
                        for k, v in snap.get("ezkeys", {}).items()}
        self._cache_pools = {k: dict(v) for k, v in
                             snap.get("cache_pools", {}).items()}
        self._cache_dirs = {i: {"path": v[0], "pool": v[1]}
                            for i, v in snap.get("cache_dirs", {}).items()}
        self._next_cache_id = snap.get("next_cache_id", 1)
        if "dtokens" in snap:
            self._dtokens.restore(snap["dtokens"])
        self._ec_demoted = set(snap.get("ec_demoted", []))
        self._stripe_manifests = {(owner, int(cid)): man for owner, cid, man
                                  in snap.get("stripe_manifests", [])}

    def _apply(self, rec: list) -> None:
        """Apply one edit record (replay path and live path share this)."""
        op = rec[0]
        if op == "mkdir":
            self._mkdir_apply(rec[1], user=rec[2] if len(rec) > 2 else None,
                              mode=rec[3] if len(rec) > 3 else None)
        elif op == "create":
            _, path, replication, scheme, mtime, *rest = rec
            user = rest[1] if len(rest) > 1 else None
            mode = rest[2] if len(rest) > 2 else None
            parent, name = self._parent_of(path, create=True, user=user)
            attrs = perm.inherit_attrs(
                self._dir_attrs(parent), user or self._superuser, None,
                is_dir=False, umode=mode)
            node = FileNode(replication, scheme, mtime=mtime,
                            ec=rest[0] if rest else None, attrs=attrs)
            node.inode_id = self._alloc_inode()
            parent[name] = node
        elif op == "add_block_group":
            _, path, bids, gs = rec
            node = self._file(path)
            node.blocks.append(bids[0])
            self._groups[bids[0]] = GroupInfo(bids[0], list(bids))
            for bid in bids:
                self._blocks[bid] = BlockInfo(bid, gs, -1, path)
            self._next_block_id = max(self._next_block_id, max(bids) + 1)
            self._gen_stamp = max(self._gen_stamp, gs + 1)
        elif op == "add_block":
            _, path, bid, gs = rec
            node = self._file(path)
            node.blocks.append(bid)
            self._blocks[bid] = BlockInfo(bid, gs, -1, path)
            self._next_block_id = max(self._next_block_id, bid + 1)
            self._gen_stamp = max(self._gen_stamp, gs + 1)
        elif op == "abandon_block":
            _, path, bid = rec
            node = self._file(path)
            if bid in node.blocks:
                node.blocks.remove(bid)
            self._blocks.pop(bid, None)
            self._uncharge_alloc(bid)
        elif op == "append":
            node = self._file(rec[1])
            node.complete = False
            node.mtime = rec[2]
        elif op == "bump_block":
            _, path, bid, gs = rec
            info = self._blocks[bid]
            info.gen_stamp = gs
            info.length = -1        # being rewritten; synced at complete
            # the new-generation pipeline repopulates locations via IBRs;
            # leaving the old-generation holders here would hand readers
            # stale bytes right after an append (they stay in `reported`
            # until the post-supersede block report invalidates them)
            info.locations.clear()
            self._gen_stamp = max(self._gen_stamp, gs + 1)
        elif op == "truncate":
            _, path, new_len, mtime = rec
            node = self._file(path)
            node.mtime = mtime
            pos = 0
            keep: list[int] = []
            for bid in node.blocks:
                info = self._blocks[bid]
                ln = max(info.length, 0)
                if pos >= new_len:
                    # dropping the BlockInfo orphans the replicas; the next
                    # block report invalidates them (deleted-file path)
                    self._blocks.pop(bid, None)
                    self._uncharge_alloc(bid)
                    continue
                if pos + ln > new_len:
                    info.length = new_len - pos
                keep.append(bid)
                pos += ln
            node.blocks = keep
        elif op == "provide":
            _, path, uri, length, bids, mtime = rec
            parent, name = self._parent_of(path, create=True)
            bs = self.config.block_size
            node = FileNode(1, "direct", list(bids), True, mtime,
                            inode_id=self._alloc_inode())
            parent[name] = node
            p = "/" + "/".join(self._parts(path))
            for i, bid in enumerate(bids):
                self._blocks[bid] = BlockInfo(
                    bid, 0, min(bs, length - i * bs), p)
            if bids:
                self._next_block_id = max(self._next_block_id,
                                          max(bids) + 1)
        elif op == "fsync":
            # hflush/hsync visible-length persist (FSNamesystem.fsync):
            # only ever grows — a lagging retry must not shrink it
            _, _path, bid, ln = rec
            finfo = self._blocks.get(bid)
            if finfo is not None and ln > finfo.length:
                finfo.length = ln
        elif op == "complete":
            _, path, lengths, mtime = rec
            node = self._file(path)
            node.complete = True
            node.mtime = mtime
            for bid, ln in lengths.items():
                self._uncharge_alloc(bid)
                if bid in self._groups:
                    self._groups[bid].logical_len = ln
                elif bid in self._blocks:
                    self._blocks[bid].length = ln
        elif op == "delete":
            self._delete_apply(rec[1])
        elif op == "rename":
            self._rename_apply(rec[1], rec[2])
        elif op == "allow_snapshot":
            path = "/" + "/".join(self._parts(rec[1]))
            self._snapshottable.add(path)
            self._snapshots.setdefault(path, {})
        elif op == "create_snapshot":
            _, path, name = rec
            path = "/" + "/".join(self._parts(path))
            node = self._resolve(path)
            self._snapshots.setdefault(path, {})[name] = self._freeze(node)
        elif op == "delete_snapshot":
            self._delete_snapshot_apply(rec[1], rec[2])
        elif op == "dt_key":
            self._dtokens.apply_key(rec[1], rec[2],
                                    rec[3] if len(rec) > 3 else 0.0)
        elif op == "dt_issue":
            self._dtokens.apply_issue(rec[1], rec[2])
        elif op == "dt_renew":
            self._dtokens.apply_renew(rec[1], rec[2])
        elif op == "dt_cancel":
            self._dtokens.apply_cancel(rec[1])
        elif op == "setpolicy":
            self._node_attrs(self._resolve(rec[1])).policy = rec[2] or None
        elif op == "setrepl":
            node = self._file(rec[1])
            node.replication = rec[2]
        elif op == "settimes":
            node = self._file(rec[1])
            if rec[2] >= 0:
                node.mtime = rec[2]
        elif op == "concat":
            _, dst, srcs, *_rest = rec
            dnode = self._file(dst)
            for sp in srcs:
                snode = self._file(sp)
                dnode.blocks.extend(snode.blocks)
                dpath = "/" + "/".join(self._parts(dst))
                for bid in snode.blocks:
                    if bid in self._blocks:
                        self._blocks[bid].path = dpath
                    grp = self._groups.get(bid)
                    if grp is not None:
                        for b in grp.bids:
                            if b in self._blocks:
                                self._blocks[b].path = dpath
                snode.blocks = []
                parent, name = self._parent_of(sp)
                parent.pop(name, None)
                self._leases.drop(sp)
            dnode.mtime = rec[3] if len(rec) > 3 else 0.0
        elif op == "symlink":
            _, link, target, *rest = rec
            parent, name = self._parent_of(link, create=True,
                                           user=rest[0] if rest else None)
            parent[name] = SymNode(target, perm.inherit_attrs(
                self._dir_attrs(parent), rest[0] if rest
                else self._superuser, None, is_dir=False, umode=0o777),
                inode_id=self._alloc_inode())
        elif op == "ezkey":
            self._ezkeys[rec[1]] = bytes(rec[2])
        elif op == "ez":
            self._ezones["/" + "/".join(self._parts(rec[1]))] = rec[2]
        elif op == "cachepool":
            self._cache_pools[rec[1]] = {"owner": rec[2], "limit": rec[3]}
        elif op == "rmcachepool":
            self._cache_pools.pop(rec[1], None)
            self._cache_dirs = {i: d for i, d in self._cache_dirs.items()
                                if d["pool"] != rec[1]}
        elif op == "cachedir":
            self._cache_dirs[rec[1]] = {"path": rec[2], "pool": rec[3]}
            self._next_cache_id = max(self._next_cache_id, rec[1] + 1)
        elif op == "rmcachedir":
            self._cache_dirs.pop(rec[1], None)
        elif op == "setperm":
            self._node_attrs(self._resolve(rec[1])).mode = rec[2]
        elif op == "setowner":
            a = self._node_attrs(self._resolve(rec[1]))
            if rec[2]:
                a.owner = rec[2]
            if rec[3]:
                a.group = rec[3]
        elif op == "setacl":
            a = self._node_attrs(self._resolve(rec[1]))
            a.acl = [list(e) for e in rec[2]]
            a.dacl = [list(e) for e in rec[3]]
        elif op == "setxattr":
            self._node_attrs(self._resolve(rec[1])).xattrs[rec[2]] = \
                bytes(rec[3])
        elif op == "rmxattr":
            self._node_attrs(self._resolve(rec[1])).xattrs.pop(rec[2], None)
        elif op == "set_quota":
            _, path, ns_q, sp_q = rec
            path = "/" + "/".join(self._parts(path))
            if ns_q < 0 and sp_q < 0:  # clrQuota form
                self._quotas.pop(path, None)
                self._qusage.pop(path, None)
            else:
                # -1 on one axis keeps the existing limit: -setQuota and
                # -setSpaceQuota must compose, as the HDFS commands do
                old = self._quotas.get(path, (-1, -1))
                self._quotas[path] = (ns_q if ns_q >= 0 else old[0],
                                      sp_q if sp_q >= 0 else old[1])
                self._qusage[path] = None  # seed lazily
        elif op == "ec_demote":
            # [op, block_id, owner_dn, manifests?] — block's containers
            # demoted to the EC stripe tier; from here the block wants ONE
            # full replica (the stripe owner) and redundancy lives in the
            # (k+m)/k stripes.  Grown records carry the stripe manifests
            # (cid -> {k, m, holders, crcs, ...}) so owner-loss repair can
            # rebuild stripes after the owner DN's WAL-durable index is
            # gone; block_id is None when a repair re-journals manifests
            # for an already-demoted block.  Two-field seed records (no
            # owner/manifests) still replay.
            if rec[1] is not None:
                self._ec_demoted.add(rec[1])
            if len(rec) >= 4:
                for cid_s, man in (rec[3] or {}).items():
                    self._stripe_manifests[(rec[2], int(cid_s))] = man

    def _account(self, rec: list) -> None:
        """Keep cached quota usage in sync with an applied edit.  Cheap ops
        adjust incrementally; structural ops (delete/rename/snapshots) mark
        affected roots dirty for lazy recount."""
        if not self._quotas:
            return
        op = rec[0]
        if op == "mkdir":
            for r, _ in self._quota_roots_of(rec[1]):
                self._qusage[r] = None  # created-count unknown: recount lazily
        elif op == "create":
            for r, _ in self._quota_roots_of(rec[1]):
                u = self._qusage.get(r)
                if u is not None:
                    u[0] += 1
                else:
                    self._qusage[r] = None
        elif op == "complete":
            # delta vs lengths already known (an IBR may have set a block's
            # length before complete — don't double count)
            add = rec[-1]  # precomputed by _log before apply
            for r, _ in self._quota_roots_of(rec[1]):
                u = self._qusage.get(r)
                if u is not None:
                    u[1] += add
        elif op == "symlink":
            for r, _ in self._quota_roots_of(rec[1]):
                u = self._qusage.get(r)
                if u is not None:
                    u[0] += 1
                else:
                    self._qusage[r] = None
        elif op == "concat":
            for path in [rec[1], *rec[2]]:
                for r, _ in self._quota_roots_of(path):
                    self._qusage[r] = None
        elif op in ("delete", "rename", "delete_snapshot", "truncate",
                    "fsync"):
            # truncate included: it SHRINKS usage (dropped whole blocks +
            # the cut boundary block), which the incremental paths never
            # subtract — a stale high value would falsely reject writes.
            # fsync included: it sets a UC block length early, which would
            # skew complete's incremental delta — recount lazily instead
            for path in (rec[1], rec[2] if op == "rename" else rec[1]):
                if isinstance(path, str):
                    for r, _ in self._quota_roots_of(path):
                        self._qusage[r] = None

    def _sync_wrap(self, fn):
        """Bound-method wrapper giving every entry point group-commit
        semantics: edits buffered by ``_log`` during the call are synced
        (durably journaled) after the namesystem lock is released, before
        the caller sees the result — the reference's handler shape
        (mutate under lock, ``logSync`` outside it, FSEditLog.java:124).
        Depth-tracked so nested rpc_* calls sync once, at the top."""
        import functools

        @functools.wraps(fn)
        def wrapped(*a, **kw):
            ctx = self._sync_ctx
            ctx.depth = getattr(ctx, "depth", 0) + 1
            try:
                out = fn(*a, **kw)
                if ctx.depth == 1:
                    self._sync_pending()
                return out
            except BaseException:
                if ctx.depth == 1:
                    try:
                        self._sync_pending()
                    except Exception:  # noqa: BLE001 — original error wins
                        pass
                raise
            finally:
                ctx.depth -= 1
        return wrapped

    def _sync_pending(self) -> None:
        """Make this thread's buffered edits durable; on a fencing or
        quorum-loss failure the NN stops acking and demotes."""
        from hdrf_tpu.server.editlog import FencedError, QuorumLostError

        seq = getattr(self._sync_ctx, "pending", None)
        if seq is None:
            return
        self._sync_ctx.pending = None
        try:
            self._editlog.sync(seq)
        except FencedError:
            self._demote()
            raise StandbyError("namenode fenced: now standby") from None
        except QuorumLostError:
            self._demote()
            raise StandbyError(
                "journal quorum lost: namenode demoted") from None

    def _log(self, rec: list) -> None:
        """Validate, then append, then apply.  Validation (non-mutating)
        rejects bad ops — mkdir over a file, rename onto an existing dst —
        before anything reaches the WAL, so a rejected op cannot poison
        replay; appending before applying keeps the log-before-apply
        durability discipline (editlog.py): if the append raises, memory is
        untouched and the client sees the error; if apply then raises, WAL
        and memory agree again after a restart replays the record.

        The append is BUFFERED (group commit): inside an rpc_* call the
        sync happens after the namesystem lock is released (_sync_wrap);
        background callers (lease monitor, scanners) sync inline."""
        from hdrf_tpu.server.editlog import FencedError

        if self.role != "active":
            raise StandbyError("namenode is standby")
        self._check_safemode()
        self._validate(rec)
        try:
            seq = self._editlog.append_async(rec)
        except FencedError:
            # another NN claimed the journal: demote (old-active fencing)
            self._demote()
            raise StandbyError("namenode fenced: now standby") from None
        self._sync_ctx.pending = seq
        if getattr(self._sync_ctx, "depth", 0) == 0:
            self._sync_pending()  # background caller: durable before return
        if rec[0] == "complete" and self._quotas:
            delta = 0
            for bid, ln in rec[2].items():
                if bid in self._groups:
                    prev = self._groups[bid].logical_len
                elif bid in self._blocks:
                    prev = self._blocks[bid].length
                else:
                    continue
                delta += ln - max(prev, 0)
            self._apply(rec)
            self._account(rec + [delta])
            self._emit_event(rec)
        else:
            self._apply(rec)
            self._account(rec)
            self._emit_event(rec)

    def _demote(self) -> None:
        """Fenced/quorum-lost active -> standby.  With group commit the
        in-memory namespace may contain applied-but-never-durable edits
        (the sync that failed), so the namespace is RELOADED from the
        durable image + journal — a demoted NN must converge to what the
        new active replays, not to its own unacked leftovers."""
        with self._lock:
            if self.role == "standby":
                return
            self.role = "standby"
            self._editlog.close()
            self._editlog = EditLog(self.config.meta_dir,
                                    self.config.editlog_checkpoint_every,
                                    journal_addrs=self.config.journal_addrs)
            old_locs = {bid: info.locations
                        for bid, info in self._blocks.items()}
            self._restore({"tree": {}, "blocks": {}, "groups": {},
                           "next_block_id": 1, "gen_stamp": 1})
            self._leases.clear()
            snap = self._editlog.load_image()
            if snap is not None:
                self._restore(snap)
            try:
                self._editlog.replay(self._apply_tolerant, readonly=True)
            except Exception:  # noqa: BLE001 — tailer keeps retrying
                _M.incr("tail_errors")
            # re-seed block locations from the DN-report-built map (the
            # whole point of a warm standby)
            for bid, locs in old_locs.items():
                info = self._blocks.get(bid)
                if info is not None:
                    info.locations |= locs
        tailer = threading.Thread(target=self._tailer_loop,
                                  name="nn-tailer", daemon=True)
        tailer.start()  # the running monitor loop exits on its role check
        _M.incr("demotions")

    @staticmethod
    def _link_redirect(target: str, at: list[str], rest: list[str]):
        """Raise SymlinkRedirect for a link hit at path prefix ``at`` with
        remaining components ``rest``.  Relative targets resolve against
        the LINK'S PARENT directory (POSIX), not the root.  The message
        carries "original\nresolved" so a client retrying a MULTI-path op
        (rename src/dst, concat srcs) can tell which argument redirected."""
        tgt = target.rstrip("/")
        if not tgt.startswith("/"):
            tgt = "/" + "/".join(at[:-1] + [tgt]) if len(at) > 1 \
                else "/" + tgt
        orig = "/" + "/".join(at + rest)
        raise SymlinkRedirect(
            orig + "\n"
            + tgt + ("/" + "/".join(rest) if rest else ""))

    def _peek_parent(self, path: str) -> tuple[dict | None, str]:
        """Non-mutating walk to ``path``'s parent: raises if a component is a
        file; returns (None, name) when intermediate dirs don't exist yet
        (the apply will create them)."""
        parts = self._parts(path)
        node: Any = self._root
        for i, p in enumerate(parts[:-1]):
            child = node.get(p)
            if child is None:
                return None, parts[-1]
            if isinstance(child, SymNode):
                self._link_redirect(child.target, parts[:i + 1],
                                    parts[i + 1:])
            if isinstance(child, FileNode):
                raise NotADirectoryError(f"{p} in {path} is a file")
            node = child
        return node, parts[-1]

    def _validate(self, rec: list) -> None:
        """Raise iff applying ``rec`` to the current state would raise,
        without mutating anything."""
        op = rec[0]
        if op == "mkdir":
            try:
                parent, name = self._peek_parent(rec[1])
            except NotADirectoryError as e:  # match _mkdir_apply's type
                raise FileExistsError(str(e)) from None
            if parent is not None and isinstance(parent.get(name), FileNode):
                raise FileExistsError(f"{rec[1]}: {name} is a file")
        elif op == "create":
            self._peek_parent(rec[1])
        elif op == "provide":
            parent, name = self._peek_parent(rec[1])
            if parent is not None and name in parent:
                raise FileExistsError(rec[1])
        elif op in ("add_block", "add_block_group", "abandon_block",
                    "complete", "fsync"):
            self._file(rec[1])
        elif op == "delete":
            self._parent_of(rec[1])
            self._resolve(rec[1], follow_leaf=False)
        elif op == "rename":
            self._resolve(rec[1], follow_leaf=False)
            dparent, dname = self._peek_parent(rec[2])
            if dparent is not None and dname in dparent:
                raise FileExistsError(rec[2])
        elif op == "allow_snapshot":
            if not isinstance(self._resolve(rec[1]), dict):
                raise NotADirectoryError(rec[1])
        elif op == "create_snapshot":
            p = "/" + "/".join(self._parts(rec[1]))
            if p not in self._snapshottable:
                raise PermissionError(f"{p} is not snapshottable")
            if rec[2] in self._snapshots.get(p, {}):
                raise FileExistsError(f"snapshot {rec[2]} exists")
        elif op == "delete_snapshot":
            p = "/" + "/".join(self._parts(rec[1]))
            if rec[2] not in self._snapshots.get(p, {}):
                raise FileNotFoundError(f"no snapshot {rec[2]} of {p}")
        elif op == "set_quota":
            if not isinstance(self._resolve(rec[1]), dict):
                raise NotADirectoryError(rec[1])
        elif op in ("setperm", "setowner", "setacl", "setxattr", "rmxattr",
                    "setpolicy"):
            self._resolve(rec[1])
        elif op == "ezkey":
            if rec[1] in self._ezkeys:
                raise FileExistsError(f"encryption key {rec[1]} exists")
        elif op == "ez":
            node = self._resolve(rec[1])
            if not isinstance(node, dict):
                raise NotADirectoryError(rec[1])
            if len(node):
                raise IOError(f"{rec[1]} is not empty (zones are created "
                              "on empty directories, as in the reference)")
            if rec[2] not in self._ezkeys:
                raise KeyError(f"no encryption key {rec[2]}")
            p = "/" + "/".join(self._parts(rec[1]))
            for z in self._ezones:
                if p == z or p.startswith(z + "/") or z.startswith(p + "/"):
                    raise IOError(f"nested encryption zones: {z}")
        elif op == "cachepool":
            if rec[1] in self._cache_pools:
                raise FileExistsError(f"cache pool {rec[1]} exists")
        elif op == "cachedir":
            if rec[3] not in self._cache_pools:
                raise FileNotFoundError(f"no cache pool {rec[3]}")
            self._resolve(rec[2])
        elif op == "rmcachedir":
            if rec[1] not in self._cache_dirs:
                raise FileNotFoundError(f"no cache directive {rec[1]}")
        elif op in ("setrepl", "settimes"):
            self._file(rec[1])
        elif op == "concat":
            dnode = self._file(rec[1])
            if not dnode.complete or dnode.ec:
                raise IOError(f"concat target {rec[1]} must be a complete "
                              "non-EC file")
            seen = {"/" + "/".join(self._parts(rec[1]))}
            for sp in rec[2]:
                p = "/" + "/".join(self._parts(sp))
                if p in seen:
                    raise ValueError(f"duplicate path {sp} in concat")
                seen.add(p)
                snode = self._file(sp)
                if not snode.complete or snode.ec:
                    raise IOError(f"concat source {sp} must be a complete "
                                  "non-EC file")
                if snode.scheme != dnode.scheme:
                    raise IOError("concat across reduction schemes")
        elif op == "symlink":
            parent, name = self._peek_parent(rec[1])
            if parent is not None and name in parent:
                raise FileExistsError(rec[1])

    # ------------------------------------------------------- tree utilities

    @staticmethod
    def _parts(path: str) -> list[str]:
        parts = [p for p in path.split("/") if p]
        if not parts:
            raise ValueError("root path not allowed here")
        return parts

    def _parent_of(self, path: str, create: bool = False,
                   user: str | None = None) -> tuple[dict, str]:
        parts = self._parts(path)
        node = self._root
        for i, p in enumerate(parts[:-1]):
            child = node.get(p)
            if child is None:
                if not create:
                    raise FileNotFoundError(f"parent of {path} does not exist")
                child = node[p] = DirNode(attrs=perm.inherit_attrs(
                    self._dir_attrs(node), user or self._superuser, None,
                    is_dir=True), inode_id=self._alloc_inode())
            if isinstance(child, SymNode):
                self._link_redirect(child.target, parts[:i + 1],
                                    parts[i + 1:])
            if isinstance(child, FileNode):
                raise NotADirectoryError(f"{p} in {path} is a file")
            node = child
        return node, parts[-1]

    @staticmethod
    def _dir_attrs(node: Any) -> Attrs:
        return node.attrs if isinstance(node, DirNode) else Attrs(
            "hdrf", "supergroup", 0o755)

    @staticmethod
    def _node_attrs(node: Any) -> Attrs:
        if isinstance(node, (FileNode, DirNode)):
            return node.attrs
        raise FileNotFoundError("node has no attributes")

    # ---------------------------------------------------------- permissions

    def _check_access(self, path: str, want: int = 0, parent_want: int = 0,
                      owner_only: bool = False,
                      super_only: bool = False) -> None:
        """FSPermissionChecker.java:49 analog: EXECUTE on every ancestor,
        ``parent_want`` on the parent directory, ``want`` on the target (if
        it exists), ``owner_only`` for attribute changes, ``super_only``
        for admin ops.  The superuser — and in-process callers, which carry
        no wire identity — bypass, matching the reference."""
        user, groups = perm.caller()
        if user is None or user == self._superuser \
                or not self.config.permissions_enabled:
            return
        if super_only:
            raise PermissionError(f"{user} is not the superuser")
        raw_parts = [p for p in path.split("/") if p]
        snapshot_path = ".snapshot" in raw_parts
        parts = [p for p in raw_parts if p != ".snapshot"]
        if snapshot_path:
            # checks walk the LIVE ancestors up to the snapshottable dir;
            # the frozen target itself is resolved snapshot-aware below
            parts = raw_parts[:raw_parts.index(".snapshot")]
        node: Any = self._root
        chain: list[Any] = [node]
        for i, p in enumerate(parts):
            if not isinstance(node, (DirNode, dict)):
                break
            attrs = self._dir_attrs(node)
            if not perm.allows(attrs, user, groups, perm.EXECUTE):
                raise PermissionError(
                    f"permission denied: user={user} needs EXECUTE on "
                    f"/{'/'.join(parts[:i])}")
            node = node.get(p) if isinstance(node, dict) else None
            chain.append(node)
        parent = chain[-2] if len(chain) >= 2 else self._root
        target = chain[-1] if len(chain) == len(parts) + 1 else None
        if snapshot_path:
            # the frozen inode carries the attrs it had at snapshot time;
            # enforce the target check against those (a 0600 file does not
            # become readable through /dir/.snapshot/name/...)
            try:
                target = self._resolve(path)
            except (FileNotFoundError, NotADirectoryError):
                target = None
            parent = None
        if parent_want and isinstance(parent, (DirNode, dict)):
            if not perm.allows(self._dir_attrs(parent), user, groups,
                               parent_want):
                raise PermissionError(
                    f"permission denied: user={user} needs "
                    f"{'WRITE' if parent_want & 2 else 'READ'} on the "
                    f"parent of {path}")
        if target is not None and isinstance(target, (FileNode, DirNode)):
            attrs = self._node_attrs(target)
            if owner_only and user != attrs.owner:
                raise PermissionError(
                    f"permission denied: {user} is not the owner of {path}")
            if want and not perm.allows(attrs, user, groups, want):
                raise PermissionError(
                    f"permission denied: user={user} on {path}")

    def _resolve(self, path: str, follow_leaf: bool = True) -> Any:
        parts = [p for p in path.split("/") if p]
        if ".snapshot" in parts:
            return self._resolve_snapshot(parts)
        node: Any = self._root
        for i, p in enumerate(parts):
            if isinstance(node, (FileNode, SymNode)):
                raise NotADirectoryError(path)
            if p not in node:
                raise FileNotFoundError(path)
            node = node[p]
            if isinstance(node, SymNode) and (follow_leaf
                                              or i < len(parts) - 1):
                self._link_redirect(node.target, parts[:i + 1],
                                    parts[i + 1:])
        return node

    def _resolve_snapshot(self, parts: list[str]) -> Any:
        """Resolve ``<dir>/.snapshot[/<name>[/rest...]]`` through the frozen
        trees (the /.snapshot virtual-directory convention)."""
        i = parts.index(".snapshot")
        droot = "/" + "/".join(parts[:i])
        snaps = self._snapshots.get(droot)
        if snaps is None:
            raise FileNotFoundError(f"{droot} is not snapshottable")
        rest = parts[i + 1:]
        if not rest:  # listing /dir/.snapshot -> one dir per snapshot name
            return {name: self._thaw(tree) for name, tree in snaps.items()}
        if rest[0] not in snaps:
            raise FileNotFoundError(f"no snapshot {rest[0]} of {droot}")
        node = self._thaw(snaps[rest[0]])
        for p in rest[1:]:
            if isinstance(node, (FileNode, SymNode)):
                raise NotADirectoryError("/".join(parts))
            if p not in node:
                raise FileNotFoundError("/".join(parts))
            node = node[p]
        return node

    def _file(self, path: str) -> FileNode:
        node = self._resolve(path)
        if not isinstance(node, FileNode):
            raise IsADirectoryError(path)
        return node

    def _mkdir_apply(self, path: str, user: str | None = None,
                     mode: int | None = None) -> None:
        node = self._root
        parts = self._parts(path)
        for i, p in enumerate(parts):
            child = node.get(p)
            if child is None:
                child = node[p] = DirNode(attrs=perm.inherit_attrs(
                    self._dir_attrs(node), user or self._superuser, None,
                    is_dir=True,
                    umode=mode if i == len(parts) - 1 else None),
                    inode_id=self._alloc_inode())
            if isinstance(child, FileNode):
                raise FileExistsError(f"{path}: {p} is a file")
            node = child

    def _delete_apply(self, path: str) -> None:
        dp = "/" + "/".join(self._parts(path))
        for z in list(self._ezones):  # deleting a zone (or its ancestor)
            if z == dp or z.startswith(dp + "/"):
                del self._ezones[z]
        parent, name = self._parent_of(path)
        node = parent.pop(name, None)
        kept = self._snapshot_referenced()  # (block ids, group ids) to keep
        for fn in self._iter_files(node):
            for gb in fn.blocks:
                grp = self._groups.get(gb)
                if grp is not None:
                    if gb in kept[1]:
                        continue  # a snapshot still references this group
                    self._groups.pop(gb)
                    bids = grp.bids
                else:
                    bids = [gb]
                for bid in bids:
                    if bid in kept[0]:
                        continue
                    self._drop_block(bid)
        # in-flight writes anywhere under the deleted path lose their leases
        self._leases.drop_subtree(path)

    def _drop_block(self, bid: int) -> None:
        self._uncharge_alloc(bid)
        info = self._blocks.pop(bid, None)
        if info:
            for dn_id in info.locations:
                dn = self._datanodes.get(dn_id)
                if dn:
                    dn.commands.append({"cmd": "invalidate",
                                        "block_ids": [bid]})

    # ------------------------------------------------------------ snapshots

    @staticmethod
    def _freeze(node: Any) -> Any:
        """Live subtree -> the serialized tree form (same layout as the
        fsimage walk): a consistent point-in-time view, since completed
        blocks are immutable."""
        if isinstance(node, FileNode):
            return ["f", node.replication, node.scheme, list(node.blocks),
                    node.complete, node.mtime, node.ec, node.attrs.pack(),
                    node.inode_id]
        if isinstance(node, SymNode):
            return ["l", node.target, node.attrs.pack(), node.inode_id]
        return ["d", {name: NameNode._freeze(child)
                      for name, child in node.items()},
                node.attrs.pack() if isinstance(node, DirNode) else None,
                getattr(node, "inode_id", 0)]

    def _thaw(self, v: Any) -> Any:
        """Frozen form -> read-only live-form objects (for resolution through
        ``/dir/.snapshot/name/...`` paths)."""
        if v[0] == "f":
            return FileNode(v[1], v[2], list(v[3]), v[4], v[5],
                            v[6] if len(v) > 6 else None,
                            Attrs.unpack(v[7] if len(v) > 7 else None,
                                         mode=0o644),
                            inode_id=v[8] if len(v) > 8 else 0)
        if v[0] == "l":
            return SymNode(v[1], Attrs.unpack(v[2]),
                           inode_id=v[3] if len(v) > 3 else 0)
        d = DirNode({name: self._thaw(child) for name, child in v[1].items()})
        d.attrs = Attrs.unpack(v[2] if len(v) > 2 else None)
        d.inode_id = v[3] if len(v) > 3 else 0
        return d

    def _tree_blocks(self, v: Any) -> tuple[set[int], set[int]]:
        """(block ids, group ids) referenced by a frozen tree."""
        bids: set[int] = set()
        gids: set[int] = set()
        if v[0] == "l":
            return bids, gids
        if v[0] == "f":
            for gb in v[3]:
                grp = self._groups.get(gb)
                if grp is not None:
                    gids.add(gb)
                    bids.update(grp.bids)
                else:
                    bids.add(gb)
        else:
            for child in v[1].values():
                b, g = self._tree_blocks(child)
                bids |= b
                gids |= g
        return bids, gids

    def _snapshot_referenced(self) -> tuple[set[int], set[int]]:
        bids: set[int] = set()
        gids: set[int] = set()
        for snaps in self._snapshots.values():
            for tree in snaps.values():
                b, g = self._tree_blocks(tree)
                bids |= b
                gids |= g
        return bids, gids

    def _delete_snapshot_apply(self, path: str, name: str) -> None:
        path = "/" + "/".join(self._parts(path))
        tree = self._snapshots.get(path, {}).pop(name)
        dead_b, dead_g = self._tree_blocks(tree)
        live_b, live_g = self._snapshot_referenced()
        # blocks still reachable from the live namespace also stay
        for fn in self._iter_files(self._root):
            for gb in fn.blocks:
                grp = self._groups.get(gb)
                if grp is not None:
                    live_g.add(gb)
                    live_b.update(grp.bids)
                else:
                    live_b.add(gb)
        for gid in dead_g - live_g:
            self._groups.pop(gid, None)
        for bid in dead_b - live_b:
            self._drop_block(bid)

    def _rename_apply(self, src: str, dst: str) -> None:
        sp = "/" + "/".join(self._parts(src))
        dp = "/" + "/".join(self._parts(dst))
        # a renamed ZONE ROOT (or an ancestor of one) carries its zone entry
        for z in list(self._ezones):
            if z == sp or z.startswith(sp + "/"):
                self._ezones[dp + z[len(sp):]] = self._ezones.pop(z)
        sparent, sname = self._parent_of(src)
        node = sparent[sname]
        dparent, dname = self._parent_of(dst, create=True)
        if dname in dparent:
            raise FileExistsError(dst)
        del sparent[sname]
        dparent[dname] = node
        # fix block back-pointers
        prefix_old, prefix_new = src.rstrip("/"), dst.rstrip("/")
        for info in self._blocks.values():
            if info.path == prefix_old or info.path.startswith(prefix_old + "/"):
                info.path = prefix_new + info.path[len(prefix_old):]

    @staticmethod
    def _iter_files(node: Any):
        if isinstance(node, FileNode):
            yield node
        elif isinstance(node, dict):
            for child in node.values():
                yield from NameNode._iter_files(child)

    # ------------------------------------------------------ client RPC: fs ops

    def rpc_mkdir(self, path: str, mode: int | None = None) -> bool:
        with self._lock:
            self._check_access(path, parent_want=perm.WRITE)
            self._check_ns_quota(path)
            self._log(["mkdir", path,
                       perm.caller()[0] or self._superuser, mode])
            _M.incr("mkdir")
            return True

    def rpc_create(self, path: str, client: str, replication: int | None = None,
                   scheme: str | None = None, ec: str | None = None,
                   mode: int | None = None) -> dict:
        with self._lock:
            self._check_access(path, parent_want=perm.WRITE)
            replication = replication or self.config.replication
            scheme = scheme or "direct"
            if ec is not None:
                from hdrf_tpu.ops import rs
                rs.parse_policy(ec)  # validate before logging
            parent, name = self._parent_of(path, create=True)
            existing = parent.get(name)
            if existing is not None:
                if isinstance(existing, dict):
                    raise IsADirectoryError(path)
                if existing.complete:
                    raise FileExistsError(path)
            self._check_ns_quota(path)
            # Check (non-mutating) before logging, acquire only after: _log
            # raises StandbyError/FencedError on a non-active NN, and a lease
            # granted before that check would sit un-expirable on the standby
            # (lease recovery only runs on the active), spuriously blocking
            # creates after a promotion.
            self._leases.check_available(path, client)
            zone = self._zone_of(path)
            if zone is not None and ec is not None:
                # validated BEFORE the overwrite delete below: a rejected
                # create must not destroy the existing file
                raise IOError("EC files inside encryption zones are not "
                              "supported")
            if existing is not None:
                # Overwriting an abandoned incomplete file: drop it first so
                # its allocated blocks are invalidated on DNs rather than
                # leaking in the block map forever.
                self._log(["delete", path])
            self._log(["create", path, replication, scheme, time.time(), ec,
                       perm.caller()[0] or self._superuser, mode])
            enc = None
            if zone is not None:
                # per-file DEK wrapped by the zone key; the EDEK persists
                # as a raw.* xattr (FSDirEncryptionZoneOp semantics), the
                # RAW dek returns only to this creator (who holds WRITE)
                import os as _os

                import msgpack as _mp

                from hdrf_tpu import native as _nat

                key_name = self._ezones[zone]
                dek, iv = _os.urandom(32), _os.urandom(12)
                edek = _nat.aead_seal(self._ezkeys[key_name], iv,
                                      self._EZ_AAD, dek)
                self._log(["setxattr", path, self._EZ_XATTR,
                           _mp.packb([key_name, iv, edek])])
                enc = {"dek": dek, "iv": iv}
            self._leases.acquire(path, client)
            _M.incr("create")
            return {"block_size": self.config.block_size, "scheme": scheme,
                    "replication": replication, "ec": ec,
                    "encryption": enc}

    def rpc_add_block(self, path: str, client: str) -> dict:
        """Allocate the next block + choose target DNs (addBlock RPC ->
        BlockManager placement, DataStreamer.java:1655's nextBlockOutputStream)."""
        with self._lock:
            node = self._file(path)  # resolves symlinks (redirect) FIRST —
            # the lease is keyed by the resolved path the create used
            self._leases.check(path, client)
            self._check_space_quota(path, self.config.block_size)
            bid, gs = self._next_block_id, self._gen_stamp
            slots: list = []
            targets = self._choose_targets(node.replication, exclude=set(),
                                           policy=self._policy_of(path),
                                           slots=slots)
            if not targets:
                raise IOError("no datanodes available")
            self._log(["add_block", path, bid, gs])
            self._blocks[bid].expected = [d.dn_id for d in targets]
            self._charge_alloc(path, bid, self.config.block_size)
            _M.incr("add_block")
            return {"block_id": bid, "gen_stamp": gs, "scheme": node.scheme,
                    "token": (self._tokens.mint(bid, "w")
                              if self._tokens else None),
                    "targets": [{"dn_id": d.dn_id, "addr": list(d.addr),
                                 "storage_type": st}
                                for d, st in zip(targets, slots)]}

    def rpc_add_block_group(self, path: str, client: str) -> dict:
        """Allocate one EC block group: k+m internal blocks on k+m distinct
        DNs (DFSStripedOutputStream's block-group allocation analog)."""
        from hdrf_tpu.ops import rs

        with self._lock:
            node = self._file(path)
            self._leases.check(path, client)
            if not node.ec:
                raise ValueError(f"{path} is not an EC file")
            k, m, cell = rs.parse_policy(node.ec)
            self._check_space_quota(path, k * self.config.block_size)
            targets = self._choose_targets(k + m, exclude=set(),
                                           policy=self._policy_of(path))
            if len(targets) < k + m:
                # fewer DNs than shards: wrap around (degraded placement;
                # real deployments require >= k+m racks/nodes)
                if not targets:
                    raise IOError("no datanodes available")
                targets = [targets[i % len(targets)] for i in range(k + m)]
            bids = list(range(self._next_block_id, self._next_block_id + k + m))
            gs = self._gen_stamp
            self._log(["add_block_group", path, bids, gs])
            self._charge_alloc(path, bids[0], k * self.config.block_size)
            _M.incr("add_block_group")
            return {"group_id": bids[0], "gen_stamp": gs, "k": k, "m": m,
                    "cell": cell,
                    "blocks": [{"block_id": b,
                                "token": (self._tokens.mint(b, "w")
                                          if self._tokens else None),
                                "target": {"dn_id": t.dn_id,
                                           "addr": list(t.addr)}}
                               for b, t in zip(bids, targets)]}

    def rpc_append(self, path: str, client: str) -> dict:
        """Reopen a complete file for appending (FSNamesystem.appendFile
        analog).  The file's last partial block is rewritten by the client
        under a bumped generation stamp (block-granular copy-on-append —
        the clean fit for reduced storage, where in-place mutation of a
        deduplicated block has no meaning; CDC makes the re-reduction of
        the rewritten block dedup against its own old chunks)."""
        with self._lock:
            self._check_access(path, want=perm.WRITE)
            node = self._file(path)
            if not node.complete:
                raise IOError(f"{path} is already open for writing")
            if node.ec:
                raise IOError("append to EC files is not supported "
                              "(matches the reference)")
            if self._EZ_XATTR in node.attrs.xattrs:
                raise IOError("append to encrypted files is not supported "
                              "(rewrite-under-new-DEK is the workaround)")
            self._leases.check_available(path, client)
            self._log(["append", path, time.time()])
            self._leases.acquire(path, client)
            last = None
            if node.blocks:
                info = self._blocks[node.blocks[-1]]
                if 0 < info.length < self.config.block_size:
                    last = {"block_id": info.block_id,
                            "gen_stamp": info.gen_stamp,
                            "length": info.length}
            _M.incr("appends")
            return {"block_size": self.config.block_size, "last_block": last,
                    "file_length": self._file_len(node)}

    def rpc_append_block(self, path: str, client: str) -> dict:
        """Targets + bumped gen stamp for rewriting the last partial block.
        Old-generation replicas are superseded: block reports carrying a
        stale gen stamp are invalidated (the reference's gen-stamp
        supersede after pipeline recovery)."""
        with self._lock:
            node = self._file(path)
            self._leases.check(path, client)
            bid = node.blocks[-1]
            info = self._blocks[bid]
            new_gs = self._gen_stamp + 1
            targets = self._choose_targets(node.replication, exclude=set(),
                                           policy=self._policy_of(path))
            if not targets:
                raise IOError("no datanodes available")
            self._log(["bump_block", path, bid, new_gs])
            info.expected = [d.dn_id for d in targets]
            return {"block_id": bid, "gen_stamp": new_gs,
                    "scheme": node.scheme,
                    "token": (self._tokens.mint(bid, "w")
                              if self._tokens else None),
                    "targets": [{"dn_id": d.dn_id, "addr": list(d.addr)}
                                for d in targets]}

    def rpc_truncate(self, path: str, new_length: int) -> bool:
        """Namespace-level truncate (FSNamesystem.truncate analog): whole
        blocks beyond the cut are dropped (their replicas invalidate like a
        delete), and the boundary block's logical length is reduced — reads
        clamp to it, so no replica rewrite is needed; the surplus physical
        bytes are reclaimed when the block is next copied (re-replication /
        balancer), the same deferred-trim the reference's truncate recovery
        performs."""
        with self._lock:
            self._check_access(path, want=perm.WRITE)
            node = self._file(path)
            if not node.complete:
                raise IOError(f"{path} is open for writing")
            if node.ec:
                raise IOError("truncate of EC files is not supported")
            cur = self._file_len(node)
            if new_length > cur:
                raise ValueError(f"truncate to {new_length} > length {cur}")
            if new_length == cur:
                return True
            self._log(["truncate", path, new_length, time.time()])
            _M.incr("truncates")
            return True

    def rpc_abandon_block(self, path: str, client: str, block_id: int) -> bool:
        with self._lock:
            self._file(path)  # symlink redirect before the lease check
            self._leases.check(path, client)
            self._log(["abandon_block", path, block_id])
            return True

    def rpc_complete(self, path: str, client: str,
                     block_lengths: dict[int, int]) -> bool:
        """False = not yet: some block has no reported location (IBRs are
        asynchronous); the client retries — completeFile's retry loop in the
        reference (DFSClient) exists for exactly this, with the NN holding
        completion until minimal replication is met."""
        with self._lock:
            self._file(path)  # symlink redirect before the lease check
            self._leases.check(path, client)
            for bid in block_lengths:
                bids = (self._groups[bid].bids if bid in self._groups
                        else [bid])
                for b in bids:
                    info = self._blocks.get(b)
                    if info is None or not (info.locations & set(self._datanodes)):
                        _M.incr("complete_waiting_ibr")
                        return False
            self._log(["complete", path, dict(block_lengths), time.time()])
            self._leases.release(path, client)
            _M.incr("complete")
            return True

    def rpc_recover_lease(self, path: str) -> bool:
        """Force lease recovery on ``path`` (DFSAdmin recoverLease /
        DistributedFileSystem.recoverLease analog).  The writer's lease is
        force-expired — NOT dropped — so the recovery monitor keeps driving
        the (asynchronous, possibly multi-step) recovery even if the caller
        stops polling.  Returns True when the file is closed afterwards."""
        with self._lock:
            node = self._file(path)
            p = "/" + "/".join(self._parts(path))
            self._leases.drop(path)  # un-normalized alias, if any
            if node.complete:
                self._leases.drop(p)
                return True
            self._leases.force_expire(p)
            if self._finalize_abandoned(p, node):
                self._leases.drop(p)
                return True
            return False

    def rpc_renew_lease(self, client: str) -> bool:
        with self._lock:
            self._leases.renew_all(client)
            return True

    def rpc_fsync(self, path: str, client: str, block_id: int,
                  length: int) -> bool:
        """Persist the visible length of an under-construction block after a
        client hflush/hsync (ClientProtocol.fsync, FSNamesystem.fsync:
        updateBlockForPipeline's length persist) — a reader calling
        get_block_locations from now on sees the flushed bytes.  Length can
        only grow (a lagging retry must not shrink a longer flush)."""
        with self._lock:
            self._file(path)
            self._leases.check(path, client)
            info = self._blocks.get(block_id)
            if info is None:
                raise KeyError(f"block {block_id} is not allocated")
            if info.path != "/" + "/".join(self._parts(path)):
                # the lease only covers the caller's own file: without this
                # check a writer could inflate ANY under-construction
                # block's recorded length in the namespace
                raise PermissionError(
                    f"block {block_id} does not belong to {path}")
            if length > info.length:
                self._log(["fsync", path, block_id, length])
            self._leases.renew_all(client)
            _M.incr("fsyncs")
            return True

    def rpc_get_block_locations(self, path: str) -> dict:
        with self._lock:
            self._check_access(path, want=perm.READ)
            node = self._file(path)
            _M.incr("get_block_locations")
            if node.ec:
                groups = []
                for gid in node.blocks:
                    grp = self._groups[gid]
                    groups.append({
                        "group_id": gid,
                        "gen_stamp": self._blocks[gid].gen_stamp,
                        "length": grp.logical_len,
                        "blocks": [{"block_id": b,
                                    "token": (self._tokens.mint(b, "r")
                                              if self._tokens else None),
                                    "locations": self._locs_of(b)}
                                   for b in grp.bids]})
                return {"ec": node.ec, "groups": groups, "scheme": node.scheme,
                        "length": sum(max(g["length"], 0) for g in groups),
                        "complete": node.complete}
            blocks = []
            for bid in node.blocks:
                info = self._blocks[bid]
                locs = self._locs_of(bid)
                if not locs and not node.complete and info.length > 0:
                    # under-construction block with an hflush'd visible
                    # length: no replica has finalized yet, so serve the
                    # intended pipeline DNs (the reference returns the UC
                    # block's expected locations to readers of open files)
                    locs = [{"dn_id": d,
                             "addr": list(self._datanodes[d].addr),
                             "sc_path": self._datanodes[d].sc_path}
                            for d in info.expected if d in self._datanodes]
                blocks.append({"block_id": bid, "gen_stamp": info.gen_stamp,
                               "length": info.length,
                               "token": (self._tokens.mint(bid, "r")
                                         if self._tokens else None),
                               "locations": locs})
            enc = None
            if self._EZ_XATTR in node.attrs.xattrs:
                # FileEncryptionInfo-in-LocatedBlocks: the decrypted DEK
                # rides the same READ-gated response, sparing the client a
                # second NN round trip per read
                enc = self._decrypt_edek_locked(node)
            return {"blocks": blocks, "scheme": node.scheme, "ec": None,
                    "length": sum(max(b["length"], 0) for b in blocks),
                    "complete": node.complete,
                    "encrypted": enc is not None,
                    "encryption": enc}

    def _locs_of(self, bid: int) -> list[dict]:
        info = self._blocks[bid]
        return [{"dn_id": d, "addr": list(self._datanodes[d].addr),
                 "sc_path": self._datanodes[d].sc_path}
                for d in info.locations if d in self._datanodes]

    def rpc_delete(self, path: str) -> bool:
        with self._lock:
            self._check_access(path, parent_want=perm.WRITE)
            try:
                self._resolve(path, follow_leaf=False)  # delete the LINK
            except FileNotFoundError:
                return False
            self._log(["delete", path])
            _M.incr("delete")
            return True

    def rpc_rename(self, src: str, dst: str) -> bool:
        with self._lock:
            self._check_access(src, parent_want=perm.WRITE)
            self._check_access(dst, parent_want=perm.WRITE)
            if self._zone_of(src) != self._zone_of(dst):
                # crossing an encryption-zone boundary would detach files
                # from their zone key (the reference rejects this too)
                raise IOError("renames across encryption-zone boundaries "
                              "are not supported")
            self._resolve(src, follow_leaf=False)
            s = "/" + "/".join(self._parts(src))
            d = "/" + "/".join(p for p in dst.split("/") if p)
            if d == s or d.startswith(s + "/"):
                raise ValueError(f"cannot rename {src} into its own subtree {dst}")
            self._log(["rename", src, dst])
            return True

    def rpc_listing(self, path: str) -> list[dict]:
        with self._lock:
            self._check_access(path, want=perm.READ)
            node = self._resolve(path)
            if isinstance(node, FileNode):
                return [self._stat_entry(path.rstrip("/").rsplit("/", 1)[-1], node)]
            return [self._stat_entry(name, child)
                    for name, child in sorted(node.items())]

    def rpc_stat(self, path: str) -> dict:
        with self._lock:
            self._check_access(path)  # traverse (getFileInfo semantics)
            node = self._resolve(path)
            name = path.rstrip("/").rsplit("/", 1)[-1] or "/"
            return self._stat_entry(name, node)

    def _stat_entry(self, name: str, node: Any) -> dict:
        if isinstance(node, FileNode):
            if node.ec:
                length = sum(max(self._groups[g].logical_len, 0)
                             for g in node.blocks if g in self._groups)
            else:
                length = sum(max(self._blocks[b].length, 0)
                             for b in node.blocks if b in self._blocks)
            a = node.attrs
            return {"name": name, "type": "file", "length": length,
                    "replication": node.replication, "scheme": node.scheme,
                    "complete": node.complete, "blocks": len(node.blocks),
                    "mtime": node.mtime, "ec": node.ec,
                    "owner": a.owner, "group": a.group, "mode": a.mode}
        if isinstance(node, SymNode):
            a = node.attrs
            return {"name": name, "type": "symlink", "target": node.target,
                    "owner": a.owner, "group": a.group, "mode": a.mode}
        a = self._dir_attrs(node)
        return {"name": name, "type": "dir", "children": len(node),
                "owner": a.owner, "group": a.group, "mode": a.mode}

    # ----------------------------------------------------- encryption zones

    _EZ_XATTR = "raw.hdrf.crypto"
    _EZ_AAD = b"hdrf-ez-edek"

    def _zone_of(self, path: str) -> str | None:
        p = "/" + "/".join(x for x in path.split("/") if x)
        for z in self._ezones:
            if p == z or p.startswith(z + "/"):
                return z
        return None

    def rpc_create_encryption_key(self, name: str) -> bool:
        """Key-provider create (the ``hadoop key create`` role).  Keys are
        journaled: a promoted standby must decrypt EDEKs too."""
        import os as _os

        with self._lock:
            self._check_access("/", super_only=True)
            self._log(["ezkey", name, _os.urandom(32)])
            _M.incr("ez_keys_created")
            return True

    def rpc_create_encryption_zone(self, path: str, key_name: str) -> bool:
        """crypto -createZone (EncryptionZoneManager.java:71): an EMPTY
        directory becomes a zone; every file created under it gets a
        per-file DEK wrapped by the zone key."""
        with self._lock:
            self._check_access("/", super_only=True)
            self._log(["ez", path, key_name])
            _M.incr("ez_created")
            return True

    def rpc_list_encryption_zones(self) -> dict:
        """listEncryptionZones is superuser-only, as in the reference —
        zone roots + key names leak namespace structure otherwise."""
        with self._lock:
            self._check_access("/", super_only=True)
            return dict(self._ezones)

    def rpc_get_ez(self, path: str) -> dict:
        with self._lock:
            self._check_access(path)  # traverse
            z = self._zone_of(path)
            return {"zone": z, "key": self._ezones.get(z) if z else None}

    def _decrypt_edek_locked(self, node) -> dict | None:
        from hdrf_tpu import native

        blob = self._node_attrs(node).xattrs.get(self._EZ_XATTR)
        if blob is None:
            return None
        import msgpack as _mp

        key_name, iv, edek = _mp.unpackb(bytes(blob), raw=False)
        zkey = self._ezkeys.get(key_name)
        if zkey is None:
            raise KeyError(f"zone key {key_name} is gone")
        dek = native.aead_open(zkey, bytes(iv), self._EZ_AAD, bytes(edek))
        if dek is None:
            raise IOError("EDEK failed authentication")
        return {"dek": dek, "iv": bytes(iv)}

    def rpc_decrypt_edek(self, path: str) -> dict:
        """The KMS-decrypt role: a reader with READ permission on the file
        gets the file's raw DEK + IV (the zone key itself never leaves the
        NN)."""
        with self._lock:
            self._check_access(path, want=perm.READ)
            out = self._decrypt_edek_locked(self._resolve(path))
            if out is None:
                raise KeyError(f"{path} is not encrypted")
            return out

    # ------------------------------------------------------ cache directives

    def rpc_add_cache_pool(self, name: str, limit: int = -1) -> bool:
        """cacheadmin -addPool (CacheManager.java:103 analog)."""
        with self._lock:
            self._check_access("/", super_only=True)
            self._log(["cachepool", name,
                       perm.caller()[0] or self._superuser, limit])
            _M.incr("cache_pools_added")
            return True

    def rpc_remove_cache_pool(self, name: str) -> bool:
        with self._lock:
            self._check_access("/", super_only=True)
            if name not in self._cache_pools:
                return False
            self._log(["rmcachepool", name])
            return True

    def rpc_list_cache_pools(self) -> dict:
        with self._lock:
            return {n: dict(p) for n, p in self._cache_pools.items()}

    def rpc_add_cache_directive(self, path: str, pool: str) -> int:
        """cacheadmin -addDirective: pin ``path``'s blocks (a file, or every
        file under a directory) in DN memory; the cache monitor drives
        DNA_CACHE commands until the DNs report the blocks pinned."""
        with self._lock:
            self._check_access(path, want=perm.READ)
            did = self._next_cache_id
            self._log(["cachedir", did, path, pool])
            _M.incr("cache_directives_added")
            return did

    def rpc_remove_cache_directive(self, directive_id: int) -> bool:
        with self._lock:
            d = self._cache_dirs.get(directive_id)
            if d is not None:
                # the directive path's owner (or the superuser) controls it
                self._check_access(d["path"], owner_only=True)
            self._log(["rmcachedir", directive_id])
            return True

    def rpc_list_cache_directives(self) -> list[dict]:
        with self._lock:
            out = []
            for did, d in sorted(self._cache_dirs.items()):
                bids = self._directive_blocks(d["path"])
                cached = sum(1 for b in bids
                             if any(b in dn.cached
                                    for dn in self._datanodes.values()))
                out.append({"id": did, "path": d["path"], "pool": d["pool"],
                            "blocks": len(bids), "blocks_cached": cached})
            return out

    def _directive_blocks(self, path: str) -> set[int]:
        try:
            node = self._resolve(path)
        except (FileNotFoundError, NotADirectoryError, SymlinkRedirect):
            return set()
        out: set[int] = set()
        files = [node] if isinstance(node, FileNode) \
            else list(self._iter_files(node))
        for fn in files:
            if fn.complete and not fn.ec:
                out.update(fn.blocks)
        return out

    CACHE_RETRY_S = 10.0

    def _check_cache(self) -> None:
        """Cache monitor (CacheReplicationMonitor analog): command one
        holder of each directive-covered block to pin it; uncache pinned
        blocks no directive covers anymore."""
        with self._lock:
            wanted: set[int] = set()
            for d in self._cache_dirs.values():
                wanted |= self._directive_blocks(d["path"])
            now = time.monotonic()
            # expire dead bookkeeping: entries for satisfied/removed
            # directives or past their retry deadline (unbounded growth
            # otherwise), and rotate holders so one full cache doesn't pin
            # a directive unsatisfied forever
            self._pending_cache = {k: v for k, v in
                                   self._pending_cache.items()
                                   if v > now and k[0] in wanted}
            cached_anywhere: set[int] = set()
            for dn in self._datanodes.values():
                cached_anywhere |= dn.cached
            for bid in wanted - cached_anywhere:
                info = self._blocks.get(bid)
                if info is None:
                    continue
                holders = sorted(d for d in info.locations
                                 if d in self._datanodes)
                target = next((d for d in holders
                               if (bid, d) not in self._pending_cache),
                              None)
                if target is None:
                    continue  # every holder tried recently; retry later
                self._pending_cache[(bid, target)] = now + self.CACHE_RETRY_S
                self._datanodes[target].commands.append(
                    {"cmd": "cache", "block_ids": [bid]})
                _M.incr("cache_commands_sent")
            for dn in self._datanodes.values():
                extra = dn.cached - wanted
                if extra:
                    dn.commands.append({"cmd": "uncache",
                                        "block_ids": sorted(extra)})

    # ---------------------- storage policies / replication / times / concat

    def rpc_set_storage_policy(self, path: str, policy: str) -> bool:
        """setStoragePolicy (FSDirAttrOp analog): per-path policy selecting
        replica storage types; '' clears (inherit)."""
        with self._lock:
            if policy and policy not in self.STORAGE_POLICIES:
                raise ValueError(f"unknown storage policy {policy!r}; "
                                 f"known: {sorted(self.STORAGE_POLICIES)}")
            self._check_access(path, owner_only=True)
            self._resolve(path)
            self._log(["setpolicy", path, policy])
            _M.incr("setpolicy")
            return True

    def rpc_get_storage_policy(self, path: str) -> dict:
        with self._lock:
            self._check_access(path)
            node = self._resolve(path)
            a = getattr(node, "attrs", None)
            return {"policy": a.policy if a else None,
                    "effective": self._policy_of(path)}

    def rpc_set_replication(self, path: str, replication: int) -> bool:
        """setReplication (FSDirAttrOp.setReplication): the redundancy
        monitor converges live replica counts to the new target (adds via
        re-replication, trims via excess pruning)."""
        with self._lock:
            if replication < 1:
                raise ValueError("replication must be >= 1")
            self._check_access(path, want=perm.WRITE)
            node = self._file(path)
            if node.ec:
                raise ValueError("EC files carry no replication factor")
            self._log(["setrepl", path, replication])
            _M.incr("setrepl")
            return True

    def rpc_set_times(self, path: str, mtime: float = -1.0) -> bool:
        """setTimes analog (atime tracking is not kept — the reference
        only persists atime at precision intervals; we document mtime)."""
        with self._lock:
            self._check_access(path, want=perm.WRITE)
            self._file(path)
            self._log(["settimes", path, float(mtime)])
            return True

    def rpc_concat(self, dst: str, srcs: list[str]) -> bool:
        """concat (FSDirConcatOp.java:49): move srcs' blocks onto dst and
        delete the src inodes — pure namespace surgery, no data motion.
        Unlike the reference, interior partial blocks are legal here: reads
        walk per-block logical lengths, so no full-block constraint."""
        with self._lock:
            self._check_access(dst, want=perm.WRITE)
            for sp in srcs:
                self._check_access(sp, want=perm.WRITE,
                                   parent_want=perm.WRITE)
            for pth in [dst, *srcs]:
                node = self._file(pth)
                if self._EZ_XATTR in node.attrs.xattrs \
                        or self._zone_of(pth) is not None:
                    # per-file DEKs make concatenated ciphertexts
                    # undecipherable as one stream; the reference forbids
                    # concat inside encryption zones too
                    raise IOError("concat of encrypted files / inside "
                                  "encryption zones is not supported")
            self._log(["concat", dst, list(srcs), time.time()])
            _M.incr("concat")
            return True

    def rpc_create_symlink(self, link: str, target: str) -> bool:
        """createSymlink (FSDirSymlinkOp.java:34).  Resolution is client
        side: any path through a link answers SymlinkRedirect and the
        client retries with the resolved path."""
        with self._lock:
            self._check_access(link, parent_want=perm.WRITE)
            self._check_ns_quota(link)
            self._log(["symlink", link, target,
                       perm.caller()[0] or self._superuser])
            _M.incr("symlinks_created")
            return True

    def rpc_policy_violations(self, limit: int = 100) -> list[dict]:
        """Mover support (Mover.java:70 analog): blocks whose live replica
        storage types don't satisfy their path's effective policy, each
        with a proposed (from_dn, to_dn) migration.  The mover executes
        them via rpc_move_block and re-polls until empty."""
        with self._lock:
            out: list[dict] = []
            now = time.monotonic()
            live_dns = {d.dn_id: d for d in self._datanodes.values()
                        if now - d.last_heartbeat
                        < self.config.dead_node_interval_s}
            for info in self._blocks.values():
                if len(out) >= limit:
                    break
                node = self._try_file(info.path)
                if node is None or not node.complete or info.length < 0:
                    continue
                if info.block_id in self._pending_moves:
                    continue
                policy = self._policy_of(info.path)
                locs = [d for d in info.locations if d in live_dns]
                if not locs:
                    continue
                want = self._types_for(policy, len(locs))
                # multiset matching: each replica consumes one want slot of
                # its type; replicas that find no slot are SURPLUS (wrong),
                # and the unconsumed slots are what's still needed — a
                # plain membership test misses multi-type policies (warm
                # with every replica on DISK has need but no "not in want"
                # replica)
                need = list(want)
                wrong = []
                for d in locs:
                    # the replica's ACTUAL volume type when the DN reports
                    # per-storage; the node's primary type otherwise
                    t = info.storage_of.get(d, live_dns[d].storage_type)
                    if t in need:
                        need.remove(t)
                    else:
                        wrong.append(d)
                if not need:
                    continue
                cands = [d for d in live_dns.values()
                         if need[0] in d.storage_types
                         and d.dn_id not in info.locations
                         and d.dn_id not in self._decommissioning]
                if wrong and cands:
                    out.append({"block_id": info.block_id,
                                "from_dn": wrong[0],
                                "to_dn": cands[0].dn_id,
                                "policy": policy})
            return out

    # ------------------------------------------- permissions / ACLs / xattrs

    def rpc_set_permission(self, path: str, mode: int) -> bool:
        """chmod (FSDirAttrOp.setPermission): owner or superuser only."""
        with self._lock:
            self._check_access(path, owner_only=True)
            self._resolve(path)
            self._log(["setperm", path, int(mode) & 0o7777])
            _M.incr("setperm")
            return True

    def rpc_set_owner(self, path: str, owner: str = "",
                      group: str = "") -> bool:
        """chown/chgrp.  Changing the OWNER is superuser-only (HDFS
        semantics); the owner may change the group — but only to a group
        they belong to (FSDirAttrOp rejects foreign-group attribution)."""
        with self._lock:
            if owner:
                self._check_access(path, super_only=True)
            else:
                self._check_access(path, owner_only=True)
                user, groups = perm.caller()
                if group and user is not None \
                        and user != self._superuser \
                        and self.config.permissions_enabled \
                        and group not in groups:
                    raise PermissionError(
                        f"{user} is not a member of group {group}")
            self._resolve(path)
            self._log(["setowner", path, owner, group])
            _M.incr("setowner")
            return True

    def rpc_get_acl(self, path: str) -> dict:
        """getfacl (FSDirAclOp.getAclStatus analog)."""
        with self._lock:
            self._check_access(path, want=perm.READ)
            a = self._node_attrs(self._resolve(path))
            return {"owner": a.owner, "group": a.group, "mode": a.mode,
                    "entries": perm.acl_to_strings(a),
                    "acl": [list(e) for e in a.acl],
                    "default_acl": [list(e) for e in a.dacl]}

    def rpc_set_acl(self, path: str, spec: str = "",
                    default_spec: str = "", remove_all: bool = False,
                    remove_default: bool = False) -> bool:
        """setfacl: ``spec``/``default_spec`` use the setfacl entry syntax
        ('user:alice:rwx,group::r-x'); modify semantics (entries merge by
        (kind, name)); ``remove_all``/``remove_default`` mirror -b / -k.
        Persisted through the editlog like every namespace mutation
        (AclStorage.java:65 stores ACL features on the inode the same way)."""
        with self._lock:
            self._check_access(path, owner_only=True)
            a = self._node_attrs(self._resolve(path))
            if remove_all:
                acl, dacl = [], []
            elif remove_default:
                acl, dacl = [list(e) for e in a.acl], []
            else:
                def merge(cur: list, new: list) -> list:
                    out = {(k, n): [k, n, p] for k, n, p in cur}
                    for k, n, p in new:
                        out[(k, n)] = [k, n, p]
                    return list(out.values())

                def remask(entries: list, group_bits: int,
                           explicit_mask: bool) -> list:
                    """POSIX setfacl: unless THIS spec set a mask
                    explicitly, the mask recalculates to the union of the
                    group class (named users/groups + owning-group bits) —
                    a stale mask must not silently limit a fresh grant."""
                    if not entries or explicit_mask:
                        return entries
                    entries = [e for e in entries if e[0] != "mask"]
                    u = group_bits
                    for k, n, p in entries:
                        if k in ("user", "group") and n:
                            u |= p
                    return entries + [["mask", "", u]]

                gbits = (a.mode >> 3) & 7
                new_a = perm.acl_spec_parse(spec) if spec else []
                acl = remask(merge(a.acl, new_a), gbits,
                             any(e[0] == "mask" for e in new_a))
                new_d = perm.acl_spec_parse(default_spec) \
                    if default_spec else []
                if new_d and not isinstance(self._resolve(path), DirNode):
                    raise ValueError("default ACLs apply to directories only")
                dacl = remask(merge(a.dacl, new_d), gbits,
                              any(e[0] == "mask" for e in new_d))
            self._log(["setacl", path, acl, dacl])
            _M.incr("setacl")
            return True

    def rpc_set_xattr(self, path: str, name: str, value: bytes) -> bool:
        """setfattr (FSDirXAttrOp.java:46 analog).  Namespaces: ``user.``
        needs WRITE on the inode; ``trusted.`` is superuser-only."""
        with self._lock:
            self._check_xattr_ns(path, name, writing=True)
            self._resolve(path)
            self._log(["setxattr", path, name, bytes(value)])
            _M.incr("setxattr")
            return True

    def rpc_get_xattrs(self, path: str,
                       names: list[str] | None = None) -> dict:
        with self._lock:
            self._check_access(path, want=perm.READ)
            a = self._node_attrs(self._resolve(path))
            user, _ = perm.caller()
            out = {}
            for k, v in a.xattrs.items():
                if names is not None and k not in names:
                    continue
                # trusted.*, raw.* (wrapped EDEKs live here) and system.*
                # are confined to the superuser, like the reference's
                # XAttrPermissionFilter namespace rules
                if (k.startswith(("trusted.", "raw.", "system."))
                        and user is not None
                        and user != self._superuser
                        and self.config.permissions_enabled):
                    continue
                out[k] = bytes(v)
            return out

    def rpc_remove_xattr(self, path: str, name: str) -> bool:
        with self._lock:
            self._check_xattr_ns(path, name, writing=True)
            self._resolve(path)
            self._log(["rmxattr", path, name])
            return True

    def _check_xattr_ns(self, path: str, name: str, writing: bool) -> None:
        ns = name.split(".", 1)[0] if "." in name else ""
        if ns not in ("user", "trusted", "system", "raw"):
            raise ValueError(f"xattr {name!r} lacks a valid namespace "
                             "(user./trusted./system./raw.)")
        if ns in ("trusted", "system", "raw"):
            self._check_access(path, super_only=True)
        else:
            self._check_access(path, want=perm.WRITE)

    # ----------------------------------------------------- snapshots & quotas

    def rpc_allow_snapshot(self, path: str) -> bool:
        """Superuser-only, like dfsadmin -allowSnapshot."""
        with self._lock:
            self._check_access(path, super_only=True)
            self._log(["allow_snapshot", path])
            return True

    def rpc_create_snapshot(self, path: str, name: str) -> bool:
        """Requires ownership of the snapshottable dir (HDFS semantics)."""
        with self._lock:
            self._check_access(path, owner_only=True)
            self._log(["create_snapshot", path, name])
            _M.incr("snapshots_created")
            return True

    def rpc_delete_snapshot(self, path: str, name: str) -> bool:
        with self._lock:
            self._check_access(path, owner_only=True)
            self._log(["delete_snapshot", path, name])
            return True

    def rpc_list_snapshots(self, path: str) -> list[str]:
        with self._lock:
            self._check_access(path, want=perm.READ)
            p = "/" + "/".join(self._parts(path))
            if p not in self._snapshots:
                raise FileNotFoundError(f"{p} is not snapshottable")
            return sorted(self._snapshots[p])

    def rpc_snapshot_diff(self, path: str, from_snap: str,
                          to_snap: str = "") -> dict:
        """Created/deleted/modified/renamed deltas between two snapshots of
        a snapshottable root (SnapshotManager.getSnapshotDiffReport,
        SnapshotDiffInfo.java:44) — renames are matched by inode id, so a
        moved file reports RENAME instead of delete+create (what makes
        snapshots usable for incremental distcp).  Empty ``to_snap`` diffs
        against the CURRENT tree ('.' in the reference CLI).  Paths in the
        report are relative to the snapshot root."""
        with self._lock:
            self._check_access(path, want=perm.READ)
            p = "/" + "/".join(self._parts(path))
            snaps = self._snapshots.get(p)
            if snaps is None:
                raise FileNotFoundError(f"{p} is not snapshottable")

            def tree_of(name: str):
                if not name:
                    return self._freeze(self._resolve(p))
                if name not in snaps:
                    raise FileNotFoundError(f"no snapshot {name} of {p}")
                return snaps[name]

            entries = _diff_trees(tree_of(from_snap), tree_of(to_snap))
            _M.incr("snapshot_diffs")
            return {"path": p, "from": from_snap, "to": to_snap,
                    "entries": entries}

    def rpc_set_quota(self, path: str, namespace_quota: int = -1,
                      space_quota: int = -1) -> bool:
        """-1/-1 clears (setQuota/clrQuota analog).  Superuser-only."""
        with self._lock:
            self._check_access(path, super_only=True)
            self._log(["set_quota", path, namespace_quota, space_quota])
            return True

    def rpc_content_summary(self, path: str) -> dict:
        """du -s analog (getContentSummary)."""
        with self._lock:
            self._check_access(path, want=perm.READ)
            node = self._resolve(path)
            files = dirs = length = 0
            if isinstance(node, FileNode):
                files, length = 1, self._file_len(node)
            else:
                dirs = 1
                for fn in self._iter_files(node):
                    files += 1
                    length += self._file_len(fn)
                dirs += sum(1 for _ in self._iter_dirs(node))
            p = "/" + "/".join(self._parts(path)) if path.strip("/") else "/"
            q = self._quotas.get(p, (-1, -1))
            return {"files": files, "dirs": dirs, "length": length,
                    "namespace_quota": q[0], "space_quota": q[1]}

    def _file_len(self, fn: FileNode) -> int:
        if fn.ec:
            return sum(max(self._groups[g].logical_len, 0)
                       for g in fn.blocks if g in self._groups)
        return sum(max(self._blocks[b].length, 0)
                   for b in fn.blocks if b in self._blocks)

    @staticmethod
    def _iter_dirs(node: Any):
        if isinstance(node, dict):
            for child in node.values():
                if isinstance(child, dict):
                    yield child
                    yield from NameNode._iter_dirs(child)

    def _quota_roots_of(self, path: str) -> list[tuple[str, tuple[int, int]]]:
        parts = self._parts(path)
        out = []
        for i in range(len(parts)):
            p = "/" + "/".join(parts[:i + 1])
            if p in self._quotas:
                out.append((p, self._quotas[p]))
        return out

    def _usage(self, root: str) -> list:
        """[namespace entries incl. the root dir, completed logical bytes],
        cached; recomputed only after structural mutations."""
        u = self._qusage.get(root)
        if u is None:
            node = self._try_dir(root)
            if node is None:
                u = [0, 0]
            else:
                files = list(self._iter_files(node))
                u = [1 + len(files) + sum(1 for _ in self._iter_dirs(node)),
                     sum(self._file_len(fn) for fn in files)]
            self._qusage[root] = u
        return u

    def _check_ns_quota(self, path: str) -> None:
        """One new namespace entry at ``path``: every enclosing quota dir
        must have headroom (QuotaExceededException analog; HDFS semantics —
        the quota'd directory itself counts)."""
        for p, (ns_q, _) in self._quota_roots_of(path):
            if ns_q < 0:
                continue
            count = self._usage(p)[0]
            if count + 1 > ns_q:
                raise OSError(f"namespace quota of {p} exceeded: "
                              f"{count}+1 > {ns_q}")

    def _check_space_quota(self, path: str, additional: int) -> None:
        for p, (_, sp_q) in self._quota_roots_of(path):
            if sp_q < 0:
                continue
            used = self._usage(p)[1] + self._pending_space.get(p, 0)
            if used + additional > sp_q:
                raise OSError(f"space quota of {p} exceeded: "
                              f"{used}+{additional} > {sp_q}")

    def _try_dir(self, path: str) -> Any | None:
        try:
            node = self._resolve(path)
            return node if isinstance(node, dict) else None
        except (FileNotFoundError, NotADirectoryError):
            return None

    # --------------------------------------------------- datanode RPC: control

    def rpc_register_datanode(self, dn_id: str, addr: list,
                              sc_path: str | None = None,
                              rack: str = "/default-rack",
                              storage_type: str = "DISK",
                              storage_types: list | None = None) -> dict:
        with self._lock:
            self._datanodes[dn_id] = DatanodeInfo(
                dn_id, (addr[0], addr[1]), last_heartbeat=time.monotonic(),
                sc_path=sc_path, rack=rack, storage_type=storage_type,
                storage_types=tuple(storage_types or [storage_type]))
            _M.incr("dn_registered")
            self._logger.info("datanode registered", dn_id=dn_id,
                           addr=f"{addr[0]}:{addr[1]}", rack=rack)
            keys = None
            if self._tokens is not None:
                # keys ship WITH registration (the reference's
                # DatanodeRegistration carries ExportedBlockKeys) — a DN must
                # be able to verify tokens before its first heartbeat
                self._tokens.maybe_roll()
                keys = self._tokens.keys()
            return {"heartbeat_interval_s": self.config.heartbeat_interval_s,
                    "block_keys": keys,
                    # block-pool identity (federation): the DN partitions
                    # its reports/IBRs per nameservice by this id range
                    "nameservice_id": self.config.nameservice_id,
                    "block_pool_index": self.config.block_pool_index}

    def rpc_lifeline(self, dn_id: str) -> dict:
        """DatanodeLifelineProtocol analog: touch ONLY the liveness clock.
        No stats, no commands, no key rolls — the whole point is staying
        cheap while the DN (or this NN) is too loaded for full
        heartbeats, so an overloaded-but-alive node is not declared dead
        and mass re-replicated."""
        with self._lock:
            dn = self._datanodes.get(dn_id)
            if dn is None:
                return {"reregister": True}
            dn.last_heartbeat = time.monotonic()
            _M.incr("lifelines")
            return {}

    def rpc_heartbeat(self, dn_id: str, stats: dict | None = None) -> dict:
        with self._lock:
            dn = self._datanodes.get(dn_id)
            if dn is None:
                return {"reregister": True, "commands": []}
            dn.last_heartbeat = time.monotonic()
            dn.stats = stats or {}
            if "cached_blocks" in dn.stats:
                dn.cached = set(dn.stats["cached_blocks"])
            if "ec" in dn.stats:
                self._refresh_stripe_groups(dn_id, dn.stats["ec"])
            # refresh health intelligence on every stats delivery so the
            # slow-peer/slow-volume gauges are never older than one
            # heartbeat interval (SlowPeerTracker's report-driven update)
            self._health_report()
            keys = None
            if self._tokens is not None:
                self._tokens.maybe_roll()
                keys = self._tokens.keys()
            if self.role != "active":  # standby never commands DNs
                return {"reregister": False, "commands": [],
                        "role": self.role, "block_keys": keys}
            cmds, dn.commands = dn.commands, []
            return {"reregister": False, "commands": cmds,
                    "role": self.role, "block_keys": keys}

    def rpc_block_report(self, dn_id: str, blocks: list) -> bool:
        """Full report: authoritative sync of this DN's replica set
        (BlockManager.processReport analog)."""
        with self._lock:
            dn = self._datanodes.get(dn_id)
            if dn is None:
                raise KeyError(f"unregistered datanode {dn_id}")
            reported = set()
            for row in blocks:
                # rows are (bid, gs, len) or (bid, gs, len, storage_type) —
                # multi-volume DNs report each replica's volume type
                # (per-storage reports, DatanodeStorageInfo analog)
                bid, gs, length = row[0], row[1], row[2]
                if bid >> 48 != self.config.block_pool_index:
                    continue  # another nameservice's pool: not ours to
                    # track OR to invalidate (federation guard)
                stype = row[3] if len(row) > 3 else None
                info = self._blocks.get(bid)
                if stype is not None and info is not None:
                    info.storage_of[dn_id] = stype
                if info is None:
                    # replica for a deleted file: drop it (only the active
                    # may command — a lagging standby would invalidate
                    # replicas it just hasn't heard about yet)
                    if self.role == "active":
                        dn.commands.append({"cmd": "invalidate",
                                            "block_ids": [bid]})
                    continue
                if gs >= info.gen_stamp:
                    if 0 <= length < info.length:
                        # a SHORT replica of a completed block cannot serve
                        # it (corrupt-on-length-mismatch, BlockManager
                        # semantics).  With healthy copies elsewhere it is
                        # invalidated outright — left in `reported` it would
                        # later act as a length candidate in lease recovery
                        # and min-sync healthy replicas down to it.  Only
                        # while it is the block's last copy is it preserved.
                        others = {d for d in info.locations
                                  if d in self._datanodes} - {dn_id}
                        if others:
                            if self.role == "active":
                                dn.commands.append({"cmd": "invalidate",
                                                    "block_ids": [bid]})
                            info.reported.pop(dn_id, None)
                            info.storage_of.pop(dn_id, None)
                            info.locations.discard(dn_id)
                        else:
                            reported.add(bid)
                            info.reported[dn_id] = (gs, length)
                            info.locations.discard(dn_id)
                        continue
                    reported.add(bid)
                    info.reported[dn_id] = (gs, length)
                    info.locations.add(dn_id)
                    continue
                # Stale generation (append/recovery supersede).  NEVER
                # destroy it while the block is under construction or it is
                # the only live copy — a client crash right after an append's
                # bump_block would otherwise let the NN invalidate every
                # old-generation replica before any new-generation byte
                # lands (silent data loss); lease recovery restamps the
                # survivors instead (commitBlockSynchronization semantics).
                others = {d for d in info.locations
                          if d in self._datanodes} - {dn_id}
                if info.length < 0 or not others:
                    reported.add(bid)
                    info.reported[dn_id] = (gs, length)
                    # kept alive but NOT in locations: a stale replica must
                    # not serve reads of the superseded block
                    info.locations.discard(dn_id)
                else:
                    if self.role == "active":
                        dn.commands.append({"cmd": "invalidate",
                                            "block_ids": [bid]})
                    info.reported.pop(dn_id, None)
                    info.storage_of.pop(dn_id, None)
                    info.locations.discard(dn_id)
            for bid in dn.blocks - reported:
                info = self._blocks.get(bid)
                if info:
                    info.locations.discard(dn_id)
                    info.reported.pop(dn_id, None)
                    info.storage_of.pop(dn_id, None)
            dn.blocks = reported
            _M.incr("block_reports")
            return True

    def rpc_block_received(self, dn_id: str, block_id: int, length: int,
                           gen_stamp: int = -1,
                           storage_type: str | None = None,
                           partial: bool = False) -> bool:
        """Incremental block report on pipeline finalize (IBR analog).

        An IBR records the replica but never fixes a UC block's length:
        first-reporter-wins would let the file complete at whatever length
        that one replica has, violating the min-CRC-verified-prefix
        invariant lease recovery guarantees — only ``complete`` and
        ``commit_block_sync`` resolve lengths.

        ``partial=True`` reports a coded mirror SEGMENT (a k-of-n slice
        of the reduced payload, server/mirror_plane.py), not a replica:
        it is tracked in ``_partial_replicas`` — never ``info.locations``,
        never ``info.reported`` (segment lengths would poison lease
        recovery) — until the reconciliation monitor upgrades the holder
        to a full replica and a normal IBR clears the partial entry."""
        with self._lock:
            if block_id >> 48 != self.config.block_pool_index:
                return False   # another nameservice's pool (federation)
            dn = self._datanodes.get(dn_id)
            info = self._blocks.get(block_id)
            if dn is None:
                return False
            if partial:
                if info is None:
                    return False
                self._partial_replicas.setdefault(block_id, {}).setdefault(
                    dn_id, time.monotonic())
                _M.incr("partial_replicas_reported")
                return True
            if info is None:
                if self.role == "standby":
                    # IBR raced ahead of the journal tail: queue it (the
                    # reference's PendingDataNodeMessages on the standby)
                    self._pending_ibr.setdefault(block_id, []).append(
                        (dn_id, length, gen_stamp, storage_type))
                    if len(self._pending_ibr) > 100_000:
                        self._pending_ibr.pop(next(iter(self._pending_ibr)))
                return False
            if 0 <= gen_stamp < info.gen_stamp:
                # a superseded pipeline finalizing late (fenced by the
                # append/recovery gen-stamp bump): keep the bytes visible to
                # recovery, but never serve the stale generation
                info.reported[dn_id] = (gen_stamp, length)
                return False
            dn.blocks.add(block_id)
            if storage_type is not None:
                # PROVIDED arrives here too (alias_add IBRs), so the
                # replication monitor's shared-storage accounting never
                # sees a provided replica as a local disk copy in the
                # window before the next full block report.
                info.storage_of[dn_id] = storage_type
            info.reported[dn_id] = (
                gen_stamp if gen_stamp >= 0 else info.gen_stamp, length)
            if 0 <= length < info.length:
                # short replica of a completed block: cannot serve it
                info.locations.discard(dn_id)
            else:
                info.locations.add(dn_id)
            pr = self._partial_replicas.get(block_id)
            if pr is not None and pr.pop(dn_id, None) is not None:
                # a segment holder finished reconciling into a full replica
                _M.incr("partial_upgrades")
                if not pr:
                    self._partial_replicas.pop(block_id, None)
                    self._pending_partial.pop(block_id, None)
            return True

    def _charge_alloc(self, path: str, bid: int, size: int) -> None:
        """Conservative full-block space charge at allocation time (HDFS does
        the same): async IBRs would otherwise let back-to-back add_block
        calls race past the quota."""
        if not self._quotas:
            return
        self._alloc_charge[bid] = (path, size)
        for r, _ in self._quota_roots_of(path):
            self._pending_space[r] = self._pending_space.get(r, 0) + size

    def _uncharge_alloc(self, bid: int) -> None:
        ch = self._alloc_charge.pop(bid, None)
        if ch is None:
            return
        path, size = ch
        for r, _ in self._quota_roots_of(path):
            left = self._pending_space.get(r, 0) - size
            if left > 0:
                self._pending_space[r] = left
            else:
                self._pending_space.pop(r, None)

    def _drain_pending_ibr(self) -> None:
        """Apply queued IBRs whose blocks the journal tail has now created."""
        for bid in [b for b in self._pending_ibr if b in self._blocks]:
            for dn_id, length, gen_stamp, stype in self._pending_ibr.pop(bid):
                info = self._blocks[bid]
                dn = self._datanodes.get(dn_id)
                if dn is not None:
                    info.reported[dn_id] = (
                        gen_stamp if gen_stamp >= 0 else info.gen_stamp,
                        length)
                    if not (0 <= gen_stamp < info.gen_stamp):
                        dn.blocks.add(bid)
                        if stype is not None:
                            info.storage_of[dn_id] = stype
                        # same short-replica guard as rpc_block_received:
                        # the tailed batch may have completed the block
                        if not 0 <= length < info.length:
                            info.locations.add(dn_id)

    # ------------------------------------------------------------- admin RPC

    def rpc_provide_file(self, path: str, uri: str, length: int) -> dict:
        """Register a PROVIDED file: a complete namespace entry whose
        blocks' bytes live in an external store (the provided-storage
        half of aliasmap/InMemoryAliasMapProtocol; the reference builds
        this mapping offline with the fsimage image-writer).  Returns the
        FileRegions the caller pushes to DataNodes (``alias_add``), which
        then report PROVIDED replicas.  Superuser-only."""
        with self._lock:
            self._check_access(path, super_only=True)
            if length < 0:
                raise ValueError("length must be >= 0")
            bs = self.config.block_size
            nblocks = max(-(-length // bs), 1) if length else 0
            bids = list(range(self._next_block_id,
                              self._next_block_id + nblocks))
            self._log(["provide", path, uri, length, bids, time.time()])
            _M.incr("provided_files")
            return {"regions": [
                [bid, uri, i * bs, min(bs, length - i * bs)]
                for i, bid in enumerate(bids)],
                # per-region WRITE tokens gate the DN-side alias_add push
                "tokens": ([self._tokens.mint(bid, "w") for bid in bids]
                           if self._tokens else None)}

    def rpc_set_balancer_bandwidth(self, bytes_per_s: int) -> int:
        """Broadcast a background-transfer bandwidth cap to every DataNode
        via its next heartbeat (DFSAdmin setBalancerBandwidth ->
        BalancerBandwidthCommand).  Returns the number of DNs queued."""
        with self._lock:
            self._check_access("/", super_only=True)
            for d in self._datanodes.values():
                d.commands.append({"cmd": "balancer_bandwidth",
                                   "bytes_per_s": int(bytes_per_s)})
            _M.incr("set_balancer_bandwidth")
            return len(self._datanodes)

    def rpc_datanode_report(self) -> list[dict]:
        with self._lock:
            now = time.monotonic()
            return [{"dn_id": d.dn_id, "addr": list(d.addr),
                     "alive": now - d.last_heartbeat < self.config.dead_node_interval_s,
                     "blocks": len(d.blocks), "stats": d.stats}
                    for d in self._datanodes.values()]

    def rpc_cluster_status(self) -> dict:
        """Cluster overview backing the dfshealth web UI — the aggregate
        fields of the reference's webapps/hdfs/dfshealth.html and
        NameNodeMXBean (capacity, DN liveness buckets, block totals,
        safemode, journal wiring)."""
        with self._lock:
            now = time.monotonic()
            live = dead = decom = 0
            logical = physical = cached = 0
            ded_logical = ded_unique = 0
            ec_striped = ec_logical = ec_physical = 0
            scrub_corrupt = scrub_garbage = scrub_repairs = 0
            qos_sheds = 0
            for d in self._datanodes.values():
                alive = (now - d.last_heartbeat
                         < self.config.dead_node_interval_s)
                if d.dn_id in self._decommissioning:
                    decom += 1
                elif alive:
                    live += 1
                else:
                    dead += 1
                st = d.stats or {}
                logical += int(st.get("logical_bytes", 0))
                physical += int(st.get("physical_bytes", 0))
                cached += int(st.get("cache_used", 0))
                idx = st.get("index") or {}
                ded_logical += int(idx.get("logical_bytes", 0))
                ded_unique += int(idx.get("unique_chunk_bytes", 0))
                ec = st.get("ec") or {}
                ec_striped += int(ec.get("striped_containers", 0))
                ec_logical += int(ec.get("stripe_logical_bytes", 0))
                ec_physical += int(ec.get("stripe_physical_bytes", 0))
                sc = st.get("scrub") or {}
                scrub_corrupt += int(sc.get("corrupt_total", 0))
                scrub_garbage += int(sc.get("garbage_bytes", 0))
                scrub_repairs += int(sc.get("repairs_triggered", 0))
                qo = st.get("qos") or {}
                qos_sheds += int(qo.get("sheds_total", 0))
            # The under-replicated count is the redundancy monitor's own
            # (cached each _check_replication tick) — recomputing it here
            # would both duplicate the want/counted semantics and walk
            # every block under the namesystem lock per page load.
            under = self._under_replicated
            health = self._health_report()
            from hdrf_tpu.reduction import accounting as _acc

            return {
                "role": self.role,
                "safemode": self._in_safemode(),
                "blocks": len(self._blocks),
                "under_replicated": under,
                "pending_replication": len(self._pending_repl),
                "live": live, "dead": dead, "decommissioning": decom,
                "logical_bytes": logical, "physical_bytes": physical,
                "cache_used": cached,
                # cluster-wide reduction effectiveness: the chunk-index
                # aggregates every DN ships in its heartbeat, summed —
                # exactly the recompute-from-index ground truth
                "dedup_logical_bytes": ded_logical,
                "dedup_unique_bytes": ded_unique,
                "dedup_ratio": _acc.dedup_ratio(ded_logical, ded_unique),
                # EC cold tier: demoted census + stripe-tier footprint
                # (the dfshealth page's "storage ratio" row pairs this
                # against the replicated tier's factor)
                "ec_demoted_blocks": len(self._ec_demoted),
                "striped_containers": ec_striped,
                "stripe_logical_bytes": ec_logical,
                "stripe_physical_bytes": ec_physical,
                # coded mirror plane: segment holders awaiting upgrade to
                # full replicas (the reconciliation monitor's backlog)
                "partial_replicas": sum(
                    len(v) for v in self._partial_replicas.values()),
                "slow_peers": len(health["slow_peers"]),
                "slow_volumes": len(health["slow_volumes"]),
                "reduction_degraded": len(health["degraded_nodes"]),
                "degraded_nodes": health["degraded_nodes"],
                # integrity plane: DN heartbeat scrub aggregates + the
                # cached invariant-census verdict (the /health gateway
                # extends its degraded expression with these)
                "scrub_corrupt_total": scrub_corrupt,
                "garbage_bytes": scrub_garbage,
                "scrub_repairs_triggered": scrub_repairs,
                # overload plane (ISSUE 14): cluster-wide admission sheds
                # from DN heartbeats — intentional refusals under overload,
                # NOT a degraded-verdict input (shedding is the system
                # working; breakers/deadline failures flag separately)
                "qos_sheds_total": qos_sheds,
                "fsck_violations": (self._last_fsck or {}).get(
                    "violations", 0),
                "editlog_seq": self._editlog.seq,
                "journal_addrs": [list(a) for a in
                                  (self.config.journal_addrs or [])],
            }

    def _refresh_stripe_groups(self, dn_id: str, ec: dict) -> None:
        """Rebuild this owner's slice of the soft-state stripe-group cache
        from its heartbeat manifest report (the WAL-durable copy is the
        owner DN's chunk index; an NN restart or failover re-learns every
        group within one heartbeat).  Caller holds self._lock."""
        reported = {}
        for cid_s, g in (ec.get("manifests") or {}).items():
            reported[int(cid_s)] = {
                "holders": [list(h) for h in g["holders"]],
                "length": int(g.get("length", 0))}
        for cid, grp in reported.items():
            cur = self._stripe_groups.get((dn_id, cid))
            grp["block_id"] = cur.get("block_id") if cur else None
            self._stripe_groups[(dn_id, cid)] = grp
        for key in [kk for kk in self._stripe_groups
                    if kk[0] == dn_id and kk[1] not in reported]:
            # owner dropped the manifest (container deleted/promoted)
            del self._stripe_groups[key]
            self._pending_stripe_repair.pop(key, None)
            self._corrupt_stripes.pop(key, None)

    def rpc_stripe_complete(self, dn_id: str, block_id=None,
                            containers: list | None = None,
                            owner: str | None = None) -> bool:
        """Owner-DN report closing a stripe demotion (or refreshing holder
        maps after a repair): journal the block's demotion (``ec_demote``
        edit — from here the redundancy monitor wants ONE full replica),
        invalidate the other full replicas, and cache the stripe groups
        for the repair scheduler.  ``owner`` keys the groups when a
        deputized agent reports a dead owner's repair — the stripes (and
        the group identity) keep the original owner's name.  First
        accepting NN wins — a standby refuses, the same contract as
        commit_block_sync."""
        with self._lock:
            if self.role != "active":
                raise StandbyError("namenode is standby")
            own = owner or dn_id
            # full stripe manifests riding the report become editlog/fsimage
            # durable (owner-loss repair input — the owner's WAL copy dies
            # with the owner); repairs re-journal so holders stay current.
            # Journal BEFORE touching the soft group cache: if _log raises
            # (safemode right after a restart, a standby demotion), a cache
            # already showing the repaired holders would tell the repair
            # monitor "missing = []" forever while the durable manifests
            # still name the dead DNs — the report must fail atomically so
            # the DN-side repair gets re-scheduled and re-reported.
            manifests = {str(int(c["cid"])): c["manifest"]
                         for c in containers or [] if c.get("manifest")}

            def _cache_groups() -> None:
                for c in containers or []:
                    key = (own, int(c["cid"]))
                    self._stripe_groups[key] = {
                        "holders": [list(h) for h in c["holders"]],
                        "length": int(c.get("logical", 0)),
                        "block_id": block_id}
                    self._pending_stripe_repair.pop(key, None)
                    self._corrupt_stripes.pop(key, None)

            if block_id is None:
                # repair of an unmapped group: re-journal + cache manifests
                if manifests:
                    self._log(["ec_demote", None, own, manifests])
                    _M.incr("stripe_manifests_journaled")
                _cache_groups()
                return True
            bid = int(block_id)
            info = self._blocks.get(bid)
            if info is None:
                self._pending_demote.pop(bid, None)
                return True
            if bid not in self._ec_demoted:
                self._log(["ec_demote", bid, own, manifests])
                _M.incr("blocks_ec_demoted")
                if manifests:
                    _M.incr("stripe_manifests_journaled")
            elif manifests:
                self._log(["ec_demote", None, own, manifests])
                _M.incr("stripe_manifests_journaled")
            _cache_groups()
            self._pending_demote.pop(bid, None)
            if own != dn_id:
                # deputized-agent report: the agent holds no full replica,
                # so the single-holder invalidation below must not run
                return True
            # the owner is now the single full-replica holder; the other
            # copies are excess (redundancy rides the stripes)
            for d in sorted(info.locations - {dn_id}):
                other = self._datanodes.get(d)
                if other is not None:
                    other.commands.append({"cmd": "invalidate",
                                           "block_ids": [bid]})
                    other.blocks.discard(bid)
                info.reported.pop(d, None)
                info.storage_of.pop(d, None)
            info.locations &= {dn_id}
            self._pending_repl.pop(bid, None)
            return True

    def rpc_ec_status(self) -> dict:
        """Cold-tier census backing ``dfsadmin -ecStatus`` and the
        gateway's /status and /health EC rows: striped vs replicated
        container counts, the tier's physical/logical expansion (~(k+m)/k)
        against the replicated tier's factor, and the schedulers' queue
        depths — aggregated from the DNs' heartbeat ``ec`` stats."""
        from hdrf_tpu.reduction import accounting as _acc

        with self._lock:
            striped = sealed = 0
            logical = physical = 0
            for d in self._datanodes.values():
                ec = (d.stats or {}).get("ec") or {}
                striped += int(ec.get("striped_containers", 0))
                logical += int(ec.get("stripe_logical_bytes", 0))
                physical += int(ec.get("stripe_physical_bytes", 0))
                idx = (d.stats or {}).get("index") or {}
                sealed += int(idx.get("sealed_containers", 0))
            return {
                "policy": (f"rs-{self.config.ec_data_shards}"
                           f"-{self.config.ec_parity_shards}"),
                "demote_after_s": self.config.ec_demote_after_s,
                "demoted_blocks": len(self._ec_demoted),
                "pending_demotions": len(self._pending_demote),
                "pending_stripe_repairs": len(self._pending_stripe_repair),
                "stripe_groups": len(self._stripe_groups),
                "striped_containers": striped,
                "replicated_containers": max(0, sealed - striped),
                "stripe_logical_bytes": logical,
                "stripe_physical_bytes": physical,
                "storage_ratio_striped": _acc.stripe_ratio(logical,
                                                           physical),
                "storage_ratio_replicated": float(self.config.replication),
            }

    def _fsck_census(self) -> dict:
        """Invariant reconciliation over the whole namesystem (NamenodeFsck
        analog, §blockIdCK): block map vs live DN membership, reported
        replica lengths, stripe-group decodability, and partial-replica
        coverage.  Caller holds ``self._lock``.  Classes:

        - ``missing``: a COMPLETE block with zero live full replicas and no
          other byte source (no partial mirror segments awaiting upgrade,
          no stripe demotion, not an EC-group internal block).
        - ``extra``: a DN claims a block the map no longer knows (missed
          invalidation — the reference's invalidateBlocks backlog).
        - ``length_mismatch``: a live current-generation replica reports a
          length different from the committed block length (the torn-
          finalize class the shadow-block design stopped checking).
        - ``unrepairable_stripe``: a stripe group (or EC block group) with
          fewer than k intact+live members — any-k decode is dead and only
          re-replication from outside sources could help.
        """
        now = time.monotonic()
        dead_after = self.config.dead_node_interval_s

        def _alive(dn_id: str) -> bool:
            d = self._datanodes.get(dn_id)
            return (d is not None
                    and now - d.last_heartbeat < dead_after)

        ec_bids = {b for g in self._groups.values() for b in g.bids}
        striped_bids = {g.get("block_id")
                        for g in self._stripe_groups.values()}
        missing: list[int] = []
        length_mismatch: list[int] = []
        partial_covered = 0
        for bid, info in self._blocks.items():
            node = self._try_file(info.path)
            if node is None or not node.complete:
                continue
            live = {d for d in info.locations if _alive(d)}
            if not live and bid not in ec_bids:
                if self._partial_replicas.get(bid):
                    partial_covered += 1  # upgrade monitor's problem
                elif not (bid in self._ec_demoted and bid in striped_bids):
                    missing.append(bid)
            if info.length >= 0:
                for d in live:
                    rep = info.reported.get(d)
                    if (rep is not None and rep[0] == info.gen_stamp
                            and rep[1] != info.length):
                        length_mismatch.append(bid)
                        break
        extra: list[int] = []
        for d in self._datanodes.values():
            if not _alive(d.dn_id):
                continue
            for bid in d.blocks:
                if bid not in self._blocks:
                    extra.append(bid)
        unrepairable: list[list] = []
        for (owner, cid), grp in self._stripe_groups.items():
            man = self._stripe_manifests.get((owner, cid)) or {}
            k = int(man.get("k", self.config.ec_data_shards))
            corrupt = self._corrupt_stripes.get((owner, cid), set())
            intact = sum(1 for i, h in enumerate(grp["holders"])
                         if i not in corrupt and _alive(h[0]))
            if intact < k:
                unrepairable.append([owner, cid])
        for gid, g in self._groups.items():
            k = self.config.ec_data_shards
            live_members = sum(
                1 for b in g.bids
                if any(_alive(d)
                       for d in (self._blocks.get(b).locations
                                 if self._blocks.get(b) else ())))
            if live_members < k:
                unrepairable.append(["ec_group", gid])
        classes = {"missing": sorted(missing),
                   "extra": sorted(set(extra)),
                   "length_mismatch": sorted(length_mismatch),
                   "unrepairable_stripe": sorted(unrepairable)}
        counts = {c: len(v) for c, v in classes.items()}
        violations = sum(counts.values())
        return {
            "healthy": violations == 0,
            "violations": violations,
            "counts": counts,
            # per-class ids, capped so a mass-failure fsck stays shippable
            # over the RPC (the counts above are exact)
            **{c: v[:50] for c, v in classes.items()},
            "blocks_checked": len(self._blocks),
            "partial_covered": partial_covered,
            "corrupt_stripes_pending": sum(
                len(v) for v in self._corrupt_stripes.values()),
        }

    def rpc_fsck(self) -> dict:
        """dfsadmin -fsck / gateway /fsck: run the invariant census NOW and
        return the verdict (also refreshing the cached copy /health and
        cluster_status read)."""
        with self._lock:
            census = self._fsck_census()
            self._last_fsck = census
            return census

    def _check_fsck(self) -> None:
        """Monitor pass: refresh the invariant census each tick and export
        the violation gauges (the fsck analog of _check_replication's
        cached under-replication count)."""
        with self._lock:
            census = self._fsck_census()
            self._last_fsck = census
            _M.gauge("fsck_violations", census["violations"])
            for cls, n in census["counts"].items():
                _M.gauge(f"fsck_{cls}", n)

    def rpc_finalize_upgrade(self) -> dict:
        """dfsadmin -finalizeUpgrade: drop this NameNode's rollback
        snapshot and queue a finalize command to every DataNode (the
        reference propagates finalization through heartbeat responses)."""
        from hdrf_tpu.storage import version as storage_version

        with self._lock:
            self._check_access("/", super_only=True)
            nn = storage_version.finalize_upgrade(self.config.meta_dir)
            queued = 0
            for d in self._datanodes.values():
                d.commands.append({"cmd": "finalize_upgrade"})
                queued += 1
            return {"namenode_finalized": nn, "datanodes_queued": queued}

    def rpc_save_namespace(self) -> bool:
        with self._lock:
            self._check_access("/", super_only=True)
            if self.role != "active":
                raise StandbyError("namenode is standby")
            self._editlog.checkpoint()
            return True

    def rpc_bad_block(self, dn_id: str, block_id: int) -> bool:
        """A DN's scanner found a corrupt replica: drop the location so the
        redundancy monitor re-replicates from a good copy
        (BlockManager.markBlockAsCorrupt analog)."""
        with self._lock:
            info = self._blocks.get(block_id)
            dn = self._datanodes.get(dn_id)
            if info is None:
                return False
            info.locations.discard(dn_id)
            info.reported.pop(dn_id, None)
            info.storage_of.pop(dn_id, None)
            if dn is not None:
                dn.blocks.discard(block_id)
            self._pending_repl.pop(block_id, None)  # reschedule immediately
            _M.incr("corrupt_replicas_reported")
            self._logger.warning("corrupt replica reported", dn_id=dn_id,
                              block_id=block_id)
            return True

    def rpc_bad_stripe(self, dn_id: str, owner: str, cid: int,
                       idx: int) -> bool:
        """A DN's scrubber found a corrupt EC stripe it does NOT own (no
        local manifest to repair against): record the index so the stripe-
        repair monitor schedules the owner's any-k re-decode — the
        markBlockAsCorrupt path applied to the cold tier's stripes."""
        with self._lock:
            key = (owner, int(cid))
            self._corrupt_stripes.setdefault(key, set()).add(int(idx))
            # clear the repair backoff: a corruption report should not
            # wait out a prior schedule's deadline
            self._pending_stripe_repair.pop(key, None)
            _M.incr("corrupt_stripes_reported")
            self._logger.warning("corrupt stripe reported", dn_id=dn_id,
                                 owner=owner, cid=int(cid), idx=int(idx))
            return True

    def rpc_datanode_blocks(self, dn_id: str, limit: int = 100) -> list[int]:
        """Balancer support: a sample of non-EC block ids hosted by ``dn_id``
        that have at least one other live replica source."""
        with self._lock:
            dn = self._datanodes.get(dn_id)
            if dn is None:
                return []
            ec_bids = {b for g in self._groups.values() for b in g.bids}
            out = []
            for bid in dn.blocks:
                if bid in ec_bids or bid in self._pending_moves:
                    continue
                out.append(bid)
                if len(out) >= limit:
                    break
            return out

    def rpc_move_block(self, block_id: int, from_dn: str, to_dn: str) -> bool:
        """Balancer support: copy a replica to ``to_dn`` (reduced-form push),
        then invalidate on ``from_dn`` once the new location reports in
        (the Dispatcher/replaceBlock analog of the reference's Balancer)."""
        with self._lock:
            if self.role != "active":
                raise StandbyError("namenode is standby")
            info = self._blocks.get(block_id)
            src = self._datanodes.get(from_dn)
            dst = self._datanodes.get(to_dn)
            if info is None or src is None or dst is None:
                return False
            if from_dn not in info.locations or to_dn in info.locations:
                return False
            src.commands.append({
                "cmd": "replicate", "block_id": block_id,
                "gen_stamp": info.gen_stamp,
                "targets": [{"dn_id": dst.dn_id, "addr": list(dst.addr)}]})
            self._pending_moves[block_id] = {
                "from": from_dn, "to": to_dn,
                "deadline": time.monotonic() + self.MOVE_TIMEOUT_S}
            return True

    MOVE_TIMEOUT_S = 120.0  # abandon a move whose target never reports

    def _settle_moves(self) -> None:
        """Finish balancer moves: only when the REQUESTED target has reported
        its copy does the source replica get invalidated — "some other
        replica exists" is not enough, since with replication>=2 that would
        drop redundancy below target the moment the command is queued.
        A move whose target never shows up is abandoned at its deadline (the
        source replica simply stays where it was)."""
        with self._lock:
            now = time.monotonic()
            for bid, mv in list(self._pending_moves.items()):
                info = self._blocks.get(bid)
                if info is None or mv["from"] not in info.locations:
                    self._pending_moves.pop(bid)
                    continue
                if mv["to"] in info.locations and mv["to"] in self._datanodes:
                    dn = self._datanodes.get(mv["from"])
                    if dn is not None:
                        dn.commands.append({"cmd": "invalidate",
                                            "block_ids": [bid]})
                    info.locations.discard(mv["from"])
                    self._pending_moves.pop(bid)
                elif now > mv["deadline"]:
                    self._pending_moves.pop(bid)

    def rpc_metrics(self) -> dict:
        return metrics.all_snapshots()

    def rpc_contention(self) -> dict:
        """Control-plane contention observatory (ISSUE 18): the RPC
        server's per-method service table (calls, p99, phase means,
        attribution) merged with the instrumented namesystem lock's books
        — each method row gains its share of total lock hold time.  Served
        as ``/contention`` on the NN status server and the gateway, and as
        ``dfsadmin -contention``."""
        out = self._rpc.contention_summary()
        lock = self._lock.contention_summary()
        out["lock"] = lock
        by_method = lock["by_method"]
        for m, row in out["methods"].items():
            lk = by_method.get(m)
            row["lock_share"] = lk["hold_share"] if lk else 0.0
            row["lock_hold_s"] = lk["hold_s"] if lk else 0.0
        return out

    def _flight_sample(self) -> dict:
        """Cluster-level flight-recorder gauges: namespace size, replication
        backlogs, live DN population, safemode, per-tenant population and
        breaker states — the numbers an operator plots first."""
        with self._lock:
            now = time.monotonic()
            live = sum(1 for dn in self._datanodes.values()
                       if now - dn.last_heartbeat
                       < self.config.dead_node_interval_s)
            sample = {
                "blocks": len(self._blocks),
                "datanodes": len(self._datanodes),
                "datanodes_live": live,
                "under_replicated": self._under_replicated,
                "pending_replication": len(self._pending_repl),
                "pending_recovery": len(self._pending_recovery),
                "safemode": int(self._safemode_forced or self._safemode_auto),
                # integrity drift: the cached invariant-census verdict plus
                # the DN heartbeats' scrub aggregates, so corruption and
                # garbage growth show in the /timeseries regression table
                "fsck_violations": (self._last_fsck or {}).get(
                    "violations", 0),
                "garbage_bytes": sum(
                    int(((d.stats or {}).get("scrub") or {})
                        .get("garbage_bytes", 0))
                    for d in self._datanodes.values()),
                "scrub_corrupt_total": sum(
                    int(((d.stats or {}).get("scrub") or {})
                        .get("corrupt_total", 0))
                    for d in self._datanodes.values()),
            }
        states = [b.state for b in retry.all_breakers().values()]
        sample["breakers_open"] = sum(1 for s in states if s == "open")
        sample["tenant_count"] = tenants.tenant_count()
        # Metadata-plane latency health (ROADMAP item 2's axis): rolling
        # p99 over every RPC the server dispatched in the last window,
        # plus the namesystem lock's contention gauges — saturation,
        # rolling wait p99 and the hold p99 of the heaviest holders —
        # so a creeping lock convoy shows in /timeseries and slo_report
        # before it becomes an outage.
        sample["nn_rpc_p99_ms"] = self._rpc.rpc_p99_ms()
        sample["nn_lock_saturation"] = self._lock.saturation()
        sample["nn_lock_wait_p99_us"] = self._lock.wait_p99_us()
        for m, p99 in self._lock.top_methods(3):
            sample[f"nn_lock_hold_p99_us|method={m}"] = p99
        # Observer staleness (design decision 19): how far this replica's
        # applied txid trails the demand horizon, in seconds and txids —
        # the curve slo_report regresses on (REGRESS_UP observer_lag_s).
        if self.role != "active":
            sample["observer_lag_s"] = round(self._tail_lag_s(), 3)
            sample["observer_lag_txids"] = max(
                0, self._max_seen_sid - self._editlog.seq)
        return sample

    def rpc_flight_timeseries(self) -> dict:
        """The NN flight recorder's bounded ring, for the gateway's
        /timeseries endpoint (same pull model as rpc_trace_spans)."""
        return self.flight.snapshot()

    def rpc_flight_query(self, metric=None, since=None,
                         limit: int = 2048) -> dict:
        """Long-horizon flight query: ring + crash-safe archive merged,
        de-duplicated, ``metric``/``since`` filtered and tail-limited
        (utils/flight_archive.py query) — the restart-surviving sibling
        of rpc_flight_timeseries the gateway's cluster scope pulls."""
        return flight_archive.query(self.flight, self.flight_archive,
                                    metric=metric, since=since,
                                    limit=int(limit or 2048))

    def rpc_trace_spans(self) -> dict:
        """This process's finished spans + device-ledger events, for the
        gateway's cross-daemon /traces merge (the span-receiver pull model
        replacing the reference's HTrace push receivers)."""
        from hdrf_tpu.utils import device_ledger, profiler

        return {"daemon": "namenode",
                "spans": tracing.all_span_snapshots(),
                "ledger": device_ledger.events_snapshot(),
                "counters": profiler.counters_snapshot()}

    # Absolute slowness floor for the no-baseline rule: a peer whose median
    # downstream transfer is worse than 1 MB/s is pathological regardless of
    # what the rest of the cluster looks like (the reference's low-threshold
    # guard, OutlierDetector.lowThresholdMs, inverted to a floor).
    SLOW_PEER_FLOOR_S_PER_MB = 1.0
    # Same idea for disk probes: one write+read+unlink of a few bytes
    # taking a full second is a sick disk on any hardware.
    SLOW_VOLUME_FLOOR_S = 1.0

    def _health_report(self) -> dict:
        """Cluster health intelligence over the DN heartbeat telemetry
        (caller holds self._lock): per-peer pipeline-latency medians and
        per-volume disk-probe medians through the median+MAD outlier
        detector (utils/outlier.py — OutlierDetector.java:61-103 applied
        to both SlowPeerTracker and SlowDiskTracker populations), with
        the absolute floors covering tiny-population clusters where the
        MAD rule has no baseline.  Updates the /prom gauges as a side
        effect so exposition is never older than one heartbeat."""
        import statistics

        peers: dict[str, list[float]] = {}
        vols: dict[str, float] = {}
        mirror_failures: dict[str, int] = {}
        degraded: list[str] = []
        for dn in self._datanodes.values():
            st = dn.stats or {}
            for peer, rep in (st.get("peer_transfer") or {}).items():
                peers.setdefault(peer, []).append(float(rep[0]))
            for vid, v in (st.get("volumes") or {}).items():
                pm = v.get("probe_median_s")
                if pm is not None and not v.get("failed"):
                    vols[f"{dn.dn_id}:vol-{vid}"] = float(pm)
            # outright mirror failures per peer (block_receiver attribution
            # riding heartbeats): summed across reporters
            for peer, n in (st.get("mirror_failures") or {}).items():
                mirror_failures[peer] = mirror_failures.get(peer, 0) + int(n)
            # reduction_degraded: the DN's worker breaker is not closed —
            # writes succeed via passthrough but reduction is off
            if st.get("reduction_degraded"):
                degraded.append(dn.dn_id)
        peer_meds = {p: statistics.median(ms) for p, ms in peers.items()}
        slow_peers = outlier.detect(
            peer_meds, abs_floor=self.SLOW_PEER_FLOOR_S_PER_MB)
        # a peer with outright mirror failures is flagged even when its
        # latency median looks fine (broken beats slow) — within two
        # heartbeats of the failure: one to ship the count, one to read it
        for peer, n in mirror_failures.items():
            if peer not in slow_peers:
                slow_peers[peer] = {"rule": "mirror_failure"}
            slow_peers[peer]["mirror_failures"] = n
        slow_vols = outlier.detect(
            vols, abs_floor=self.SLOW_VOLUME_FLOOR_S)
        _M.gauge("slow_peer_count", len(slow_peers))
        _M.gauge("slow_volume_count", len(slow_vols))
        _M.gauge("reduction_degraded_count", len(degraded))
        return {"slow_peers": slow_peers,
                "slow_volumes": slow_vols,
                "peer_medians_s_per_mb": peer_meds,
                "volume_probe_medians_s": vols,
                "mirror_failures": mirror_failures,
                "degraded_nodes": sorted(degraded),
                "reporters": {p: len(ms) for p, ms in peers.items()}}

    def rpc_slow_nodes_report(self) -> dict:
        """Health-intelligence RPC backing ``dfsadmin -slowPeers`` and the
        gateway's /health endpoint: the outlier detector's verdict over the
        latest heartbeat telemetry, plus the raw medians it judged."""
        with self._lock:
            return self._health_report()

    def rpc_slow_peers(self) -> dict:
        """SlowPeerTracker.java:56 analog: aggregate the DNs' peer-latency
        reports (riding heartbeat stats) and flag outliers.  Two rules:

        - relative: a peer whose median reported latency exceeds 3x the
          median of OTHER peers' reports (the reference's outlier rule on
          the same reporter->peer structure);
        - absolute: when no other peer has reports (tiny cluster, skewed
          placement), unanimous multi-reporter slowness past an absolute
          floor still flags — the reference needs no cross-peer baseline
          (it detects outliers over the *reported* latencies).
        """
        import statistics

        with self._lock:
            reports: dict[str, list[float]] = {}
            for dn in self._datanodes.values():
                for peer, (med, _n) in (dn.stats.get("peer_transfer")
                                        or {}).items():
                    reports.setdefault(peer, []).append(float(med))
            if not reports:
                return {"cluster_median_s_per_mb": None, "slow_peers": {}}
            med_all = statistics.median(
                [m for ms in reports.values() for m in ms])
            floor = self.SLOW_PEER_FLOOR_S_PER_MB
            slow = {}
            for p, ms in reports.items():
                # relative baseline EXCLUDES the candidate's own reports — an
                # outlier must not inflate the median it is judged against
                others = [m for q, qs in reports.items() if q != p
                          for m in qs]
                base = statistics.median(others) if others else 0.0
                med_p = statistics.median(ms)
                flagged = base > 0 and med_p > 3 * base
                if not flagged and base == 0.0:
                    flagged = len(ms) >= 2 and med_p > floor
                if flagged:
                    slow[p] = {"median_s_per_mb": med_p,
                               "reporters": len(ms)}
            return {"cluster_median_s_per_mb": med_all,
                    "slow_peers": slow,
                    "reports": {p: len(ms) for p, ms in reports.items()}}

    # ---------------------------------------------------------- block mgmt

    # Storage policies (BlockStoragePolicySuite analog): preferred storage
    # type per replica index; fallback = any type when the preferred ones
    # are unavailable (the reference's policy fallback chain).
    STORAGE_POLICIES = {
        "hot": ["DISK"],
        "warm": ["DISK", "ARCHIVE"],    # first replica DISK, rest ARCHIVE
        "cold": ["ARCHIVE"],
        "all_ssd": ["SSD"],
        "one_ssd": ["SSD", "DISK"],
        "lazy_persist": ["RAM_DISK", "DISK"],
    }

    def _policy_of(self, path: str) -> str:
        """Effective storage policy: the nearest ancestor's explicit
        policy, default 'hot'."""
        node: Any = self._root
        policy = self._root.attrs.policy
        for p in [q for q in path.split("/") if q]:
            if not isinstance(node, dict):
                break
            node = node.get(p)
            if node is None:
                break
            a = getattr(node, "attrs", None)
            if a is not None and a.policy:
                policy = a.policy
        return policy or "hot"

    def _types_for(self, policy: str, n: int) -> list[str]:
        pref = self.STORAGE_POLICIES.get(policy, ["DISK"])
        return [pref[min(i, len(pref) - 1)] for i in range(n)]

    def _choose_targets(self, n: int, exclude: set[str],
                        policy: str | None = None,
                        slots: list | None = None) -> list[DatanodeInfo]:
        """Rack- and storage-policy-aware placement
        (BlockPlacementPolicyDefault-lite): per replica index the policy's
        preferred storage type is satisfied first, falling back to any
        live node; within a type class, round-robin across racks so
        replicas spread over failure domains before doubling up.  A
        multi-volume DN matches a type class if ANY of its volumes has
        that type.  ``slots`` (out-param) receives the storage type each
        chosen target was picked FOR, aligned with the returned list —
        the hint the write op carries so the receiving DN routes the
        replica to a matching volume."""
        now = time.monotonic()
        live = [d for d in self._datanodes.values()
                if now - d.last_heartbeat < self.config.dead_node_interval_s
                and d.dn_id not in exclude
                and d.dn_id not in self._decommissioning]
        random.shuffle(live)
        wanted_types = self._types_for(policy or "hot", n)

        def pick(pool: list[DatanodeInfo], k: int,
                 chosen: list[DatanodeInfo]) -> None:
            by_rack: dict[str, list[DatanodeInfo]] = {}
            used = {c.dn_id for c in chosen}
            for d in pool:
                if d.dn_id not in used:
                    by_rack.setdefault(d.rack, []).append(d)
            racks = list(by_rack.values())
            random.shuffle(racks)
            while k > 0 and any(racks):
                for r in racks:
                    if r and k > 0:
                        chosen.append(r.pop())
                        k -= 1

        out: list[DatanodeInfo] = []
        slot_of: dict[str, str] = {}
        # policy pass: fill each type class from matching nodes
        from collections import Counter

        for stype, count in Counter(wanted_types).items():
            before = len(out)
            pick([d for d in live if stype in d.storage_types], count, out)
            for d in out[before:]:
                slot_of[d.dn_id] = stype
        if len(out) < n:  # fallback chain: any live node
            pick(live, n - len(out), out)
        out = out[:n]
        if slots is not None:
            slots.extend(slot_of.get(d.dn_id, d.storage_type) for d in out)
        return out

    # -------------------------------------------------------------------- HA

    # -------------------------------------------------------------- safemode

    def _in_safemode(self) -> bool:
        if self._safemode_forced:
            return True
        if not self._safemode_auto:
            return False
        # auto safemode: leave once the reported fraction of known completed
        # blocks reaches the threshold (SafeModeInfo analog)
        total = known = 0
        dns = set(self._datanodes)
        for info in self._blocks.values():
            if info.length < 0:
                continue
            total += 1
            if info.locations & dns:
                known += 1
        if total == 0 or known / total >= self.config.safemode_threshold:
            self._safemode_auto = False
            _M.incr("safemode_left")
            return self._safemode_forced
        return True

    def _check_safemode(self) -> None:
        if self._in_safemode():
            raise OSError("NameNode is in safe mode")

    def rpc_safemode(self, action: str = "get") -> bool:
        """dfsadmin -safemode get|enter|leave|forceExit analog."""
        with self._lock:
            if action != "get":
                self._check_access("/", super_only=True)
            if action == "enter":
                self._safemode_forced = True
            elif action in ("leave", "forceExit"):
                self._safemode_forced = False
                self._safemode_auto = False
            return self._in_safemode()

    # ----------------------------------------------------------- decommission

    def rpc_decommission(self, dn_id: str) -> bool:
        """Begin draining a DN (DecommissionManager analog): it stays live
        for reads and as a re-replication source, is excluded from new
        placements, and its blocks are re-replicated elsewhere; poll
        rpc_decommission_status for completion, then stop the DN."""
        with self._lock:
            self._check_access("/", super_only=True)
            if dn_id not in self._datanodes:
                return False
            self._decommissioning.add(dn_id)
            self._save_decommissioning()
            _M.incr("decommissions_started")
            return True

    def rpc_recommission(self, dn_id: str) -> bool:
        """Return a drained (or repaired) DN to service — clears the exclude
        state so placement uses it again (refreshNodes-after-edit analog)."""
        with self._lock:
            self._check_access("/", super_only=True)
            if dn_id not in self._decommissioning:
                return False
            self._decommissioning.discard(dn_id)
            self._save_decommissioning()
            return True

    def _save_decommissioning(self) -> None:
        """The exclude set persists like the reference's hosts-exclude file:
        a sidecar in the (HA-shared) meta dir, so restarts and promoted
        standbys keep honoring an in-progress drain."""
        import json
        import os

        path = os.path.join(self.config.meta_dir, "decommissioning.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(sorted(self._decommissioning), f)
        os.replace(tmp, path)

    def _load_decommissioning(self) -> None:
        import json
        import os

        path = os.path.join(self.config.meta_dir, "decommissioning.json")
        try:
            with open(path) as f:
                self._decommissioning = set(json.load(f))
        except (FileNotFoundError, ValueError):
            self._decommissioning = set()

    def rpc_decommission_status(self, dn_id: str) -> dict:
        """'decommissioning' while blocks still need copies elsewhere;
        'decommissioned' when every hosted block is safe without this DN."""
        with self._lock:
            dn = self._datanodes.get(dn_id)
            if dn is None:
                return {"state": "dead", "remaining": 0}
            if dn_id not in self._decommissioning:
                return {"state": "normal", "remaining": 0}
            ec_bids = {b for g in self._groups.values() for b in g.bids}
            avail = sum(1 for d in self._datanodes
                        if d not in self._decommissioning)
            remaining = sum(1 for bid in dn.blocks
                            if not self._safe_without(bid, dn_id, ec_bids,
                                                      avail))
            return {"state": ("decommissioned" if remaining == 0
                              else "decommissioning"),
                    "remaining": remaining}

    def _safe_without(self, bid: int, dn_id: str, ec_bids: set[int],
                      avail: int) -> bool:
        info = self._blocks.get(bid)
        if info is None:
            return True
        node = self._try_file(info.path)
        want = node.replication if node else 1
        if bid in ec_bids:
            want = 1  # EC internal blocks carry one replica each
        # Cap by cluster capacity (DecommissionManager's isSufficient): a
        # 3-replica block on a 3-node cluster must not pin the drain forever.
        want = min(want, max(avail, 1))
        others = {d for d in info.locations
                  if d in self._datanodes and d != dn_id
                  and d not in self._decommissioning}
        return len(others) >= want

    # --------------------------------------------------------------- inotify

    def rpc_get_events(self, since_seq: int = 0, limit: int = 1000) -> dict:
        """Edit-event stream (hdfs/inotify analog — DFSInotifyEventInputStream
        over getEditsFromTxid): events after ``since_seq`` from the in-memory
        ring.  ``first_seq`` lets a slow consumer detect gaps (ring
        overwrote) and resync via a namespace listing."""
        with self._lock:
            evs = [e for e in self._events if e["seq"] > since_seq][:limit]
            return {"events": evs, "last_seq": self._editlog.seq,
                    "trimmed_through": self._events_trimmed}

    _EVENT_TYPES = {"create": "create", "complete": "close",
                    "delete": "unlink", "rename": "rename",
                    "mkdir": "mkdir", "setperm": "metadata",
                    "setowner": "metadata", "setacl": "metadata",
                    "setxattr": "metadata", "rmxattr": "metadata"}

    def _emit_event(self, rec: list) -> None:
        kind = self._EVENT_TYPES.get(rec[0])
        if kind is None:
            return
        ev = {"seq": self._editlog.seq, "type": kind, "path": rec[1],
              "time": time.time()}
        if kind == "rename":
            ev["dst"] = rec[2]
        self._events.append(ev)
        if len(self._events) > self._events_cap:
            drop = self._events_cap // 10
            self._events_trimmed = self._events[drop - 1]["seq"]
            del self._events[:drop]

    def rpc_ha_state(self) -> dict:
        return {"role": self.role, "seq": self._editlog.seq,
                "applied_txid": self._editlog.seq,
                "lag_s": round(self._tail_lag_s(), 3),
                "epoch": self._editlog.read_epoch()}

    # ------------------------------------------------- observer read plane

    # Wire methods an observer accepts besides _OBSERVER_READS: the DN
    # protocol (registrations/heartbeats/reports keep the observer's soft
    # block map warm — location sets are never journaled, so an observer
    # that refused reports could not serve get_block_locations) and HA
    # plumbing (transition_to_active is accepted here and refused in the
    # handler so the caller gets a typed error, not a silent bounce).
    _OBSERVER_PLUMBING = frozenset({
        "register_datanode", "heartbeat", "lifeline", "block_report",
        "incremental_block_report", "bad_block", "block_received",
        "commit_block_sync", "stripe_complete", "bad_stripe",
        "transition_to_active", "fetch_image",
    })

    def _tail_lag_s(self) -> float:
        """Seconds since the last successful tail pass (0 on the active —
        it IS the journal head)."""
        if self.role == "active":
            return 0.0
        return max(0.0, time.monotonic() - self._tail_ok_t)

    def _rpc_state_id(self) -> dict:
        """Reply-envelope state stamp (GlobalStateIdContext analog): the
        RPC server appends this to EVERY reply, so mutations on the active
        piggyback the journal txid and observer replies carry applied_txid
        + tail lag for the client's read-your-writes bookkeeping."""
        return {"txid": self._editlog.seq, "role": self.role,
                "lag_s": round(self._tail_lag_s(), 3)}

    def _rpc_observer_gate(self, method: str, sid: int | None) -> None:
        """Called by RpcServer before dispatching wire calls.  On an
        observer: refuse non-read methods (StandbyError → HA proxy fails
        over), wait out a bounded window for the tailer to reach the
        caller's state-id, and enforce the hard staleness bound
        (ObserverReadProxyProvider.isRead + ObserverRetryOnActive analog).
        Active/standby roles pass everything through unchanged."""
        if self.role != "observer":
            return
        if method not in _OBSERVER_READS:
            if method in self._OBSERVER_PLUMBING:
                return
            raise StandbyError("observer namenode serves reads only")
        if method in ("msync", "ha_state"):
            return  # barrier/probe calls report staleness, never refuse
        want = int(sid) if sid else 0
        if want > self._max_seen_sid:
            self._max_seen_sid = want
        if want > self._editlog.seq:
            deadline = time.monotonic() + self.config.observer_wait_s
            pause = min(0.005, self.config.tail_interval_s)
            while (self._editlog.seq < want
                   and time.monotonic() < deadline):
                time.sleep(pause)
            if self._editlog.seq < want:
                _M.incr("observer_stale_bounced")
                raise ObserverStaleError(
                    f"observer applied txid {self._editlog.seq} < client "
                    f"state-id {want} after {self.config.observer_wait_s}s")
        lag = self._tail_lag_s()
        if lag > self.config.observer_max_lag_s:
            _M.incr("observer_stale_bounced")
            raise ObserverStaleError(
                f"observer tail lag {lag:.2f}s exceeds the "
                f"{self.config.observer_max_lag_s}s staleness bound")
        _M.incr("observer_reads")

    def rpc_msync(self, txid: int = 0, wait_s: float | None = None) -> dict:
        """Consistency barrier (HAServiceProtocol msync analog): block —
        deadline-bounded — until this NN has applied ``txid``, then report
        where it stands.  On the active this returns immediately (it is
        the txid source); a caller that msyncs every observer with its
        last-seen txid gets read-your-writes on all subsequent observer
        reads."""
        _M.incr("msync_calls")
        want = int(txid or 0)
        if want > self._max_seen_sid:
            self._max_seen_sid = want
        budget = (self.config.observer_msync_wait_s if wait_s is None
                  else float(wait_s))
        deadline = time.monotonic() + max(0.0, budget)
        while (self.role != "active" and self._editlog.seq < want
               and time.monotonic() < deadline):
            time.sleep(0.005)
        applied = self._editlog.seq
        return {"applied_txid": applied, "role": self.role,
                "caught_up": bool(self.role == "active" or applied >= want),
                "lag_s": round(self._tail_lag_s(), 3)}

    # ------------------------------------------------- delegation tokens

    # Methods reachable without a delegation token when require_token_auth
    # is on: the DN protocol (DNs authenticate via the shared block keys /
    # deployment perimeter, as in the reference's service principals), HA
    # and journal plumbing, and token acquisition itself (the kerberos leg
    # that gates issuance in the reference has no analog here).
    _AUTH_EXEMPT = frozenset({
        "register_datanode", "heartbeat", "lifeline", "block_report",
        "incremental_block_report", "bad_block", "block_received",
        "commit_block_sync", "ha_state", "msync", "transition_to_active",
        "fetch_image", "get_delegation_token", "renew_delegation_token",
        "cancel_delegation_token", "check_delegation_token",
    })

    def _rpc_auth_hook(self, method: str, dtoken: dict | None) -> None:
        """Called by RpcServer before every dispatch.  In-process callers
        (tests, embedded use) bypass it — the wire is the trust boundary,
        same as the reference's IPC-layer SASL authentication."""
        if not self.config.require_token_auth or method in self._AUTH_EXEMPT:
            return
        self._dtokens.verify(dtoken)

    def rpc_get_delegation_token(self, renewer: str = "",
                                 owner: str = "") -> dict:
        """Issue a delegation token (FSNamesystem.getDelegationToken): the
        identifier + master key id are journaled, so a promoted standby
        keeps verifying and renewing mid-lifetime tokens."""
        with self._lock:
            if self.role != "active":
                raise StandbyError("namenode is standby")
            nk = self._dtokens.need_key()
            if nk is not None:
                self._log(["dt_key", nk[0], nk[1], nk[2]])
            ident = self._dtokens.build_identifier(owner or "anonymous",
                                                   renewer)
            expiry = time.time() + self._dtokens.renew_interval_s
            self._log(["dt_issue", ident, expiry])
            return {**ident, "password": self._dtokens.password(ident),
                    "expiry": expiry}

    def rpc_check_delegation_token(self, token: dict) -> bool:
        """Non-mutating verification (the gateway's token-issue gate asks
        before treating a presented delegation token as authentication —
        decoding alone proves nothing)."""
        with self._lock:
            try:
                self._dtokens.verify(token)
                return True
            except Exception:  # noqa: BLE001 — verification IS the answer
                return False

    def rpc_renew_delegation_token(self, token: dict) -> float:
        with self._lock:
            if self.role != "active":
                raise StandbyError("namenode is standby")
            self._dtokens.verify(token)
            expiry = self._dtokens.check_renew(token["seq"],
                                               token.get("renewer", ""))
            self._log(["dt_renew", int(token["seq"]), expiry])
            return expiry

    def rpc_cancel_delegation_token(self, token: dict) -> bool:
        with self._lock:
            if self.role != "active":
                raise StandbyError("namenode is standby")
            self._dtokens.verify(token)
            self._dtokens.check_cancel(token["seq"], token.get("owner", ""))
            self._log(["dt_cancel", int(token["seq"])])
            return True

    def rpc_fetch_image(self) -> dict:
        """Serve this NN's fsimage bytes (image-transfer analog: the
        reference moves images between NNs over its HTTP servlet; quorum
        JournalNodes hold only edits, so a far-behind standby bootstraps
        from a peer)."""
        data = self._editlog.read_image_bytes()
        return {"image": data, "seq": self._editlog.seq}

    def _fetch_image_from_peer(self) -> bool:
        from hdrf_tpu.proto.rpc import RpcClient

        for addr in (self.config.peers or []):
            try:
                with RpcClient(tuple(addr), timeout=10.0) as c:
                    r = c.call("fetch_image")
                if r.get("image"):
                    with self._lock:
                        self._editlog.write_image_bytes(r["image"])
                    _M.incr("image_bootstraps")
                    return True
            except (OSError, ConnectionError, RpcError):
                continue
        return False

    def rpc_transition_to_active(self) -> bool:
        """Manual/controller-driven failover (transitionToActive analog):
        final catch-up tail, claim the journal epoch (fencing the old
        active), open for append, start the redundancy monitor.  Observers
        are read replicas by contract, never failover candidates — the
        refusal is typed so a misconfigured controller learns why."""
        with self._lock:
            if self.role == "active":
                return True
            if self.config.role == "observer":
                raise ValueError("observer namenode cannot be promoted")
            # claim FIRST (fencing the old writer), THEN the final tail —
            # the reverse order loses any edit the not-yet-fenced active
            # appends between the tail and the claim, and reuses its seq.
            # The tail runs readonly=False: we are now the sole journal
            # writer, and the torn tail a crashed ex-active left behind must
            # be truncated before open_for_append, or every edit we append
            # behind it becomes unreachable to future replays.
            self._editlog.claim_epoch()
            from hdrf_tpu.server.editlog import JournalGapError
            try:
                self._editlog.tail(self._apply_tolerant,
                                   reload_fn=self._reload_image,
                                   readonly=False)
            except JournalGapError:
                # Lagged past the quorum's purge horizon: bootstrap the
                # ex-active's image, then retry — failing here would leave
                # the cluster active-less with the old writer already
                # fenced.  (The claim is not undone: a retried transition
                # simply claims the next epoch.)
                if not self._fetch_image_from_peer():
                    raise
                self._editlog.tail(self._apply_tolerant,
                                   reload_fn=self._reload_image,
                                   readonly=False)
            self._drain_pending_ibr()
            self._editlog.open_for_append(self._snapshot)
            self._load_decommissioning()
            # same protection as a cold start: hold mutations until enough
            # replicas are known (a warm standby lifts this immediately)
            self._safemode_auto = bool(self._blocks)
            self.role = "active"
        mon = threading.Thread(target=self._monitor_loop, name="nn-monitor",
                               daemon=True)
        mon.start()
        _M.incr("transitions_to_active")
        return True

    def _tailer_loop(self) -> None:
        """Standby/observer: periodically replay the shared journal
        (EditLogTailer.java:74 + StandbyCheckpointer roles).  On an
        observer each pass also refreshes the staleness gauges the read
        gate and flight recorder report against."""
        from hdrf_tpu.server.editlog import JournalGapError

        interval = self.config.tail_interval_s
        quorum = bool(self.config.journal_addrs)
        applied_since_image = 0
        while not self._monitor_stop.wait(interval):
            if self.role == "active":
                return  # transitioned; monitor thread has taken over
            try:
                fault_injection.point("namenode.tail", role=self.role)
                with self._lock:
                    n = self._editlog.tail(self._apply_tolerant,
                                           reload_fn=self._reload_image)
                    self._drain_pending_ibr()
                self._tail_ok_t = time.monotonic()
                if self.role == "observer":
                    _M.gauge("observer_lag_s", round(self._tail_lag_s(), 3))
                    _M.gauge("observer_lag_txids",
                             max(0, self._max_seen_sid - self._editlog.seq))
                applied_since_image += n
                if quorum and applied_since_image >= \
                        self.config.editlog_checkpoint_every:
                    # Quorum-mode standby keeps its OWN local image current
                    # (each NN owns its meta_dir; in shared-dir mode the
                    # active owns the one shared image).  Everything the
                    # standby applied is quorum-committed, so snapshotting
                    # it is always safe.
                    with self._lock:
                        self._editlog.write_image(self._editlog.seq,
                                                  self._snapshot())
                    applied_since_image = 0
            except JournalGapError:
                # the journal was purged past our seq: bootstrap a newer
                # image from the active peer, then resume tailing from it
                if self._fetch_image_from_peer():
                    applied_since_image = 0
                else:
                    _M.incr("tail_errors")
            except Exception:  # noqa: BLE001 — tailer must survive
                _M.incr("tail_errors")

    def _monitor_loop(self) -> None:
        """HeartbeatManager.Monitor + RedundancyMonitor (§3.5): declare dead
        DNs, schedule re-replication, recover expired leases."""
        interval = self.config.heartbeat_interval_s
        while not self._monitor_stop.wait(interval):
            if self.role != "active":
                return  # demoted: the tailer owns this NN now
            try:
                fault_injection.point("namenode.monitor_tick")
                self._check_dead_nodes()
                self._check_replication()
                self._check_partial_replicas()
                self._settle_moves()
                self._check_cache()
                self._recover_leases()
                self._check_ec_demotion()
                self._check_stripe_repair()
                self._check_fsck()
                with self._lock:
                    self._dtokens.purge_expired()
                if self._editlog.should_checkpoint():
                    # Background checkpointer (SecondaryNameNode /
                    # StandbyCheckpointer role): with group commit the
                    # append path no longer checkpoints inline; the
                    # namesystem lock makes the snapshot consistent.
                    with self._lock:
                        self._editlog.checkpoint()
            except Exception:  # noqa: BLE001 — monitor must survive
                _M.incr("monitor_errors")

    def _check_dead_nodes(self) -> None:
        with self._lock:
            now = time.monotonic()
            for dn in list(self._datanodes.values()):
                if now - dn.last_heartbeat > self.config.dead_node_interval_s:
                    _M.incr("dn_declared_dead")
                    for bid in dn.blocks:
                        info = self._blocks.get(bid)
                        if info:
                            info.locations.discard(dn.dn_id)
                            info.reported.pop(dn.dn_id, None)
                            info.storage_of.pop(dn.dn_id, None)
                    del self._datanodes[dn.dn_id]

    def _check_replication(self) -> None:
        with self._lock:
            now = time.monotonic()
            self._check_ec_groups(now)
            ec_bids = {b for g in self._groups.values() for b in g.bids}
            under = 0
            for info in self._blocks.values():
                node = self._try_file(info.path)
                if node is None or not node.complete:
                    continue
                # EC internal blocks: zero-location loss is handled by
                # _check_ec_groups (reconstruction); a draining host still
                # holds live bytes, so the drain is a plain 1-replica copy.
                # stripe-demoted blocks keep ONE full replica (the stripe
                # owner); redundancy lives in the (k+m)/k cold-tier stripes
                want = (1 if info.block_id in ec_bids
                        or info.block_id in self._ec_demoted
                        else node.replication)
                live = {d for d in info.locations if d in self._datanodes}
                # PROVIDED replicas are views of ONE shared external store:
                # N DataNodes mounting the same provided volume add no
                # redundancy beyond the store itself.  They count once
                # toward the target, are never pruned as "excess" (pruning
                # would collapse a multi-DN provided mount to a single DN),
                # and never trigger or source deficit re-replication onto
                # local disks (provided->local migration is an explicit
                # operator action, not the monitor's).
                provided = {d for d in live
                            if info.storage_of.get(d) == "PROVIDED"}
                local = live - provided
                counted = local - self._decommissioning
                deficit = want - len(counted) - (1 if provided else 0)
                if deficit > 0 and local:
                    under += 1
                if deficit <= 0 or not local:
                    self._pending_repl.pop(info.block_id, None)
                    if (deficit < 0
                            and info.block_id not in self._pending_moves):
                        self._prune_excess(info, counted,
                                           want - (1 if provided else 0))
                    continue
                # PendingReconstructionBlocks analog: don't re-queue the same
                # block every monitor tick while a transfer is in flight.
                deadline = self._pending_repl.get(info.block_id, 0.0)
                if deadline > now:
                    continue
                targets = self._choose_targets(deficit, exclude=live)
                if targets:
                    src = self._datanodes[next(iter(local))]
                    src.commands.append({
                        "cmd": "replicate", "block_id": info.block_id,
                        "gen_stamp": info.gen_stamp,
                        "targets": [{"dn_id": t.dn_id, "addr": list(t.addr)}
                                    for t in targets]})
                    self._pending_repl[info.block_id] = (
                        now + self.config.pending_replication_timeout_s)
                    _M.incr("replications_scheduled")
            # cached for rpc_cluster_status: the dfshealth page must not
            # re-walk every block under the namesystem lock per page load
            self._under_replicated = under

    def _check_partial_replicas(self) -> None:
        """Reconciliation monitor for the coded mirror plane (alongside
        ``_check_stripe_repair``): a DN holding only a k-of-n SEGMENT of a
        block's reduced payload (server/mirror_plane.py) is upgraded to a
        full replica in the background — a ``replicate`` re-push from any
        live full-replica holder, or, when the write lost every full copy,
        a ``mirror_assemble`` command telling one segment holder to gather
        any k segments off its peers and decode.  The partial entry clears
        when the holder's normal (non-partial) IBR lands."""
        with self._lock:
            now = time.monotonic()
            for bid in list(self._partial_replicas):
                holders = self._partial_replicas[bid]
                for d in [d for d in holders if d not in self._datanodes]:
                    del holders[d]   # holder died with its segment
                info = self._blocks.get(bid)
                if not holders or info is None:
                    self._partial_replicas.pop(bid, None)
                    self._pending_partial.pop(bid, None)
                    continue
                if self._pending_partial.get(bid, 0.0) > now:
                    continue   # an upgrade is already in flight
                live_full = sorted(d for d in info.locations
                                   if d in self._datanodes)
                if live_full:
                    src = self._datanodes[live_full[0]]
                    src.commands.append({
                        "cmd": "replicate", "block_id": bid,
                        "gen_stamp": info.gen_stamp,
                        "targets": [{"dn_id": d,
                                     "addr": list(self._datanodes[d].addr)}
                                    for d in sorted(holders)]})
                    _M.incr("partial_reconciliations_scheduled")
                else:
                    agent = self._datanodes[sorted(holders)[0]]
                    agent.commands.append({"cmd": "mirror_assemble",
                                           "block_id": bid})
                    _M.incr("partial_assembles_scheduled")
                self._pending_partial[bid] = (
                    now + self.config.partial_reconcile_timeout_s)
                # keep _check_replication from double-scheduling the same
                # deficit while the reconciliation transfer is in flight
                self._pending_repl[bid] = (
                    now + self.config.pending_replication_timeout_s)

    def _prune_excess(self, info, counted: set[str], want: int) -> None:
        """Drop excess replicas (BlockManager.processExtraRedundancy /
        chooseReplicaToDelete analog): over-replication arises from
        re-replication racing a node's return, or a balancer move abandoned
        at its deadline whose target reported late.  Victim selection must
        preserve rack diversity (the invariant _choose_targets establishes):
        only prune from racks holding more than one replica while another
        rack still has a copy; among eligible victims prefer the fullest
        node.  Decommissioning nodes' copies are already excluded from
        ``counted``."""
        excess = len(counted) - want
        remaining = set(counted)
        for _ in range(excess):
            by_rack: dict[str, list[str]] = {}
            for d in remaining:
                by_rack.setdefault(self._datanodes[d].rack, []).append(d)
            if len(by_rack) > 1:
                eligible = [d for r, ds in by_rack.items() if len(ds) > 1
                            for d in ds]
            else:
                eligible = list(remaining)
            if not eligible:
                # every remaining rack holds exactly one replica: removing
                # any would shrink rack coverage — prune the fullest anyway
                # (count still exceeds want) but from the largest rack set.
                eligible = list(remaining)
            victim = max(eligible,
                         key=lambda d: len(self._datanodes[d].blocks))
            remaining.discard(victim)
            dn = self._datanodes.get(victim)
            if dn is None:
                continue
            dn.commands.append({"cmd": "invalidate",
                                "block_ids": [info.block_id]})
            info.locations.discard(victim)
            dn.blocks.discard(info.block_id)
            _M.incr("excess_replicas_pruned")

    def _check_ec_groups(self, now: float) -> None:
        """Schedule EC reconstruction for lost internal blocks
        (BlockManager's DNA_ERASURE_CODING_RECONSTRUCTION path, §3.5)."""
        from hdrf_tpu.ops import rs

        for grp in self._groups.values():
            info0 = self._blocks.get(grp.group_id)
            node = self._try_file(info0.path) if info0 else None
            if node is None or not node.complete or not node.ec:
                continue
            k, m, cell = rs.parse_policy(node.ec)
            survivors, missing = [], []
            for i, bid in enumerate(grp.bids):
                locs = self._locs_of(bid)
                (survivors if locs else missing).append(
                    (i, bid, locs))
            if not missing or len(survivors) < k:
                continue  # healthy, or unrecoverable (alerting is the
                # operator's signal: ec_groups_unrecoverable metric)
            chosen: set[str] = set()
            for i, bid, _ in missing:
                if self._pending_repl.get(bid, 0.0) > now:
                    continue
                # keep the distinct-placement invariant: exclude survivor
                # hosts AND DNs already picked for this group's other shards
                used = {loc["dn_id"] for _, _, ls in survivors
                        for loc in ls} | chosen
                targets = self._choose_targets(1, exclude=used)
                if not targets:
                    continue
                chosen.add(targets[0].dn_id)
                targets[0].commands.append({
                    "cmd": "ec_reconstruct", "block_id": bid,
                    "gen_stamp": self._blocks[bid].gen_stamp,
                    "policy": node.ec, "index": i,
                    "group_len": grp.logical_len,
                    "survivors": [{"index": si, "block_id": sb,
                                   "locations": ls}
                                  for si, sb, ls in survivors]})
                self._pending_repl[bid] = (
                    now + self.config.pending_replication_timeout_s)
                _M.incr("ec_reconstructions_scheduled")

    def _ec_placement_pool(self, now: float) -> list["DatanodeInfo"]:
        """Stripe-target pool: live, non-decommissioning DNs minus the
        health report's veto set — slow peers, reduction-degraded nodes,
        and any DN with a flagged slow volume (the PR-3 detectors gating
        cold-tier placement).  Caller holds self._lock."""
        health = self._health_report()
        vetoed = set(health["slow_peers"]) | set(health["degraded_nodes"])
        vetoed |= {v.split(":", 1)[0] for v in health["slow_volumes"]}
        pool = [d for d in self._datanodes.values()
                if now - d.last_heartbeat < self.config.dead_node_interval_s
                and d.dn_id not in self._decommissioning
                and d.dn_id not in vetoed]
        pool.sort(key=lambda d: d.dn_id)
        return pool

    def _check_ec_demotion(self) -> None:
        """EC cold-tier demotion scheduler: blocks of complete files idle
        past ``ec_demote_after_s`` drop from ``replication``x full copies
        to (k+m)/k stripes.  The primary holder is commanded to stripe its
        sealed containers (server/ec_tier.py demote); the demotion only
        becomes durable when that DN reports ``stripe_complete`` back —
        until then the block stays fully replicated.  Stripe i lands on
        pool[i % len(pool)], so a cluster smaller than k+m still places
        every stripe (with spread returning as the cluster grows)."""
        cfg = self.config
        if cfg.ec_demote_after_s <= 0:
            return
        with self._lock:
            now = time.monotonic()
            cutoff = time.time() - cfg.ec_demote_after_s
            k, m = cfg.ec_data_shards, cfg.ec_parity_shards
            self._pending_demote = {
                b: t for b, t in self._pending_demote.items()
                if t > now or b in self._blocks}
            pool = self._ec_placement_pool(now)
            if not pool:
                return
            ec_bids = {b for g in self._groups.values() for b in g.bids}
            for info in list(self._blocks.values()):
                bid = info.block_id
                if bid in self._ec_demoted or bid in ec_bids:
                    continue
                if self._pending_demote.get(bid, 0.0) > now:
                    continue
                node = self._try_file(info.path)
                if (node is None or not node.complete or node.ec
                        or info.length < 0):
                    continue
                if node.mtime <= 0 or node.mtime > cutoff:
                    continue
                live = sorted(d for d in info.locations
                              if d in self._datanodes)
                # demote only from full health: a replica deficit means
                # redundancy is already degraded — re-replicate first
                if len(live) < node.replication:
                    continue
                owner = self._datanodes[live[0]]
                targets = [pool[i % len(pool)] for i in range(k + m)]
                owner.commands.append({
                    "cmd": "stripe_demote", "block_id": bid,
                    "k": k, "m": m,
                    "targets": [[t.dn_id, t.addr[0], t.addr[1]]
                                for t in targets]})
                self._pending_demote[bid] = (
                    now + cfg.pending_replication_timeout_s)
                _M.incr("ec_demotions_scheduled")

    def _check_stripe_repair(self) -> None:
        """Background stripe-repair scheduler over the soft-state group
        cache: a stripe whose holder left the cluster is re-decoded by the
        group's owner DN (it holds the WAL-durable manifest) onto healthy
        replacements.  Owner loss is repairable too, since the demote-time
        ``ec_demote`` edits journal each group's full manifest: a surviving
        holder is deputized as the repair agent and hands the NN's durable
        manifest copy down with the ``stripe_repair`` command
        (_schedule_owner_loss_repair)."""
        with self._lock:
            now = time.monotonic()
            dead_after = self.config.dead_node_interval_s
            for (owner_id, cid), grp in list(self._stripe_groups.items()):
                owner = self._datanodes.get(owner_id)
                if (owner is None
                        or now - owner.last_heartbeat >= dead_after):
                    # owner (and its WAL manifest) is gone: fall back to
                    # the editlog-durable manifest via a surviving holder
                    self._schedule_owner_loss_repair(owner_id, cid, grp, now)
                    continue
                missing = []
                for idx, h in enumerate(grp["holders"]):
                    d = self._datanodes.get(h[0])
                    if d is None or now - d.last_heartbeat >= dead_after:
                        missing.append(idx)
                key = (owner_id, cid)
                # scrub-confirmed corrupt stripes on live holders repair
                # through the same scheduler as dead-holder losses
                corrupt = self._corrupt_stripes.get(key, set())
                missing = sorted(set(missing)
                                 | {i for i in corrupt
                                    if i < len(grp["holders"])})
                if not missing:
                    self._pending_stripe_repair.pop(key, None)
                    self._corrupt_stripes.pop(key, None)
                    continue
                if self._pending_stripe_repair.get(key, 0.0) > now:
                    continue
                survivors = {h[0] for i, h in enumerate(grp["holders"])
                             if i not in missing}
                base = self._ec_placement_pool(now)
                # small clusters: if every healthy DN already holds a
                # surviving stripe, double up on survivors (distinct
                # (owner,cid,idx) filenames make that safe) rather than
                # leaving the group degraded forever
                pool = ([d for d in base if d.dn_id not in survivors]
                        or base)
                if not pool:
                    continue
                targets = [pool[i % len(pool)]
                           for i in range(len(missing))]
                owner.commands.append({
                    "cmd": "stripe_repair", "cid": cid,
                    "block_id": grp.get("block_id"),
                    "missing": missing,
                    "targets": [[t.dn_id, t.addr[0], t.addr[1]]
                                for t in targets]})
                self._pending_stripe_repair[key] = (
                    now + self.config.pending_replication_timeout_s)
                _M.incr("stripe_repairs_scheduled")
            # orphaned groups: the durable manifests remember stripes whose
            # owner died before (or across an NN restart, where the soft
            # cache starts empty) — sweep them through the same scheduler
            for (owner_id, cid), man in list(self._stripe_manifests.items()):
                if (owner_id, cid) in self._stripe_groups:
                    continue
                owner = self._datanodes.get(owner_id)
                if (owner is not None
                        and now - owner.last_heartbeat < dead_after):
                    continue  # live owner re-reports the group itself
                self._schedule_owner_loss_repair(
                    owner_id, cid,
                    {"holders": [list(h) for h in man["holders"]],
                     "block_id": None}, now)

    def _schedule_owner_loss_repair(self, owner_id: str, cid: int,
                                    grp: dict, now: float) -> None:
        """Repair a stripe group whose OWNER (and therefore the WAL-durable
        manifest) is gone: deputize the first surviving holder as the
        repair agent and hand it the NN's journaled manifest copy with the
        ``stripe_repair`` command.  Repaired stripes keep the dead owner's
        name (ec_tier._place owner=), so the group stays addressable; the
        NN's editlog manifest remains the orphan group's durable home.
        Caller holds self._lock."""
        man = self._stripe_manifests.get((owner_id, cid))
        if man is None:
            return   # pre-durability residual: nothing to repair from
        key = (owner_id, cid)
        if self._pending_stripe_repair.get(key, 0.0) > now:
            return
        dead_after = self.config.dead_node_interval_s
        missing, agent = [], None
        for idx, h in enumerate(grp["holders"]):
            d = self._datanodes.get(h[0])
            if d is None or now - d.last_heartbeat >= dead_after:
                missing.append(idx)
            elif agent is None:
                agent = d
        if agent is None:
            return   # no surviving holder left to deputize: data loss
        if not missing:
            self._pending_stripe_repair.pop(key, None)
            return   # every stripe survives; group is merely owner-less
        survivors = {h[0] for i, h in enumerate(grp["holders"])
                     if i not in missing}
        base = self._ec_placement_pool(now)
        pool = [d for d in base if d.dn_id not in survivors] or base
        if not pool:
            return
        targets = [pool[i % len(pool)] for i in range(len(missing))]
        agent.commands.append({
            "cmd": "stripe_repair", "cid": cid,
            "block_id": grp.get("block_id"),
            "missing": missing,
            "targets": [[t.dn_id, t.addr[0], t.addr[1]] for t in targets],
            # stamp the group's owner into the handed-down manifest: the
            # agent's gather/placement key stripes by (owner, cid, idx),
            # and the agent's own dn_id must never leak in as the default
            "manifest": dict(man, owner=owner_id)})
        self._pending_stripe_repair[key] = (
            now + self.config.pending_replication_timeout_s)
        _M.incr("owner_loss_repairs_scheduled")

    def _recover_leases(self) -> None:
        with self._lock:
            for path in self._leases.expired():
                node = self._try_file(path)
                if node is None or node.complete:
                    self._leases.drop(path)
                    continue
                # keep the (expired) lease until the file actually closes:
                # it is what makes the monitor retry a finalize that is
                # waiting on IBR grace or an in-flight block recovery
                if self._finalize_abandoned(path, node):
                    self._leases.drop(path)

    RECOVERY_GRACE_S = 4.0  # bounded wait for async IBRs before concluding
    # "no replica survived" (the reference's recovery also never trusts an
    # instantaneous empty view — rpc_recover_lease polls race the DNs' IBRs)

    def _resolved_length(self, bid: int) -> int:
        """Best known logical length of a block: the committed length if
        resolved, else the MINIMUM length among live replicas of the highest
        reported generation (every byte below the minimum was CRC-verified
        on each node — BlockRecoveryWorker's sync rule)."""
        info = self._blocks.get(bid)
        if info is None:
            return 0
        if info.length >= 0:
            return info.length
        live = [v for d, v in info.reported.items() if d in self._datanodes]
        if not live:
            return 0
        top = max(gs for gs, _ in live)
        return min(ln for gs, ln in live if gs == top)

    def _finalize_abandoned(self, path: str, node: "FileNode") -> bool:
        """Close a writer-abandoned file.  If the last block's length is
        unresolved: with live replicas, journal a recovery generation stamp
        (fencing the dead writer's pipeline) and dispatch a primary-DN
        length-sync recovery (BlockRecoveryWorker; the pipeline may have
        died with different replica lengths on each node), finishing in
        rpc_commit_block_sync; with NO replicas reported yet, wait a bounded
        grace for the asynchronous IBRs before dropping the block.
        Returns True when the file closed now.  Caller holds the lock."""
        last = node.blocks[-1] if node.blocks and not node.ec else None
        info = self._blocks.get(last) if last is not None else None
        if info is not None and info.length < 0:
            now = time.monotonic()
            # candidates = reporters + the allocation's intended pipeline:
            # recovery must not race the async IBRs of a DN that holds a
            # replica but hasn't reported yet (it would sync to a PARTIAL
            # peer set — possibly one replica's length, not the min)
            live = sorted({d for d in (set(info.reported)
                                       | set(info.expected))
                           if d in self._datanodes})
            lens = {v for d, v in info.reported.items()
                    if d in self._datanodes}
            reported_live = {d for d in info.reported
                             if d in self._datanodes}
            if live and set(live) <= reported_live and len(lens) == 1 and \
                    next(iter(lens))[0] == info.gen_stamp:
                # every live replica is at the current generation and they
                # agree on length: nothing to sync — complete directly (the
                # all-replicas-consistent fast path of the reference's
                # internalReleaseLease); _resolved_length picks the agreed
                # value below
                self._recovery_grace.pop(last, None)
            elif live and reported_live:
                self._recovery_grace.pop(last, None)
                if now < self._pending_recovery.get(last, 0):
                    return False  # a recovery is already in flight
                # Journal the recovery generation stamp BEFORE dispatching:
                # it fences the dead writer (a late finalize IBRs as stale)
                # and survivors are restamped with it so the next full block
                # report doesn't invalidate the just-recovered replicas
                # (commitBlockSynchronization installs the recovery gen
                # stamp in the reference too).
                rec_gs = self._gen_stamp
                self._log(["bump_block", path, last, rec_gs])
                # retry window: a recovery aborted by an in-flight RBW
                # (writer not torn down yet) re-dispatches quickly
                self._pending_recovery[last] = now + 5.0
                primary = self._datanodes[live[0]]
                primary.commands.append({
                    "cmd": "recover_block", "path": path, "block_id": last,
                    "gen_stamp": rec_gs,
                    "peers": [{"dn_id": d,
                               "addr": list(self._datanodes[d].addr)}
                              for d in live]})
                _M.incr("block_recoveries_dispatched")
                return False
            else:
                deadline = self._recovery_grace.setdefault(
                    last, now + self.RECOVERY_GRACE_S)
                if now < deadline:
                    return False  # IBRs may still be in flight; retry later
                # grace expired with no replica reported: nothing survived —
                # drop the last block (the reference drops it too) and close
                self._recovery_grace.pop(last, None)
                self._log(["abandon_block", path, last])
        if node.ec:
            lengths = {g: max(self._groups[g].logical_len, 0)
                       for g in node.blocks if g in self._groups}
        else:
            lengths = {b: self._resolved_length(b)
                       for b in node.blocks if b in self._blocks}
        self._log(["complete", path, lengths, time.time()])
        _M.incr("leases_recovered")
        return True

    def rpc_commit_block_sync(self, path: str, block_id: int, length: int,
                              dn_ids: list, gen_stamp: int = -1) -> bool:
        """Primary-DN report after a length-sync recovery
        (commitBlockSynchronization analog): record the agreed length (or
        drop a block no replica survived for), install the recovery
        generation's replica set as the serving locations, and close the
        file."""
        with self._lock:
            node = self._try_file(path)
            info = self._blocks.get(block_id)
            if node is None or node.complete or info is None:
                return False
            if 0 <= gen_stamp < info.gen_stamp:
                return False  # a newer recovery superseded this one
            self._pending_recovery.pop(block_id, None)
            self._recovery_grace.pop(block_id, None)
            if length <= 0:
                self._log(["abandon_block", path, block_id])
            else:
                live = set(dn_ids) & set(self._datanodes)
                info.locations = set(live)
                for d in live:
                    info.reported[d] = (info.gen_stamp, length)
                    self._datanodes[d].blocks.add(block_id)
            lengths = {b: (length if b == block_id
                           else self._resolved_length(b))
                       for b in node.blocks if b in self._blocks}
            self._log(["complete", path, lengths, time.time()])
            _M.incr("blocks_synced")
            return True

    def _try_file(self, path: str) -> FileNode | None:
        try:
            node = self._resolve(path)
            return node if isinstance(node, FileNode) else None
        except (FileNotFoundError, NotADirectoryError):
            return None
