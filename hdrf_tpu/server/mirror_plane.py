"""Coded mirror plane: k-of-n reduced mirroring with hedged parity legs.

The reference forwards the raw packet stream serially down the pipeline
(DataStreamer.java:765 sets up one downstream socket; BlockReceiver.java:
635-641 ``mirrorPacketTo`` relays hop by hop), so one dead or straggling
mirror stalls the whole write — SURVEY.md §0 fact 3.  PR 5's serial
``push_reduced`` relay (server/block_receiver.py:521) kept that shape: a
single all-or-nothing leg through ``targets[0]``.

This module applies the coded-distributed-computing construction
(Compressed Coded Distributed Computing, arXiv 1805.01993; Cascaded CDC
via Placement Delivery Arrays, arXiv 2001.04194) to the mirror stream:

- the reduced chunk-delta payload is split into k data segments plus m
  Cauchy-RS parity segments (ops/rs.py:181-188 ``rs_encode``, the same
  bit-matmul code the EC cold tier stripes with, storage/stripe_store.py);
- the k data legs fan out CONCURRENTLY; the m parity legs are the hedge,
  launched when a data leg fails fast (dead peer, open breaker —
  utils/retry.py ``CircuitBreaker``) or when the rolling-window p95 leg
  deadline elapses (utils/rollwin.py:58 summaries, the PR 3 per-peer
  latency windows, scaled by ``mirror_hedge_p95_mult``);
- the write acks as soon as ANY k legs land (utils/retry.py
  ``hedged_quorum``) — a straggler costs m/k extra bytes, never a stall.

A mirror that received only a segment registers a ``partial_replica``
with the NN (DataNode.notify_block_received partial=True riding the IBR,
IncrementalBlockReportManager.java:42 analog); the NN's reconciliation
monitor (server/namenode.py ``_check_partial_replicas``, alongside
``_check_stripe_repair``) schedules background ``push_reduced`` re-pushes
from a full-replica holder to upgrade it — or, when no full replica
survives, commands a holder to ``assemble`` the payload from any k
segments gathered off its peers (the transferBlock role,
DataNode.java:2361, served without ever reconstructing full bytes twice).

``mirror_parity = 0`` (the default) bypasses this module's coded path
entirely and calls the serial ``push_reduced`` verbatim — byte-identical
replica semantics to PR 5.
"""

from __future__ import annotations

import os
import threading
import time
from typing import TYPE_CHECKING

import msgpack
import numpy as np

from hdrf_tpu import native
from hdrf_tpu.ops import rs
from hdrf_tpu.proto import datatransfer as dt
from hdrf_tpu.proto.rpc import MAX_FRAME, recv_frame, send_frame
from hdrf_tpu.server.block_receiver import _connect
from hdrf_tpu.utils import fault_injection, log, metrics, retry, tracing

if TYPE_CHECKING:
    from hdrf_tpu.server.datanode import DataNode

_M = metrics.registry("mirror")
_LOG = log.get_logger("mirror_plane")

#: per-segment frame overhead guard: header fields + msgpack framing must
#: fit MAX_FRAME beside the segment bytes
_FRAME_SLACK = 1 << 20


class MirrorPushFailed(IOError):
    """The coded fan-out missed its k-of-n quorum.  Per-leg failures were
    already attributed to the actual broken peers (``already_attributed``
    tells ``_store_and_mirror`` not to re-blame ``targets[0]``)."""

    already_attributed = True


# ------------------------------------------------------------- segment codec

def encode_segments(payload: bytes, k: int, m: int) -> tuple[list[bytes], int]:
    """Split ``payload`` into k data + m RS parity segments.

    Data segment i is the i-th ``seg_len`` slice of the zero-padded
    payload; parity rides ops/rs.py:181 ``rs_encode`` (Cauchy generator —
    any k of the k+m segments reconstruct).  Returns (segments, seg_len).
    """
    if k < 1 or m < 0:
        raise ValueError(f"bad coded-mirror geometry k={k} m={m}")
    seg_len = max(1, -(-len(payload) // k))
    padded = payload.ljust(k * seg_len, b"\0")
    data = np.frombuffer(padded, dtype=np.uint8).reshape(k, seg_len)
    segments = [data[i].tobytes() for i in range(k)]
    if m > 0:
        parity = rs.rs_encode(data, k, m)
        segments += [parity[i].tobytes() for i in range(m)]
    return segments, seg_len


def assemble_payload(segments: dict[int, bytes], k: int, m: int,
                     payload_len: int) -> bytes:
    """Rebuild the payload from ANY k of the k+m segments
    (ops/rs.py:191 ``rs_decode`` recovers missing data segments from the
    Cauchy survivors; indices 0..k-1 data, k..k+m-1 parity)."""
    shards = {int(i): np.frombuffer(s, dtype=np.uint8)
              for i, s in segments.items() if 0 <= int(i) < k + m}
    if len(shards) < k:
        raise ValueError(f"need {k} segments, have {len(shards)}")
    missing = [i for i in range(k) if i not in shards]
    if missing:
        shards.update(rs.rs_decode(shards, k, m, want=missing))
    return b"".join(shards[i].tobytes() for i in range(k))[:payload_len]


# ------------------------------------------------------------- segment store

class SegmentStore:
    """Durable per-DN store for mirror segments awaiting reconciliation.

    One file per (block, segment) under ``<data_dir>/mirror_segments``
    (tmp-write + rename, the storage/container_store.py seal discipline)
    so a partial replica survives a DN restart and the census the
    heartbeat ships stays honest."""

    def __init__(self, root: str):
        self._root = root
        self._lock = threading.Lock()
        self._segs: dict[int, dict[int, str]] = {}
        os.makedirs(root, exist_ok=True)
        for fn in sorted(os.listdir(root)):
            if not fn.endswith(".seg"):
                continue
            try:
                bid_s, idx_s, _ = fn.split(".")
                self._segs.setdefault(int(bid_s), {})[int(idx_s)] = \
                    os.path.join(root, fn)
            except ValueError:
                continue

    def put(self, block_id: int, idx: int, header: dict,
            data: bytes) -> None:
        path = os.path.join(self._root, f"{block_id}.{idx}.seg")
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(msgpack.packb([header, data]))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        with self._lock:
            self._segs.setdefault(block_id, {})[idx] = path

    def get(self, block_id: int) -> tuple[dict, dict[int, bytes]] | None:
        """(header, {seg_index: bytes}) or None when nothing is held."""
        with self._lock:
            paths = dict(self._segs.get(block_id) or {})
        header, segs = None, {}
        for idx, path in paths.items():
            try:
                with open(path, "rb") as f:
                    h, d = msgpack.unpackb(f.read(), raw=False,
                                           strict_map_key=False)
            except (OSError, ValueError):
                continue  # torn file: treat as an erasure, parity covers it
            header = header or h
            segs[idx] = bytes(d)
        return None if header is None else (header, segs)

    def drop(self, block_id: int) -> bool:
        with self._lock:
            paths = self._segs.pop(block_id, None)
        if not paths:
            return False
        for path in paths.values():
            try:
                os.unlink(path)
            except OSError:
                pass
        return True

    def count(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._segs.values())

    def blocks(self) -> list[int]:
        with self._lock:
            return sorted(self._segs)


# -------------------------------------------------------------- mirror plane

class MirrorPlane:
    """Push side (coded fan-out) + serve side (segment ingest, peer
    gather, full-replica assembly) of the coded mirror plane."""

    def __init__(self, dn: "DataNode"):
        self._dn = dn
        self._store = SegmentStore(
            os.path.join(dn.config.data_dir, "mirror_segments"))

    # ------------------------------------------------------------ push side

    def push(self, block_id: int, gen_stamp: int, scheme_name: str,
             logical_len: int, stored: bytes, crcs: list[int],
             targets: list, throttler=None):
        """Mirror the reduced form to ``targets``.

        ``mirror_parity = 0`` or a single target falls through to the
        serial relay (server/block_receiver.py:521 push_reduced) verbatim;
        otherwise the payload is coded across the mirror set and the call
        returns once any k legs land.  Returns the downstream failing
        dn_id propagated by the serial relay (None on the coded path —
        per-leg attribution happens inline here)."""
        dn = self._dn
        receiver = dn._receiver
        m_cfg = int(dn.config.reduction.mirror_parity)
        if m_cfg <= 0 or len(targets) < 2:
            return receiver.push_reduced(block_id, gen_stamp, scheme_name,
                                         logical_len, stored, crcs, targets,
                                         throttler=throttler)
        n = len(targets)
        m = min(m_cfg, n - 1)
        k = n - m
        payload, hashes, chunk_lens = self._build_payload(
            block_id, scheme_name, stored)
        if len(payload) // k + _FRAME_SLACK > MAX_FRAME:
            # segment would not fit one DT frame: serial relay fallback
            _M.incr("coded_fallbacks")
            return receiver.push_reduced(block_id, gen_stamp, scheme_name,
                                         logical_len, stored, crcs, targets,
                                         throttler=throttler)
        segments, seg_len = encode_segments(payload, k, m)
        common = dict(
            block_id=block_id, gen_stamp=gen_stamp, scheme=scheme_name,
            logical_len=logical_len, checksums=list(crcs),
            checksum_chunk=dn.checksum_chunk, hashes=hashes,
            chunk_lens=chunk_lens, k=k, m=m, seg_len=seg_len,
            payload_len=len(payload),
            payload_crc=int(native.crc32c(payload)),
            peers=[[t.get("dn_id"), t["addr"][0], t["addr"][1], i]
                   for i, t in enumerate(targets)])

        def make_leg(i: int):
            tgt, seg = targets[i], segments[i]

            def leg():
                fault_injection.point("mirror_plane.leg", dn_id=dn.dn_id,
                                      peer=tgt.get("dn_id"),
                                      block_id=block_id, seg_index=i)
                # same per-edge breaker the EC gather legs key
                # (server/ec_tier.py _gather): shared broken-peer evidence
                br = retry.breaker(f"{dn.dn_id}->{tgt.get('dn_id')}")
                br.check()
                leg_t0 = time.perf_counter()
                try:
                    if throttler is not None:
                        throttler.throttle(len(seg))
                    self._send_segment(tgt, i, seg, common)
                except Exception:
                    br.record_failure()
                    raise
                br.record_success()
                receiver._note_peer(tgt, time.perf_counter() - leg_t0,
                                    len(seg))
                _M.incr("segments_sent")
                if i >= k:
                    _M.incr("parity_bytes", len(seg))
                return i

            return leg

        push_t0 = time.perf_counter()
        try:
            _wins, errors, _hedged = retry.hedged_quorum(
                [make_leg(i) for i in range(k)],
                [make_leg(i) for i in range(k, n)],
                k, self._hedge_after_s(targets[:k], seg_len),
                timeout_s=retry.effective_budget(60.0),
                on_hedge=lambda: _M.incr("hedges_fired"))
        except retry.QuorumFailed as e:
            for idx, err in e.errors:
                receiver._note_mirror_failure(targets[idx], block_id, err)
            raise MirrorPushFailed(str(e)) from e
        for idx, err in errors:
            # quorum landed, but this leg is genuinely broken: attribute
            # the ACTUAL peer (never targets[0]) for the NN outlier feed
            receiver._note_mirror_failure(targets[idx], block_id, err)
        _M.observe("ack_us", (time.perf_counter() - push_t0) * 1e6)
        _M.incr("coded_pushes")
        return None

    def _build_payload(self, block_id: int, scheme_name: str,
                       stored: bytes) -> tuple[bytes, list | None,
                                               list | None]:
        """The byte stream the segments code over: the block's UNIQUE
        chunk bytes in first-occurrence order for the dedup family (the
        chunk-delta's superset — every leg is self-describing, no need
        negotiation per leg), the stored bytes otherwise."""
        dn = self._dn
        scheme = dn.scheme(scheme_name)
        if getattr(scheme, "container_codec", None) is None:
            return stored, None, None
        entry = dn.index.get_block(block_id)
        if entry is None:
            raise IOError(f"block {block_id} missing from chunk index")
        uniq = list(dict.fromkeys(entry.hashes))
        locs = dn.index.lookup_chunks(uniq)
        chunk_locs = [(locs[h].container_id, locs[h].offset, locs[h].length)
                      for h in uniq]
        chunks = dn.containers.read_chunks(chunk_locs)
        return (b"".join(chunks), list(entry.hashes),
                [len(c) for c in chunks])

    def _hedge_after_s(self, data_targets: list, seg_len: int) -> float:
        """Hedge deadline: p95 of the per-peer latency windows (s/MB,
        utils/rollwin.py summaries via DataNode.peer_latency_summaries)
        scaled to this segment size and ``mirror_hedge_p95_mult``, floored
        so a cold window never hedges at ~0 s."""
        red = self._dn.config.reduction
        summaries = self._dn.peer_latency_summaries()
        p95s = [summaries[t.get("dn_id")]["p95"] for t in data_targets
                if t.get("dn_id") in summaries]
        if not p95s:
            return float(red.mirror_hedge_floor_s)
        return max(float(red.mirror_hedge_floor_s),
                   float(red.mirror_hedge_p95_mult) * max(p95s)
                   * max(seg_len / 2**20, 1e-3))

    def _send_segment(self, target: dict, idx: int, seg: bytes,
                      common: dict) -> None:
        """One segment leg.  With ``mirror_compress_segments`` the wire
        payload rides coded_exchange's smaller-of LZ4 negotiation
        (``seg_enc``/``seg_usize``; ``seg_crc`` always covers the RAW
        segment, so the stored bytes and their check are knob-invariant —
        the knob pins the old raw path for A/B)."""
        from hdrf_tpu.server import coded_exchange

        dn = self._dn
        red = dn.config.reduction
        wire, extra = seg, {}
        if getattr(red, "mirror_compress_segments", True):
            payload, enc = coded_exchange.pack(
                seg, coded_exchange.backend_for(red))
            if enc:
                wire = payload
                extra = {"seg_enc": 1, "seg_usize": len(seg)}
                _M.incr("segments_compressed")
        _M.incr("segment_raw_bytes", len(seg))
        _M.incr("segment_wire_bytes", len(wire))
        sock = _connect(target["addr"], dn, common["block_id"])
        try:
            dt.send_op(sock, "mirror_segment", **common, seg_index=idx,
                       seg_crc=int(native.crc32c(seg)), **extra,
                       token=dn.tokens.mint(common["block_id"], "w"),
                       data=wire)
            resp = recv_frame(sock)
            if not resp.get("ok"):
                raise IOError(f"segment leg refused: "
                              f"{resp.get('error', 'unknown')}")
        finally:
            sock.close()

    # ----------------------------------------------------------- serve side

    def serve_segment(self, sock, fields: dict) -> None:
        """Mirror side of a coded leg: store the segment durably, register
        a partial replica with the NN (IBR partial=True), ack the leg."""
        dn = self._dn
        block_id, idx = fields["block_id"], fields["seg_index"]
        try:
            fault_injection.point("mirror_plane.segment", dn_id=dn.dn_id,
                                  block_id=block_id, seg_index=idx)
            data = bytes(fields["data"])
            if int(fields.get("seg_enc", 0)):
                from hdrf_tpu.server import coded_exchange

                data = coded_exchange.unpack(data, 1,
                                             int(fields["seg_usize"]))
            if int(native.crc32c(data)) != fields["seg_crc"]:
                raise IOError(f"segment {idx} of block {block_id} "
                              f"failed CRC")
            header = {key: fields[key] for key in (
                "block_id", "gen_stamp", "scheme", "logical_len",
                "checksums", "checksum_chunk", "hashes", "chunk_lens",
                "k", "m", "seg_len", "payload_len", "payload_crc", "peers")}
            self._store.put(block_id, idx, header, data)
            _M.incr("segments_ingested")
            dn.notify_block_received(block_id, fields["logical_len"],
                                     fields["gen_stamp"], partial=True)
            send_frame(sock, {"ok": True})
        except (OSError, ValueError, RuntimeError) as e:
            _M.incr("segment_ingest_failures")
            _LOG.warning("segment ingest failed", dn_id=dn.dn_id,
                         block_id=block_id, seg_index=idx,
                         trace=tracing.current_context(),
                         error=f"{type(e).__name__}: {e}")
            send_frame(sock, {"ok": False,
                              "error": f"{type(e).__name__}: {e}"})

    def serve_segment_read(self, sock, fields: dict) -> None:
        """Peer gather leg of ``assemble``: ship every locally-held
        segment of the block."""
        held = self._store.get(fields["block_id"])
        if held is None:
            send_frame(sock, {"ok": False, "error": "no segments held"})
            return
        _header, segs = held
        send_frame(sock, {"ok": True, "segments": segs})

    def assemble(self, block_id: int) -> None:
        """Upgrade this partial replica to a FULL one from any k segments:
        local holdings first, then peer gather over the leg map stored in
        the segment header — the no-full-replica-survives path of the NN
        reconciliation monitor."""
        dn = self._dn
        held = self._store.get(block_id)
        if held is None:
            raise IOError(f"no segments held for block {block_id}")
        header, segs = held
        k, m = int(header["k"]), int(header["m"])
        if len(segs) < k:
            token = dn.tokens.mint(block_id, "r")
            for dn_id, host, port, _idx in header["peers"]:
                if len(segs) >= k:
                    break
                if dn_id == dn.dn_id:
                    continue
                try:
                    resp = dn._peer_call((host, port), "mirror_segment_read",
                                         block_id=block_id, token=token)
                except (OSError, ConnectionError):
                    continue  # dead peer: parity slack absorbs it
                if resp.get("ok"):
                    for i, d in resp["segments"].items():
                        segs.setdefault(int(i), bytes(d))
        if len(segs) < k:
            _M.incr("assemble_failures")
            raise IOError(f"only {len(segs)} of {k} segments reachable "
                          f"for block {block_id}")
        payload = assemble_payload(segs, k, m, int(header["payload_len"]))
        if int(native.crc32c(payload)) != header["payload_crc"]:
            _M.incr("assemble_failures")
            raise IOError(f"assembled payload for block {block_id} "
                          f"failed CRC")
        self._commit_full(block_id, header, payload)
        self._store.drop(block_id)
        _M.incr("assembles")
        _M.incr("reconciliations")

    def _commit_full(self, block_id: int, header: dict,
                     payload: bytes) -> None:
        """Commit the assembled payload exactly as a full reduced ingest
        would (block_receiver._ingest_reduced_inner's container/index/
        replica sequence, minus the need negotiation)."""
        dn = self._dn
        stored = b""
        if header.get("hashes") is not None:
            hashes = [bytes(h) for h in header["hashes"]]
            uniq = list(dict.fromkeys(hashes))
            chunk_lens = [int(c) for c in header["chunk_lens"]]
            if len(chunk_lens) != len(uniq):
                raise IOError(f"segment header corrupt for block "
                              f"{block_id}: {len(chunk_lens)} chunk lens "
                              f"for {len(uniq)} unique hashes")
            chunks, off = [], 0
            for ln in chunk_lens:
                chunks.append(payload[off:off + ln])
                off += ln
            known = dn.index.lookup_chunks(uniq)
            need = [i for i, h in enumerate(uniq) if known[h] is None]
            locs = dn.containers.append_chunks(
                [chunks[i] for i in need], on_seal=dn.index.seal_container)
            dn.index.commit_block(block_id, int(header["logical_len"]),
                                  hashes,
                                  {uniq[i]: loc
                                   for i, loc in zip(need, locs)})
        else:
            stored = payload
        writer = dn.replicas.create_rbw(block_id, int(header["gen_stamp"]))
        try:
            if stored:
                writer.write(stored)
            meta = writer.finalize(int(header["logical_len"]),
                                   header["scheme"],
                                   [int(c) for c in header["checksums"]],
                                   int(header["checksum_chunk"]))
        except (OSError, ValueError):
            if dn._crashed:
                writer.detach()
            else:
                writer.abort()
            raise
        dn.notify_block_received(block_id, meta.logical_len, meta.gen_stamp)

    # ---------------------------------------------------------- bookkeeping

    def on_full_replica(self, block_id: int) -> None:
        """A full replica just landed locally (re-push upgrade): drop the
        now-redundant segments and account the reconciliation."""
        if self._store.drop(block_id):
            _M.incr("reconciliations")

    def report(self) -> dict:
        """Heartbeat census: what this DN still holds only partially."""
        return {"segments_held": self._store.count(),
                "partial_blocks": len(self._store.blocks())}
