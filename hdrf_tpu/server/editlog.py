"""NameNode persistence: edit log + fsimage checkpoints.

Analog of the reference's FSEditLog (FSEditLog.java:124 — WAL of namespace
mutations, group-committed) and FSImage (FSImage.java:85 — periodic protobuf
snapshot; fsimage.proto).  Same durability discipline as the chunk index
(hdrf_tpu/index/chunk_index.py): log-before-apply, seqno-idempotent replay so
a crash between image publish and WAL truncation cannot double-apply, torn
tails dropped via CRC framing (utils/wal.py).

Checkpointing here is in-process (the SecondaryNameNode / StandbyCheckpointer
roles collapse into one daemon; HA-style shared edits are out of scope for a
single-NN deployment).
"""

from __future__ import annotations

import os
from typing import Any, Callable

import msgpack

from hdrf_tpu.utils import fault_injection, wal as walmod

WAL_NAME = "edits.wal"
IMG_NAME = "fsimage"
IMG_TMP = "fsimage.tmp"


class EditLog:
    def __init__(self, directory: str, checkpoint_every: int = 1000):
        self._dir = directory
        os.makedirs(directory, exist_ok=True)
        self.seq = 0  # last seqno applied (image seq after load)
        self._ops_since_ckpt = 0
        self._checkpoint_every = checkpoint_every
        self._snapshot_fn: Callable[[], Any] | None = None
        self._wal = None  # opened after recovery

    # -------------------------------------------------------------- recovery

    def load_image(self) -> Any | None:
        """Returns the fsimage snapshot (or None) and primes ``seq``."""
        img = os.path.join(self._dir, IMG_NAME)
        if not os.path.exists(img):
            return None
        with open(img, "rb") as f:
            seq, snapshot = msgpack.unpackb(f.read(), raw=False, use_list=True,
                                            strict_map_key=False)
        self.seq = seq
        return snapshot

    def replay(self, apply_fn: Callable[[list], None]) -> int:
        """Replay WAL records newer than the image; returns count applied.
        Call once, after load_image, before open_for_append.  recover()
        truncates any torn tail so open_for_append continues at the good
        prefix (appending behind garbage would lose acked edits)."""
        n = 0
        for payload in walmod.recover(os.path.join(self._dir, WAL_NAME)):
            seq, *rec = msgpack.unpackb(payload, raw=False, use_list=True,
                                        strict_map_key=False)
            if seq > self.seq:
                apply_fn(rec)
                self.seq = seq
                n += 1
        return n

    def open_for_append(self, snapshot_fn: Callable[[], Any]) -> None:
        """``snapshot_fn`` is called at auto-checkpoint time to capture the
        current namespace state."""
        self._snapshot_fn = snapshot_fn
        self._wal = open(os.path.join(self._dir, WAL_NAME), "ab")

    # --------------------------------------------------------------- logging

    def append(self, rec: list) -> None:
        """Durably log one mutation (logSync analog — every record is fsync'd;
        the reference's group commit batching is future work)."""
        payload = msgpack.packb([self.seq + 1, *rec])
        fault_injection.point("editlog.append")
        self._wal.write(walmod.frame(payload))
        self._wal.flush()
        os.fsync(self._wal.fileno())
        self.seq += 1
        self._ops_since_ckpt += 1
        if self._ops_since_ckpt >= self._checkpoint_every:
            self.checkpoint()

    def checkpoint(self) -> None:
        snapshot = self._snapshot_fn() if self._snapshot_fn else None
        tmp = os.path.join(self._dir, IMG_TMP)
        with open(tmp, "wb") as f:
            f.write(msgpack.packb([self.seq, snapshot]))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self._dir, IMG_NAME))
        fault_injection.point("editlog.post_checkpoint")
        if self._wal is not None:
            self._wal.truncate(0)
            self._wal.seek(0)
        self._ops_since_ckpt = 0

    def close(self) -> None:
        if self._wal is not None:
            self._wal.close()
            self._wal = None
