"""NameNode persistence: edit log + fsimage checkpoints.

Analog of the reference's FSEditLog (FSEditLog.java:124 — WAL of namespace
mutations, group-committed) and FSImage (FSImage.java:85 — periodic protobuf
snapshot; fsimage.proto).  Same durability discipline as the chunk index
(hdrf_tpu/index/chunk_index.py): log-before-apply, seqno-idempotent replay so
a crash between image publish and WAL truncation cannot double-apply, torn
tails dropped via CRC framing (utils/wal.py).

Two pieces compose here:

- a **journal backend** (server/journal.py): either the flock-fenced shared
  directory (``LocalJournal``) or the JournalNode quorum (``QuorumJournal``,
  the qjournal re-expression) — selected by ``journal_addrs``.
- **group commit** (the reference's ``FSEditLog.logSync`` design,
  FSEditLog.java:124): mutations buffer under the namesystem lock via
  ``append_async`` and become durable in batches via ``sync`` — the first
  thread to need durability becomes the sync leader and flushes everyone's
  buffered records with ONE backend append (one fsync locally / one quorum
  round), while followers wait on the condition.  Callers that cannot
  tolerate the restructure use ``append`` (= append_async + sync).
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable

import msgpack

from hdrf_tpu.server.journal import (  # noqa: F401  (re-exported API)
    FencedError, JournalGapError, LocalJournal, QuorumJournal,
    QuorumLostError)
from hdrf_tpu.utils import fault_injection

IMG_NAME = "fsimage"
IMG_TMP = "fsimage.tmp"


class EditLog:
    def __init__(self, directory: str, checkpoint_every: int = 1000,
                 journal_addrs: list | None = None):
        """``directory`` holds the fsimage (and, without ``journal_addrs``,
        the shared journal); with ``journal_addrs`` the edits live on that
        JournalNode quorum and only the image is local."""
        self._dir = directory
        os.makedirs(directory, exist_ok=True)
        self.seq = 0            # last seqno applied (image seq after load)
        self._ops_since_ckpt = 0
        self._checkpoint_every = checkpoint_every
        self._snapshot_fn: Callable[[], Any] | None = None
        self.journal = (QuorumJournal(journal_addrs) if journal_addrs
                        else LocalJournal(directory))
        self._appendable = False
        # group-commit state
        self._buf_lock = threading.Lock()
        self._sync_cond = threading.Condition()
        self._buffered: list[bytes] = []
        self._buf_first_seq = 0   # seq of _buffered[0]
        self._buffered_seq = 0    # last buffered seq
        self._durable_seq = 0
        self._syncing = False

    # ----------------------------------------------------------- HA fencing

    def read_epoch(self) -> int:
        return self.journal.read_epoch()

    def claim_epoch(self) -> int:
        return self.journal.claim_epoch()

    # -------------------------------------------------------------- recovery

    def load_image(self) -> Any | None:
        """Returns the fsimage snapshot (or None) and primes ``seq``."""
        img = os.path.join(self._dir, IMG_NAME)
        if not os.path.exists(img):
            return None
        with open(img, "rb") as f:
            seq, snapshot = msgpack.unpackb(f.read(), raw=False, use_list=True,
                                            strict_map_key=False)
        self.seq = seq
        return snapshot

    def replay(self, apply_fn: Callable[[list], None],
               readonly: bool = False) -> int:
        """Replay journal records newer than the image; returns count
        applied.  Call once, after load_image, before open_for_append.  The
        writer path (``readonly=False``) also truncates a torn local tail so
        appends continue at the good prefix; a standby tailer passes
        ``readonly`` — it must never truncate the active's journal and never
        applies past the quorum's committed floor."""
        n = 0
        for payload in self.journal.read(self.seq, readonly=readonly):
            seq, *rec = msgpack.unpackb(payload, raw=False, use_list=True,
                                        strict_map_key=False)
            if seq > self.seq:
                apply_fn(rec)
                self.seq = seq
                n += 1
        if not readonly:
            self._durable_seq = self._buffered_seq = self.seq
            self._buf_first_seq = self.seq + 1
        return n

    def tail(self, apply_fn: Callable[[list], None],
             reload_fn: Callable[[Any], None] | None = None,
             readonly: bool = True) -> int:
        """Standby-side incremental catch-up (EditLogTailer.java:74 analog):
        if a newer fsimage is visible locally (shared-dir deployments: the
        active's checkpoint truncated the journal), reload it first, then
        apply records past ``seq``.

        The final catch-up during promotion passes ``readonly=False``: the
        caller has claimed the epoch and is the sole journal writer (local
        mode additionally truncates a torn tail, without which every
        subsequently acked edit would be unreachable to replay)."""
        img = os.path.join(self._dir, IMG_NAME)
        if os.path.exists(img) and reload_fn is not None:
            with open(img, "rb") as f:
                seq, snapshot = msgpack.unpackb(
                    f.read(), raw=False, use_list=True, strict_map_key=False)
            if seq > self.seq:
                reload_fn(snapshot)
                self.seq = seq
        return self.replay(apply_fn, readonly=readonly)

    def open_for_append(self, snapshot_fn: Callable[[], Any]) -> None:
        """``snapshot_fn`` is called at checkpoint time to capture the
        current namespace state."""
        self._snapshot_fn = snapshot_fn
        self.journal.open_for_append()
        self._appendable = True
        self._durable_seq = self._buffered_seq = self.seq
        self._buf_first_seq = self.seq + 1

    # --------------------------------------------------------------- logging

    def append_async(self, rec: list) -> int:
        """Assign the next seqno and buffer the record; durable only after
        ``sync`` covers the returned seq.  Called under the namesystem lock;
        does NOT touch the journal (that's the whole point: the fsync leaves
        the lock hold time)."""
        fault_injection.point("editlog.append")
        self.journal.check_fence()  # cheap (stat-cached locally; no-op quorum)
        with self._buf_lock:
            seq = self._buffered_seq + 1
            self._buffered.append(msgpack.packb([seq, *rec]))
            self._buffered_seq = seq
        self.seq = seq
        self._ops_since_ckpt += 1
        return seq

    def sync(self, seq: int) -> None:
        """Group commit (logSync): wait until records <= seq are durable.
        The first waiter becomes the leader and appends the WHOLE buffer as
        one backend batch; concurrent waiters ride the same fsync/quorum
        round.  Raises FencedError/QuorumLostError if durability cannot be
        promised — the caller must stop acking and demote."""
        while True:
            with self._sync_cond:
                if self._durable_seq >= seq:
                    return
                if seq > self._buffered_seq:
                    # This instance never buffered `seq` — the caller holds
                    # a pending seq from a PREVIOUS editlog (demotion swap).
                    # Without this check the leader round below would find
                    # an empty buffer and spin forever.
                    raise FencedError(
                        f"seq {seq} was never buffered here (demoted?)")
                if self._syncing:
                    self._sync_cond.wait(timeout=30)
                    continue
                self._syncing = True
            try:
                with self._buf_lock:
                    batch = self._buffered
                    first = self._buf_first_seq
                    last = self._buffered_seq
                    self._buffered = []
                    self._buf_first_seq = last + 1
                if batch:
                    try:
                        self.journal.append_frames(batch, first)
                    except Exception:
                        # Not durable: put the batch back so a retry (or a
                        # later leader) still covers these seqs in order.
                        with self._buf_lock:
                            self._buffered = batch + self._buffered
                            self._buf_first_seq = first
                        raise
                with self._sync_cond:
                    self._durable_seq = max(self._durable_seq, last)
            finally:
                with self._sync_cond:
                    self._syncing = False
                    self._sync_cond.notify_all()

    def append(self, rec: list) -> None:
        """Durably log one mutation (append_async + sync — the non-batched
        compatibility path for callers outside the RPC fast path)."""
        self.sync(self.append_async(rec))

    # ----------------------------------------------------------- checkpoints

    def should_checkpoint(self) -> bool:
        return self._appendable and \
            self._ops_since_ckpt >= self._checkpoint_every

    def checkpoint(self) -> None:
        """Publish an fsimage covering everything durable, then drop the
        covered journal prefix.  MUST be called with all applied records
        already synced (the namespace snapshot must not embed edits the
        journal could lose).  Local mode holds the journal's exclusive lock
        across check + image publish + truncate so a just-fenced old active
        cannot erase edits the new active acked; quorum mode needs no
        global lock — the purge itself is epoch-checked at every node."""
        self.sync(self._buffered_seq)
        with self.journal.exclusive():
            self.journal.check_fence()
            snapshot = self._snapshot_fn() if self._snapshot_fn else None
            self.write_image(self.seq, snapshot)
            fault_injection.point("editlog.post_checkpoint")
            self.journal.purge(self.seq)
        self._ops_since_ckpt = 0

    def write_image(self, seq: int, snapshot: Any) -> None:
        from hdrf_tpu.server.journal import _write_atomic

        _write_atomic(os.path.join(self._dir, IMG_NAME),
                      msgpack.packb([seq, snapshot]))

    def read_image_bytes(self) -> bytes | None:
        """Raw fsimage bytes (standby bootstrap fetch, rpc_fetch_image)."""
        try:
            with open(os.path.join(self._dir, IMG_NAME), "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def write_image_bytes(self, data: bytes) -> None:
        """Install a peer's fsimage (quorum-mode standby that fell behind
        the journal's purge horizon); primes ``seq`` on next load_image."""
        from hdrf_tpu.server.journal import _write_atomic

        _write_atomic(os.path.join(self._dir, IMG_NAME), data)

    def close(self) -> None:
        self.journal.close()
