"""NameNode persistence: edit log + fsimage checkpoints.

Analog of the reference's FSEditLog (FSEditLog.java:124 — WAL of namespace
mutations, group-committed) and FSImage (FSImage.java:85 — periodic protobuf
snapshot; fsimage.proto).  Same durability discipline as the chunk index
(hdrf_tpu/index/chunk_index.py): log-before-apply, seqno-idempotent replay so
a crash between image publish and WAL truncation cannot double-apply, torn
tails dropped via CRC framing (utils/wal.py).

Checkpointing here is in-process (the SecondaryNameNode / StandbyCheckpointer
roles collapse into one daemon; HA-style shared edits are out of scope for a
single-NN deployment).
"""

from __future__ import annotations

import os
from typing import Any, Callable

import msgpack

from hdrf_tpu.utils import fault_injection, wal as walmod

WAL_NAME = "edits.wal"
IMG_NAME = "fsimage"
IMG_TMP = "fsimage.tmp"
EPOCH_NAME = "epoch"


class FencedError(Exception):
    """This NameNode's epoch is stale: another NN has transitioned to active
    (the QJM epoch-fencing analog — writers with an old epoch are rejected)."""


class EditLog:
    def __init__(self, directory: str, checkpoint_every: int = 1000):
        self._dir = directory
        os.makedirs(directory, exist_ok=True)
        self.seq = 0  # last seqno applied (image seq after load)
        self._ops_since_ckpt = 0
        self._checkpoint_every = checkpoint_every
        self._snapshot_fn: Callable[[], Any] | None = None
        self._wal = None  # opened after recovery
        self._epoch: int | None = None  # writer epoch once active
        self._lock_f = None
        self._epoch_cache: int | None = None
        self._epoch_sig = ()

    # ----------------------------------------------------------- HA fencing

    def read_epoch(self) -> int:
        try:
            with open(os.path.join(self._dir, EPOCH_NAME)) as f:
                return int(f.read().strip() or 0)
        except FileNotFoundError:
            return 0

    def claim_epoch(self) -> int:
        """Become the writer: bump the shared epoch under the journal lock
        (serialized against in-flight appends); any previous writer's next
        append sees the newer epoch and gets FencedError."""
        with self._fence_lock():
            e = self.read_epoch() + 1
            tmp = os.path.join(self._dir, EPOCH_NAME + ".tmp")
            with open(tmp, "w") as f:
                f.write(str(e))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, os.path.join(self._dir, EPOCH_NAME))
        self._epoch = e
        return e

    # -------------------------------------------------------------- recovery

    def load_image(self) -> Any | None:
        """Returns the fsimage snapshot (or None) and primes ``seq``."""
        img = os.path.join(self._dir, IMG_NAME)
        if not os.path.exists(img):
            return None
        with open(img, "rb") as f:
            seq, snapshot = msgpack.unpackb(f.read(), raw=False, use_list=True,
                                            strict_map_key=False)
        self.seq = seq
        return snapshot

    def replay(self, apply_fn: Callable[[list], None],
               readonly: bool = False) -> int:
        """Replay WAL records newer than the image; returns count applied.
        Call once, after load_image, before open_for_append.  recover()
        truncates any torn tail so open_for_append continues at the good
        prefix (appending behind garbage would lose acked edits); a standby
        tailer passes ``readonly`` — it must never truncate the active's WAL
        mid-append (the tail it sees as torn may still be in flight)."""
        n = 0
        for payload in walmod.recover(os.path.join(self._dir, WAL_NAME),
                                      truncate=not readonly):
            seq, *rec = msgpack.unpackb(payload, raw=False, use_list=True,
                                        strict_map_key=False)
            if seq > self.seq:
                apply_fn(rec)
                self.seq = seq
                n += 1
        return n

    def tail(self, apply_fn: Callable[[list], None],
             reload_fn: Callable[[Any], None] | None = None,
             readonly: bool = True) -> int:
        """Standby-side incremental catch-up (EditLogTailer.java:74 analog):
        if the active has published a newer fsimage (its checkpoint truncated
        the WAL), reload it first, then apply WAL records past ``seq``.

        A standby tails ``readonly`` (the torn tail it sees may be the
        active's write in flight).  The final catch-up during promotion must
        pass ``readonly=False``: the caller has claimed the epoch and is the
        sole journal writer, and appending behind a torn frame would make
        every subsequently acked edit unreachable to replay (wal.scan stops
        at the first corrupt frame) — silent namespace loss on restart."""
        img = os.path.join(self._dir, IMG_NAME)
        if os.path.exists(img) and reload_fn is not None:
            with open(img, "rb") as f:
                seq, snapshot = msgpack.unpackb(
                    f.read(), raw=False, use_list=True, strict_map_key=False)
            if seq > self.seq:
                reload_fn(snapshot)
                self.seq = seq
        return self.replay(apply_fn, readonly=readonly)

    def open_for_append(self, snapshot_fn: Callable[[], Any]) -> None:
        """``snapshot_fn`` is called at auto-checkpoint time to capture the
        current namespace state."""
        self._snapshot_fn = snapshot_fn
        self._wal = open(os.path.join(self._dir, WAL_NAME), "ab")

    # --------------------------------------------------------------- logging

    def _fence_lock(self):
        """An flock'd context on the shared lock file (persistent handle: the
        append hot path must not pay open/close per op).  Held across
        epoch-check + WAL write so a concurrent claim_epoch (which takes the
        same lock) cannot interleave — without it a fenced writer could slip
        one record into the journal between its check and its write, and its
        seq would collide with the new active's next acked edit."""
        import contextlib
        import fcntl

        if self._lock_f is None or self._lock_f.closed:
            self._lock_f = open(os.path.join(self._dir, "journal.lock"), "a+")

        @contextlib.contextmanager
        def held():
            fcntl.flock(self._lock_f.fileno(), fcntl.LOCK_EX)
            try:
                yield
            finally:
                fcntl.flock(self._lock_f.fileno(), fcntl.LOCK_UN)

        return held()

    def _check_fence(self) -> None:
        """Raise FencedError iff another writer claimed a newer epoch.  The
        epoch value is cached against the file's stat signature so the hot
        path pays one stat, not an open+read."""
        if self._epoch is None:
            return
        path = os.path.join(self._dir, EPOCH_NAME)
        try:
            st = os.stat(path)
            sig = (st.st_mtime_ns, st.st_ino)
        except FileNotFoundError:
            sig = None
        if sig != self._epoch_sig:
            self._epoch_cache = self.read_epoch()
            self._epoch_sig = sig
        if self._epoch_cache != self._epoch:
            raise FencedError(
                f"epoch {self._epoch} superseded by {self._epoch_cache}")

    def append(self, rec: list) -> None:
        """Durably log one mutation (logSync analog — every record is fsync'd;
        the reference's group commit batching is future work)."""
        payload = msgpack.packb([self.seq + 1, *rec])
        fault_injection.point("editlog.append")
        with self._fence_lock():
            self._check_fence()
            self._wal.write(walmod.frame(payload))
            self._wal.flush()
            os.fsync(self._wal.fileno())
        self.seq += 1
        self._ops_since_ckpt += 1
        if self._ops_since_ckpt >= self._checkpoint_every:
            self.checkpoint()

    def checkpoint(self) -> None:
        # Fenced like append: a split-brain old active must never overwrite
        # the fsimage or truncate the shared WAL after a promotion.  The
        # fence lock is held across the WHOLE checkpoint (snapshot, image
        # publish, WAL truncate) — releasing it after the check would let a
        # concurrent claim_epoch land between the check and the truncate,
        # and the old active would then erase edits the new active already
        # fsync'd and acked.
        with self._fence_lock():
            self._check_fence()
            snapshot = self._snapshot_fn() if self._snapshot_fn else None
            tmp = os.path.join(self._dir, IMG_TMP)
            with open(tmp, "wb") as f:
                f.write(msgpack.packb([self.seq, snapshot]))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, os.path.join(self._dir, IMG_NAME))
            fault_injection.point("editlog.post_checkpoint")
            if self._wal is not None:
                self._wal.truncate(0)
                self._wal.seek(0)
        self._ops_since_ckpt = 0

    def close(self) -> None:
        if self._wal is not None:
            self._wal.close()
            self._wal = None
        if self._lock_f is not None:
            self._lock_f.close()
            self._lock_f = None
