"""Block read path: serve logical bytes, reconstructing reduced blocks.

Re-expression of BlockSender.java: the ctor decides whether the block can be
served straight from the replica file or needs reconstruction
(BlockSender.java:306-330 Redis probe -> ``runNormally``), reconstructed
blocks are materialized and served from memory (:612-623) — here
reconstruction is **chunk-granular for range reads** (only containers
overlapping the requested range are touched), fixing the reference's
full-block materialization (SURVEY.md §7 hard part e).

End-to-end integrity: per-checksum-chunk crc32c from BlockMeta rides the op
response header; full-block reads are verified against it server-side before
the bytes hit the wire (BlockScanner-style verification folded into the send
path; the client re-verifies per packet via the transfer framing CRC).
"""

from __future__ import annotations

import socket
import time
from typing import TYPE_CHECKING

from hdrf_tpu.proto import datatransfer as dt
from hdrf_tpu.proto.rpc import send_frame
from hdrf_tpu.utils import metrics, profiler, qos, tenants, tracing

if TYPE_CHECKING:
    from hdrf_tpu.server.datanode import DataNode

_M = metrics.registry("block_sender")
_TR = tracing.tracer("datanode")

# sentinel: "resolve the meta yourself" (None is a real value — PROVIDED
# blocks have no local BlockMeta)
_UNRESOLVED = object()


class BlockSender:
    def __init__(self, dn: "DataNode"):
        self._dn = dn

    def read_logical(self, block_id: int, offset: int = 0,
                     length: int = -1, meta=_UNRESOLVED) -> bytes:
        """Logical bytes of a block, whatever its stored form.  ``meta``
        threads an already-resolved BlockMeta (or None for a PROVIDED
        block) through from serve_read so the replica index is probed once
        per request — the double get_meta used to book a second
        ``index_lookup`` span per read."""
        dn = self._dn
        with profiler.phase("cache_probe"):
            cached = dn.cache.get(block_id, offset, length)
        if cached is not None:
            _M.incr("cached_reads")
            return cached  # pinned logical bytes: no disk, no reconstruction
        if meta is _UNRESOLVED:
            with profiler.phase("index_lookup"):
                meta = dn.replicas.get_meta(block_id)
        if meta is None:
            # PROVIDED replica: bytes live in the external store the alias
            # map points at (FileRegion -> ProvidedStorageLocation)
            with dn.read_slot(), profiler.phase("container_decode"):
                data = dn.aliasmap.read_bytes(block_id, offset, length)
            if data is not None:
                _M.incr("provided_serves")
                return data
            raise KeyError(f"block {block_id} not on this datanode")
        scheme = dn.scheme(meta.scheme)
        with profiler.phase("container_decode"):
            stored = (dn.replicas.read_data(block_id)
                      if meta.physical_len else b"")
        with dn.read_slot():  # admission control (DataXceiver.java:313-347)
            return scheme.reconstruct(block_id, stored, meta.logical_len,
                                      dn.reduction_ctx, offset, length)

    def serve_read(self, sock: socket.socket, fields: dict) -> None:
        """READ_BLOCK op: header frame {status, length, checksums...}, then a
        packet run of the requested byte range."""
        dn = self._dn
        block_id = fields["block_id"]
        offset = fields.get("offset", 0)
        length = fields.get("length", -1)
        tenant = fields.get("_client")
        t_start = time.monotonic()
        with _TR.span("serve_read",
                      parent=tuple(fields["_trace"]) if fields.get("_trace") else None) as sp, \
                profiler.read_timeline(block_id) as tl:
            sp.annotate("block_id", block_id)
            try:
                # Overload gate FIRST (utils/qos.py): over-rate tenants
                # and ops whose deadline budget can't cover the p95
                # estimate are refused here — before the read touches a
                # slot, the cache, or the decode plane — with a structured
                # retryable refusal instead of a mid-pipeline timeout.
                # Unattributed requests (DN-to-DN reconstruction fan-in)
                # are internal and never shed.
                if tenant is not None:
                    dn.qos.admit(tenant, "read")
                # Umbrella phase: gaps between the inner spans (scheme
                # resolution, read-slot admission, the materialize copy)
                # attribute here; nested index_lookup/cache_probe spans
                # still win their intervals (PHASE_ORDER lists them first).
                with qos.bind_tenant(tenant), \
                        profiler.phase("container_decode"):
                    with profiler.phase("index_lookup"):
                        meta = dn.replicas.get_meta(block_id)
                        region = (dn.aliasmap.read(block_id) if meta is None
                                  else None)
                    if meta is None and region is None:
                        raise KeyError(
                            f"block {block_id} not on this datanode")
                    data = self.read_logical(block_id, offset, length,
                                             meta=meta)
                    tl.nbytes = len(data)
            except Exception as e:  # noqa: BLE001 — status crosses the wire
                frame = {"status": 1, "error": type(e).__name__,
                         "message": str(e)}
                retry_after = getattr(e, "retry_after_s", None)
                if retry_after is not None:
                    frame["retry_after_s"] = retry_after
                send_frame(sock, frame)
                if isinstance(e, qos.ShedError):
                    _M.incr("read_sheds")
                else:
                    _M.incr("read_errors")
                tenants.note_op(tenant, "read",
                                latency_s=time.monotonic() - t_start)
                return
            with profiler.phase("net_send"):
                send_frame(sock, {"status": 0, "length": len(data),
                                  "logical_len": (meta.logical_len if meta
                                                  else region.length),
                                  "offset": offset,
                                  "checksum_chunk": (meta.checksum_chunk
                                                     if meta else 64 * 1024),
                                  "checksums": (meta.checksums
                                                if meta else [])})
                dt.stream_bytes(sock, data, dn.config.packet_size)
                _M.incr("blocks_served")
                _M.incr("bytes_served", len(data))
        served_s = time.monotonic() - t_start
        tenants.note_op(tenant, "read", len(data), latency_s=served_s)
        # deficit bucket debit + service estimator feed (utils/qos.py):
        # bytes are only known NOW, so admission charged nothing
        dn.qos.charge(tenant, "read", len(data), latency_s=served_s)
