"""Async multi-block write pipeline: shared device batches for the DN.

The reference's receive path is one-block-at-a-time: DataXceiver threads
buffer independently and each block's reduction runs alone (DDRunner,
DataDeduplicator.java:108-217), so concurrent streams never share device
work and the accelerator idles between per-block dispatches.  This module
is the admission/coalescing stage the vectorized-chunking line needs to
keep the device fed (SURVEY.md §2.1; PERF_NOTES.md round 10 measured the
serial path at 0.0% overlap efficiency):

- ``submit(block_id, data, timeline, tenant)`` hands a fully-buffered
  block to the pipeline and returns a Future of ``(cuts, digests)``.
  Admission is bounded by ``pipeline_max_inflight`` (config.py
  ReductionConfig) — the same bounded-slots discipline the DN's write_slot
  applies to buffering (DataXceiver.java:349-380's gate, applied one stage
  later) — and, when an AdmissionController is installed, gated per tenant
  by utils/qos.py:1 (token-bucket + deadline shed BEFORE a permit is
  held); the coalescer queue is a weighted-fair qos.FairQueue so queued
  blocks drain round-robin across tenants.
- On the TPU backend a single coalescer thread drains queued blocks up to
  ``pipeline_depth`` per round, groups equal lengths, and runs each group
  through ONE ResidentReducer program (ops/resident.py:358 submit_many —
  one prep dispatch, one candidate readback, one digest readback for the
  whole group).  New groups are ENQUEUED before any older group's
  readback is awaited, so device work for block K+1 is in flight while
  block K's host commit (container append, WAL, mirror) runs — the only
  real overlaps on the 1-vCPU DN host (PERF_NOTES.md round 4).
- On the native backend (and at ``pipeline_depth`` 1) ``submit`` computes
  inline on the calling connection thread via ops/dispatch.py:105
  ``chunk_and_fingerprint`` — bit-identical results, today's serial
  behavior, no extra thread hops.
- With ``ReductionConfig.mesh_plane`` on and >1 device attached, the
  coalescer instead drives parallel/sharded.MeshReducer: the whole group
  runs CDC+SHA+dedup-probe as ONE dispatch per mesh step, blocks
  data-parallel across the mesh, and futures resolve
  ``(cuts, digests, probe)`` 3-tuples whose probe set lets dedup_commit
  skip the per-chunk host index walk for probe-negative chunks.
  Mixed-size groups bucket-pad to the next lane size (``_pad_bucket``);
  the padding waste is exported as ``coalesce_pad_bytes``.

Each group's enqueue→finish window is recorded as a ``device_wait`` span
into EVERY member block's timeline (utils/profiler.py BlockTimeline), so
gap_report's per-block overlap accounting sees exactly what the shared
batch hid.  The reducer instance is shared with ops/dispatch.py's
``_resident_cache`` (same ``(cdc, fused-mode)`` key), keeping one jit
cache per configuration process-wide.
"""

from __future__ import annotations

import queue
import threading
from collections import deque
from concurrent.futures import Future

import numpy as np

from hdrf_tpu.ops import dispatch
from hdrf_tpu.utils import metrics, profiler, qos

_M = metrics.registry("write_pipeline")


class _Item:
    __slots__ = ("block_id", "arr", "timeline", "future", "tenant")

    def __init__(self, block_id: int, arr: np.ndarray, timeline,
                 future: Future, tenant: str | None = None) -> None:
        self.block_id = block_id
        self.arr = arr
        self.timeline = timeline
        self.future = future
        self.tenant = tenant


class WritePipeline:
    """Admission + device-batch coalescing for concurrent block writes."""

    def __init__(self, cdc, backend: str, depth: int = 4,
                 max_inflight: int = 8, mesh_plane: bool = False,
                 mesh_lanes: int = 2, mesh_bucket_slots: int = 1 << 15,
                 qos_ctrl=None):
        self._cdc = cdc
        self._backend = backend
        self._depth = max(depth, 1)
        # DN-wide admission gate (utils/qos.py AdmissionController): when
        # installed, submit() sheds over-rate / deadline-doomed tenants
        # BEFORE a pipeline permit is held.
        self._qos = qos_ctrl
        # Mesh-sharded reduction plane (ReductionConfig.mesh_plane): one
        # dispatch per mesh step for the whole coalesced group, dedup probe
        # answered on-mesh.  Futures then resolve (cuts, digests, probe)
        # 3-tuples; None (and 2-tuples) below 2 devices or when disabled.
        self.mesh_reducer = None
        if backend == "tpu" and mesh_plane:
            self.mesh_reducer = dispatch.mesh_reducer(
                cdc, lanes_per_device=mesh_lanes,
                bucket_slots=mesh_bucket_slots)
            if self.mesh_reducer is not None:
                # fill the mesh: a step has ndata*lanes lanes, so the
                # coalescer must be allowed to drain at least that many
                self._depth = max(self._depth, self.mesh_reducer.max_group())
        self._sem = threading.BoundedSemaphore(max(max_inflight, 1))
        # Weighted-fair dequeue (qos.FairQueue, queue.Queue-compatible):
        # per-tenant lanes drain round-robin so a flooding tenant's queued
        # blocks cannot starve a light tenant's (FairCallQueue.java:214).
        self._q = qos.FairQueue()
        self._thread: threading.Thread | None = None
        if backend == "tpu" and self._depth > 1:
            self._thread = threading.Thread(target=self._coalesce_loop,
                                            name="write-pipeline",
                                            daemon=True)
            self._thread.start()

    # ------------------------------------------------------------ admission

    def submit(self, block_id: int, data, timeline=None,
               tenant: str | None = None) -> Future:
        """Reduce ``data`` (host bytes / u8 array); Future resolves to
        ``(cuts, digests)``.  Blocks at the ``pipeline_max_inflight``
        admission bound (backpressure on client streams); sheds (raises
        qos.ShedError) before acquiring a permit when the tenant is over
        rate or the ambient deadline cannot cover the service estimate."""
        arr = (data if isinstance(data, np.ndarray)
               else np.frombuffer(data, dtype=np.uint8))
        if tenant is None:
            tenant = qos.current_tenant()
        # unattributed submits (mirror ingest, re-reduction) are internal
        # relays already admitted at the head DN — never shed them
        if self._qos is not None and tenant is not None:
            self._qos.admit(tenant, "write")
        if not self._sem.acquire(timeout=300):
            raise TimeoutError("write pipeline admission timeout")
        # Permit-leak audit: between acquire and a successfully armed
        # done-callback there is no release path — any raise in that
        # window (Future alloc, callback attach) must hand the permit
        # back inline.  Once the callback is armed, failing the future
        # releases through it.
        try:
            fut: Future = Future()
            fut.add_done_callback(lambda _f: self._sem.release())
        except BaseException:
            self._sem.release()
            raise
        if self._thread is None:
            # Serial/native path: compute on the caller's thread — the
            # native choke point records its own reduce_compute phase.
            _M.incr("inline_reduces")
            try:
                fut.set_result(dispatch.chunk_and_fingerprint(
                    arr, self._cdc, self._backend))
            except BaseException as e:  # noqa: BLE001 — caller unwraps
                fut.set_exception(e)
            return fut
        try:
            self._q.put(_Item(block_id, arr, timeline, fut, tenant))
        except BaseException as e:  # noqa: BLE001 — permit rides the future
            fut.set_exception(e)
            raise
        return fut

    def close(self) -> None:
        if self._thread is not None:
            self._q.put(None)
            self._thread.join()
            self._thread = None

    # ------------------------------------------------------------ coalescer

    def _reducer(self):
        """The dispatch-cache ResidentReducer for this cdc config (shared
        jit cache with the per-block chunk_and_fingerprint path; same key
        shape as ops/dispatch.py chunk_and_fingerprint, including the
        scan-variant flag — an adaptive retune mutating ``self._cdc``
        therefore resolves to a DIFFERENT cached reducer, never mutates a
        constructed one)."""
        from hdrf_tpu.ops.cdc_pallas import cdc_pallas_mode, cdc_skip_ahead
        from hdrf_tpu.ops.resident import ResidentReducer

        key = (self._cdc.mask_bits, self._cdc.min_chunk,
               self._cdc.max_chunk, cdc_pallas_mode(), cdc_skip_ahead())
        r = dispatch._resident_cache.get(key)
        if r is None:
            r = dispatch._resident_cache[key] = ResidentReducer(
                self._cdc, fused_mode=key[3], skip_ahead=key[4])
        return r

    def _drain(self, block: bool) -> tuple[list[_Item], bool]:
        """Up to ``depth`` queued items; ``block`` waits for the first."""
        items: list[_Item] = []
        try:
            first = self._q.get(block=block)
        except queue.Empty:
            return items, False
        if first is None:
            return items, True
        items.append(first)
        while len(items) < self._depth:
            try:
                nxt = self._q.get_nowait()
            except queue.Empty:
                break
            if nxt is None:
                return items, True
            items.append(nxt)
        return items, False

    def _coalesce_loop(self) -> None:
        # The mesh plane supersedes the single-device reducer when present
        # (same submit/start/finish protocol, one dispatch per mesh step)
        # and stays PINNED at its construction geometry — its bucket table
        # holds device state no retune may invalidate.  The single-device
        # reducer is re-resolved per round instead, so an adaptive retune
        # of the shared CdcConfig takes effect at the next group.
        inflight: deque = deque()
        stopping = False
        while True:
            r = self.mesh_reducer or self._reducer()
            if not stopping:
                items, stopping = self._drain(block=not inflight)
                for group in self._group(r, items):
                    try:
                        # ENQUEUE the group's device program now — before
                        # any older group's readback below is awaited.
                        bj = r.submit_many([it.arr for it in group])
                    except BaseException as e:  # noqa: BLE001
                        for it in group:
                            if not it.future.done():
                                it.future.set_exception(e)
                        continue
                    _M.incr("device_batches")
                    _M.observe("device_batch_blocks", len(group))
                    if self.mesh_reducer is not None:
                        _M.incr("mesh_batches")
                        _M.observe("mesh_batch_blocks", len(group))
                    inflight.append((bj, group))
            if not inflight:
                if stopping:
                    return
                continue
            # Finish the OLDEST group only, then loop back to admit newer
            # arrivals: their dispatches enqueue under this group's commit.
            bj, group = inflight.popleft()
            lead = group[0].timeline
            n0 = len(lead.ledger_ids) if lead is not None else 0
            t0 = profiler.mark()
            try:
                # the lead member's timeline is ambient for the readbacks,
                # so the device ledger's hook gives it real device_wait
                # spans + event-id links; they're mirrored to the rest below
                with profiler.bind_timeline(lead):
                    r.start_sha_many(bj)
                    results = r.finish_many(bj)
            except BaseException as e:  # noqa: BLE001
                for it in group:
                    if not it.future.done():
                        it.future.set_exception(e)
                continue
            t1 = profiler.mark()
            new_ids = lead.ledger_ids[n0:] if lead is not None else []
            for idx, (it, res) in enumerate(zip(group, results)):
                tl = it.timeline
                if tl is not None and idx > 0:
                    # shared wait window + ledger links for every member —
                    # the per-block overlap accountant's device_wait input
                    tl.add_span("device_wait", t0, t1, 0)
                    tl.ledger_ids.extend(new_ids)
                it.future.set_result(res)

    @staticmethod
    def _pad_bucket(n: int) -> int:
        """Lane-size bucket for mixed-size coalescing: members of one
        bucket share a device program padded to the longest member, so
        near-sized blocks from different streams batch together instead of
        each drawing its own dispatch (ROADMAP item 1 remainder).
        Geometric 1/8-of-pow2 steps bound worst-case padding at ~12.5%."""
        if n <= 4096:
            return 4096
        top = 1 << (n - 1).bit_length()
        step = max(top // 8, 4096)
        return -(-n // step) * step

    def _group(self, r, items: list[_Item]) -> list[list[_Item]]:
        """Lane-size-bucketed groups bounded by the reducer's max_group;
        padding waste is surfaced as ``coalesce_pad_bytes``."""
        by_bucket: dict[int, list[_Item]] = {}
        for it in items:
            by_bucket.setdefault(self._pad_bucket(it.arr.size),
                                 []).append(it)
        groups: list[list[_Item]] = []
        for bucket, members in by_bucket.items():
            g = max(1, min(self._depth, r.max_group(bucket)))
            for at in range(0, len(members), g):
                grp = members[at:at + g]
                gmax = max(it.arr.size for it in grp)
                pad = sum(gmax - it.arr.size for it in grp)
                if pad:
                    _M.incr("coalesce_pad_bytes", pad)
                groups.append(grp)
        return groups
