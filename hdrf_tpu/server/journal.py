"""Journal backends for the NameNode edit log.

Two interchangeable transports behind ``EditLog`` (server/editlog.py):

- ``LocalJournal`` — a single shared directory with flock-serialized,
  epoch-fenced appends (the NFS-shared-edits deployment; what round 1
  shipped).
- ``QuorumJournal`` + ``JournalNode`` — the re-expression of the reference's
  quorum journal (qjournal/client/QuorumJournalManager.java:55 and
  qjournal/server/JournalNode.java:61, ~6.1 kLoC): N journal daemons, every
  edit batch is durable once a MAJORITY acks, epochs fence stale writers at
  each journal node, and becoming active runs segment recovery (promise
  collection, longest-retained-log selection, re-journaling the tail to
  lagging nodes with divergent-tail truncation).

Protocol invariants the quorum path maintains:

- **Per-node prefix property**: every JournalNode holds a contiguous seq
  range [earliest, last]; batches must chain (``first_seq <= last+1``) or
  the node rejects them as ``behind`` and is caught up from the writer's
  in-memory record cache (or reset past a purge gap).
- **Committed floor**: with per-node prefixes, a record is durable iff it is
  on a majority, so the M-th largest ``last_seq`` (M = majority) bounds what
  a standby may apply — a tailer never applies a record that epoch recovery
  could drop.
- **Divergent tails**: an old epoch's unacked records may survive on a
  minority; a newer-epoch batch overlapping a node's tail truncates that
  tail first (``last_write_epoch`` tracked per node).  Like the reference's
  accepted-recovery, an unacked-but-majority-surviving record may be
  resurrected; an acked record is never lost.
"""

from __future__ import annotations

import contextlib
import os
import threading
from typing import Any

import msgpack

from hdrf_tpu.proto.rpc import RpcClient, RpcError, RpcServer
from hdrf_tpu.utils import fault_injection, metrics
from hdrf_tpu.utils import wal as walmod

_M = metrics.registry("journal")

EPOCH_NAME = "epoch"
WAL_NAME = "edits.wal"


class FencedError(Exception):
    """This writer's epoch is stale: another NN has transitioned to active
    (QJM epoch fencing — journal writes with an old epoch are rejected)."""


class QuorumLostError(Exception):
    """Fewer than a majority of journal nodes acked; the edit is NOT durable
    and the writer must stop acking clients (the reference aborts the NN)."""


def _write_atomic(path: str, data: bytes) -> None:
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


# --------------------------------------------------------------------- local


class LocalJournal:
    """Shared-directory journal: flock-serialized appends, file-based epoch.

    The fence lock is held across epoch-check + write so a concurrent
    ``claim_epoch`` (same lock) cannot interleave — without it a fenced
    writer could slip one record in between its check and its write."""

    def __init__(self, directory: str):
        self._dir = directory
        os.makedirs(directory, exist_ok=True)
        self._epoch: int | None = None
        self._lock_f = None
        self._wal = None
        self._epoch_cache: int | None = None
        self._epoch_sig: Any = ()

    # -- fencing

    def _fence_lock(self):
        import fcntl

        if self._lock_f is None or self._lock_f.closed:
            self._lock_f = open(os.path.join(self._dir, "journal.lock"), "a+")

        @contextlib.contextmanager
        def held():
            fcntl.flock(self._lock_f.fileno(), fcntl.LOCK_EX)
            try:
                yield
            finally:
                fcntl.flock(self._lock_f.fileno(), fcntl.LOCK_UN)

        return held()

    def exclusive(self):
        """Checkpoint-scope mutual exclusion (image publish + purge must be
        atomic vs a concurrent claim_epoch in the shared-dir deployment)."""
        return self._fence_lock()

    def read_epoch(self) -> int:
        try:
            with open(os.path.join(self._dir, EPOCH_NAME)) as f:
                return int(f.read().strip() or 0)
        except FileNotFoundError:
            return 0

    def claim_epoch(self) -> int:
        with self._fence_lock():
            e = self.read_epoch() + 1
            _write_atomic(os.path.join(self._dir, EPOCH_NAME),
                          str(e).encode())
        self._epoch = e
        self._epoch_sig = ()
        return e

    def check_fence(self) -> None:
        """Raise FencedError iff another writer claimed a newer epoch.  The
        epoch value is cached against the file's stat signature so the hot
        path pays one stat, not an open+read."""
        if self._epoch is None:
            return
        path = os.path.join(self._dir, EPOCH_NAME)
        try:
            st = os.stat(path)
            sig = (st.st_mtime_ns, st.st_ino)
        except FileNotFoundError:
            sig = None
        if sig != self._epoch_sig:
            self._epoch_cache = self.read_epoch()
            self._epoch_sig = sig
        if self._epoch_cache != self._epoch:
            raise FencedError(
                f"epoch {self._epoch} superseded by {self._epoch_cache}")

    # -- records

    def open_for_append(self) -> None:
        self._wal = open(os.path.join(self._dir, WAL_NAME), "ab")

    def append_frames(self, payloads: list[bytes], first_seq: int) -> None:
        """Durably append a batch: one write + one fsync under the fence
        lock (the group-commit unit)."""
        buf = b"".join(walmod.frame(p) for p in payloads)
        with self._fence_lock():
            self.check_fence()
            self._wal.write(buf)
            self._wal.flush()
            os.fsync(self._wal.fileno())

    def read(self, after_seq: int, readonly: bool = True) -> list[bytes]:
        """All retained payloads (EditLog filters by seq).  ``readonly=False``
        additionally truncates a torn tail — writer-side recovery only: a
        standby must never truncate what may be the active's in-flight
        append."""
        return walmod.recover(os.path.join(self._dir, WAL_NAME),
                              truncate=not readonly)

    def earliest(self) -> int:
        return 0  # a local WAL is only ever truncated at a checkpoint

    def purge(self, upto_seq: int) -> None:
        """Checkpoint truncation; caller holds ``exclusive()`` and has
        published an image covering ``upto_seq``."""
        if self._wal is not None:
            self._wal.truncate(0)
            self._wal.seek(0)

    def close(self) -> None:
        if self._wal is not None:
            self._wal.close()
            self._wal = None
        if self._lock_f is not None:
            self._lock_f.close()
            self._lock_f = None


# -------------------------------------------------------------- journal node


class JournalNode:
    """One member of the edit-log quorum (JournalNode.java analog).

    Holds a contiguous, CRC-framed record range [earliest, last_seq] plus a
    promised epoch; every accepted batch is fsync'd before the ack (the
    writer's majority-wait is what makes an edit durable)."""

    def __init__(self, directory: str, host: str = "127.0.0.1",
                 port: int = 0):
        self._dir = directory
        from hdrf_tpu.storage import version as storage_version

        storage_version.ensure_layout(directory, "journal",
                                      storage_version.JN_UPGRADERS)
        self._lock = threading.Lock()
        self._promised = self._read_int(EPOCH_NAME, 0)
        self._last_write_epoch = self._read_int("wepoch", 0)
        self._earliest = self._read_int("earliest", 0)  # first retained - 1
        self._records: list[tuple[int, bytes]] = []
        for payload in walmod.recover(os.path.join(directory, WAL_NAME)):
            seq, rec = msgpack.unpackb(payload, raw=False, use_list=False)
            self._records.append((seq, rec))
        self._wal = open(os.path.join(directory, WAL_NAME), "ab")
        self._rpc = RpcServer(host, port, self, "journalnode")

    def start(self) -> "JournalNode":
        self._rpc.start()
        return self

    def stop(self) -> None:
        self._rpc.stop()
        with self._lock:
            self._wal.close()

    @property
    def addr(self) -> tuple[str, int]:
        return self._rpc.addr

    def _read_int(self, name: str, default: int) -> int:
        try:
            with open(os.path.join(self._dir, name)) as f:
                return int(f.read().strip() or default)
        except FileNotFoundError:
            return default

    def _persist_int(self, name: str, value: int) -> None:
        _write_atomic(os.path.join(self._dir, name), str(value).encode())

    def _last_seq(self) -> int:
        return self._records[-1][0] if self._records else self._earliest

    def _rewrite_wal(self) -> None:
        self._wal.close()
        tmp = os.path.join(self._dir, WAL_NAME + ".tmp")
        with open(tmp, "wb") as f:
            for seq, rec in self._records:
                f.write(walmod.frame(msgpack.packb([seq, rec])))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self._dir, WAL_NAME))
        self._wal = open(os.path.join(self._dir, WAL_NAME), "ab")

    # -- rpc surface

    def rpc_jn_state(self) -> dict:
        with self._lock:
            return {"promised": self._promised, "last_seq": self._last_seq(),
                    "earliest": self._earliest,
                    "wepoch": self._last_write_epoch}

    def rpc_jn_new_epoch(self, epoch: int) -> dict:
        """Promise phase: refuse anything not beyond the current promise."""
        with self._lock:
            if epoch <= self._promised:
                raise FencedError(f"promised {self._promised} >= {epoch}")
            self._promised = epoch
            self._persist_int(EPOCH_NAME, epoch)
            return {"last_seq": self._last_seq(), "earliest": self._earliest,
                    "wepoch": self._last_write_epoch}

    def rpc_jn_journal(self, epoch: int, first_seq: int,
                       payloads: list[bytes]) -> dict:
        """Append a batch.  A newer-epoch batch overlapping our tail
        truncates the divergent records first; a batch that would leave a
        gap is refused (the writer catches us up instead)."""
        with self._lock:
            if epoch < self._promised:
                raise FencedError(f"promised {self._promised} > {epoch}")
            self._promised = max(self._promised, epoch)
            last = self._last_seq()
            if first_seq > last + 1 or (
                    epoch != self._last_write_epoch and first_seq == last + 1
                    and self._records):
                # Two refusals share the catch-up path: a genuine gap, and a
                # NON-overlapping first write from a new epoch onto a tail
                # that epoch never validated (missed the claim's recovery) —
                # our tail may hold divergent dead-epoch records, and only
                # an overlapping resend triggers the truncation below.
                # ``wepoch`` tells the writer to resend its whole cache
                # rather than from last+1 (which would preserve the stale
                # prefix under a valid-looking suffix).
                return {"behind": last, "wepoch": self._last_write_epoch}
            if first_seq <= last:
                if epoch == self._last_write_epoch:
                    # same writer resent a durable prefix (catch-up overlap):
                    # drop what we already hold
                    payloads = payloads[last + 1 - first_seq:]
                    first_seq = last + 1
                    if not payloads:
                        return {"last_seq": last}
                else:
                    # divergent tail from a dead epoch: truncate, then accept
                    self._records = [r for r in self._records
                                     if r[0] < first_seq]
                    self._rewrite_wal()
            if epoch != self._last_write_epoch:
                self._last_write_epoch = epoch
                self._persist_int("wepoch", epoch)
            buf = bytearray()
            for i, p in enumerate(payloads):
                self._records.append((first_seq + i, p))
                buf += walmod.frame(msgpack.packb([first_seq + i, p]))
            fault_injection.point("journalnode.append")
            self._wal.write(bytes(buf))
            self._wal.flush()
            os.fsync(self._wal.fileno())
            _M.incr("batches_journaled")
            return {"last_seq": self._last_seq()}

    def rpc_jn_read(self, after_seq: int, limit: int = 5000) -> dict:
        with self._lock:
            out = [(s, p) for s, p in self._records if s > after_seq][:limit]
            return {"records": out, "last_seq": self._last_seq(),
                    "earliest": self._earliest}

    def rpc_jn_purge(self, epoch: int, upto_seq: int) -> bool:
        """Drop records <= upto_seq (the writer checkpointed an image
        covering them)."""
        with self._lock:
            if epoch < self._promised:
                raise FencedError(f"promised {self._promised} > {epoch}")
            if upto_seq <= self._earliest:
                return True
            self._records = [r for r in self._records if r[0] > upto_seq]
            self._earliest = max(self._earliest, upto_seq)
            self._persist_int("earliest", self._earliest)
            self._rewrite_wal()
            _M.incr("purges")
            return True

    def rpc_jn_accept(self, epoch: int, upto_seq: int) -> bool:
        """Claim-recovery epilogue: the new writer validated our tail up to
        ``upto_seq`` (it matches the recovered canon), so adopt the epoch as
        our write epoch — future appends chain without the catch-up dance."""
        with self._lock:
            if epoch < self._promised:
                raise FencedError(f"promised {self._promised} > {epoch}")
            if self._last_seq() <= upto_seq and \
                    epoch != self._last_write_epoch:
                self._last_write_epoch = epoch
                self._persist_int("wepoch", epoch)
            return True

    def rpc_jn_reset(self, epoch: int, earliest: int) -> bool:
        """Writer-directed reset past a gap this node can never fill (its
        missing records were purged after an image covered them)."""
        with self._lock:
            if epoch < self._promised:
                raise FencedError(f"promised {self._promised} > {epoch}")
            self._records = [r for r in self._records if r[0] > earliest]
            if self._records and self._records[0][0] != earliest + 1:
                self._records = []  # still gapped: drop and resync from here
            self._earliest = earliest
            self._persist_int("earliest", earliest)
            self._rewrite_wal()
            return True


# ------------------------------------------------------------------- quorum


class QuorumJournal:
    """Writer/reader client over N JournalNodes (QuorumJournalManager
    analog).  Appends go to every node in parallel; durability = majority
    acks.  Laggards are caught up from the in-memory record cache (bounded:
    the cache is dropped at each checkpoint purge)."""

    def __init__(self, addrs: list[tuple[str, int]], timeout: float = 5.0):
        self._addrs = [tuple(a) for a in addrs]
        self._n = len(self._addrs)
        self._majority = self._n // 2 + 1
        self._timeout = timeout
        self._epoch: int | None = None
        self._recovered_hi = 0
        self._cache: list[tuple[int, bytes]] = []  # since last purge
        self._cache_lock = threading.Lock()
        self._clients: dict[tuple, RpcClient] = {}
        self._client_locks = {a: threading.Lock() for a in self._addrs}

    # -- plumbing

    def _call(self, addr: tuple, method: str, **kw):
        with self._client_locks[addr]:
            c = self._clients.get(addr)
            if c is None:
                c = self._clients[addr] = RpcClient(addr,
                                                    timeout=self._timeout)
            try:
                return c.call(method, **kw)
            except (OSError, ConnectionError):
                self._clients.pop(addr, None)
                c.close()
                raise

    def _fanout(self, method: str, **kw) -> dict[tuple, Any]:
        """Call every node in parallel; map addr -> result | Exception."""
        out: dict[tuple, Any] = {}
        threads = []

        def one(a):
            try:
                out[a] = self._call(a, method, **kw)
            except Exception as e:  # noqa: BLE001 — per-node fault isolation
                out[a] = e

        for a in self._addrs:
            t = threading.Thread(target=one, args=(a,), daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(self._timeout + 1)
        return out

    @staticmethod
    def _is_fenced(r: Any) -> bool:
        return isinstance(r, RpcError) and r.error == "FencedError"

    # -- writer

    def read_epoch(self) -> int:
        rs = self._fanout("jn_state")
        oks = [r for r in rs.values() if isinstance(r, dict)]
        if len(oks) < self._majority:
            raise QuorumLostError(f"{len(oks)}/{self._n} journal nodes up")
        return max(r["promised"] for r in oks)

    def claim_epoch(self) -> int:
        """Promise + recovery: fence out older writers on a majority, pick
        the longest retained log among promisers, re-journal its tail to the
        laggards (truncating divergent dead-epoch tails)."""
        states = self._fanout("jn_state")
        oks = {a: r for a, r in states.items() if isinstance(r, dict)}
        if len(oks) < self._majority:
            raise QuorumLostError(f"{len(oks)}/{self._n} journal nodes up")
        e = max(r["promised"] for r in oks.values()) + 1
        promises = self._fanout("jn_new_epoch", epoch=e)
        prom = {a: r for a, r in promises.items() if isinstance(r, dict)}
        if len(prom) < self._majority:
            raise QuorumLostError(
                f"only {len(prom)}/{self._n} promised epoch {e}")
        self._epoch = e
        self._recovered_hi = 0
        # Recovery (the accepted-recovery simplification of QJM's paxos):
        # the canonical log is the promiser with the newest write epoch,
        # longest log as tiebreak — any record acked by the dead writer is
        # on a majority, every majority intersects the promisers, and the
        # newest-epoch holder's log contains every acked record (older-epoch
        # logs were validated or rewritten by that epoch's own recovery).
        best_addr, best_state = max(
            prom.items(),
            key=lambda kv: (kv[1]["wepoch"], kv[1]["last_seq"]))
        hi = best_state["last_seq"]
        canon: list[tuple[int, bytes]] = []
        after = best_state["earliest"]
        while after < hi:
            r = self._call(best_addr, "jn_read", after_seq=after)
            recs = [(int(s), p) for s, p in r["records"]]
            if not recs:
                break
            canon.extend(recs)
            after = recs[-1][0]
        for a, st in prom.items():
            if a == best_addr or (st["wepoch"] == best_state["wepoch"]
                                  and st["last_seq"] >= hi):
                continue
            # Divergence can hide anywhere a different write epoch touched,
            # so laggards get the WHOLE retained canon with overlap — the
            # node-side truncation rule rewrites their suffix.  A node whose
            # retained range can't overlap the canon (stale prefix below the
            # purge horizon, or a refused non-overlapping chain) is reset
            # first: everything below the canon is committed, image-covered
            # content.
            try:
                if st["wepoch"] != best_state["wepoch"] or \
                        st["last_seq"] < best_state["earliest"]:
                    self._call(a, "jn_reset", epoch=e,
                               earliest=best_state["earliest"])
                if canon:
                    rr = self._call(a, "jn_journal", epoch=e,
                                    first_seq=canon[0][0],
                                    payloads=[p for _, p in canon])
                    if isinstance(rr, dict) and "behind" in rr:
                        self._call(a, "jn_reset", epoch=e,
                                   earliest=canon[0][0] - 1)
                        self._call(a, "jn_journal", epoch=e,
                                   first_seq=canon[0][0],
                                   payloads=[p for _, p in canon])
            except Exception:  # noqa: BLE001 — laggard recovery best-effort
                _M.incr("recovery_catchup_errors")
        # Validate every promiser's (now canonical) tail for this epoch so
        # plain appends chain without the catch-up dance; a node that missed
        # this (or the whole claim) stays unvalidated and gets the
        # whole-cache resend on first contact.
        self._fanout("jn_accept", epoch=e, upto_seq=hi)
        with self._cache_lock:
            self._cache = canon
        self._recovered_hi = hi
        return e

    def check_fence(self) -> None:
        return  # fencing is enforced by the nodes on every append

    def exclusive(self):
        return contextlib.nullcontext()

    def open_for_append(self) -> None:
        return

    def append_frames(self, payloads: list[bytes], first_seq: int) -> None:
        assert self._epoch is not None, "append before claim_epoch"
        with self._cache_lock:
            self._cache.extend(
                (first_seq + i, p) for i, p in enumerate(payloads))
            cache = list(self._cache)
        rs = self._fanout("jn_journal", epoch=self._epoch,
                          first_seq=first_seq, payloads=payloads)
        acks = 0
        for a, r in rs.items():
            if self._is_fenced(r):
                raise FencedError(str(r))
            if isinstance(r, dict) and "behind" in r:
                # Laggard: replay the missing suffix from the cache, then
                # count it if the catch-up covered this batch.  A node whose
                # last write came from an OLDER epoch gets the whole cache —
                # its tail below `behind` may hold divergent dead-epoch
                # records, and only an overlapping batch triggers the
                # node-side truncation that replaces them.
                try:
                    floor = (r["behind"] if r.get("wepoch") == self._epoch
                             else -1)
                    send = [(s, p) for s, p in cache if s > floor]
                    if send:
                        rr = self._call(a, "jn_journal", epoch=self._epoch,
                                        first_seq=send[0][0],
                                        payloads=[p for _, p in send])
                        if isinstance(rr, dict) and "behind" in rr:
                            # The node's records predate the cache (its
                            # missing range was purged into an image):
                            # reset it past the gap, then resend.  Safe —
                            # everything below the cache is committed and
                            # image-covered.
                            self._call(a, "jn_reset", epoch=self._epoch,
                                       earliest=send[0][0] - 1)
                            rr = self._call(a, "jn_journal",
                                            epoch=self._epoch,
                                            first_seq=send[0][0],
                                            payloads=[p for _, p in send])
                        if isinstance(rr, dict) and "behind" not in rr:
                            acks += 1
                except Exception as e:  # noqa: BLE001
                    if self._is_fenced(e):
                        raise FencedError(str(e)) from None
                    _M.incr("catchup_errors")
            elif isinstance(r, dict):
                acks += 1
        if acks < self._majority:
            raise QuorumLostError(
                f"{acks}/{self._n} journal acks for seq {first_seq}")

    # -- reader

    def read(self, after_seq: int, readonly: bool = True) -> list[bytes]:
        """Payloads after ``after_seq``.  A readonly tailer stops at the
        committed floor — the majority-th largest last_seq (with per-node
        prefixes, a record on a majority is exactly one at or below it), so
        it never applies a record epoch recovery could drop.  The writer
        path runs post-claim and is bounded by the RECOVERY CANON, not the
        max reachable last_seq: a node that was down through the claim and
        rejoined may carry unvalidated dead-epoch records above the canon,
        which the writer must not replay (its next append overwrites them
        via the node-side truncation rule instead)."""
        rs = self._fanout("jn_state")
        oks = {a: r for a, r in rs.items() if isinstance(r, dict)}
        if len(oks) < self._majority:
            raise QuorumLostError(f"{len(oks)}/{self._n} journal nodes up")
        # Only nodes on the NEWEST write-epoch lineage are trustworthy: a
        # node that was down through epoch recovery can rejoin holding
        # divergent dead-epoch records at the same seqs (its tail is only
        # truncated by the writer's next overlapping append).  Counting its
        # last_seq toward the floor — or reading from it — would let a
        # standby apply uncommitted records that contradict what the active
        # acked.  Epochs are monotone, so max(wepoch) identifies the canon.
        wmax = max(r["wepoch"] for r in oks.values())
        canon = {a: r for a, r in oks.items() if r["wepoch"] == wmax}
        if readonly:
            if len(canon) < self._majority:
                # can't certify a committed floor from this view (e.g. a
                # brand-new epoch caught up only a minority before we
                # polled): make no progress this tick rather than risk
                # applying an uncommitted record
                return []
            lasts = sorted((r["last_seq"] for r in canon.values()),
                           reverse=True)
            floor = lasts[self._majority - 1]
        else:
            assert self._epoch is not None, "writer read before claim_epoch"
            floor = self._recovered_hi
        out: list[bytes] = []
        cands = [(a, r) for a, r in canon.items() if r["last_seq"] >= floor]
        if not cands:
            # writer path only (readonly floors come FROM canon): a newer
            # claimant's write epoch appeared and none of its nodes cover
            # our recovered range — we are superseded, not merely degraded
            if not readonly and self._epoch is not None \
                    and wmax > self._epoch:
                raise FencedError(
                    f"epoch {self._epoch} superseded by write epoch {wmax}")
            raise QuorumLostError("no journal node holds the committed range")
        src = max(cands, key=lambda kv: kv[1]["last_seq"])[0]
        after = after_seq
        while after < floor:
            r = self._call(src, "jn_read", after_seq=after)
            if r["earliest"] > after:
                # records (after, earliest] were purged into an image this
                # reader doesn't have — silently skipping them would corrupt
                # the replayed namespace
                raise JournalGapError(r["earliest"])
            recs = [(int(s), p) for s, p in r["records"] if int(s) <= floor]
            if not recs:
                break
            out.extend(p for _, p in recs)
            after = recs[-1][0]
        return out

    def earliest(self) -> int:
        rs = self._fanout("jn_state")
        es = [r["earliest"] for r in rs.values() if isinstance(r, dict)]
        if not es:
            raise QuorumLostError("no journal nodes reachable")
        return min(es)

    def purge(self, upto_seq: int) -> None:
        assert self._epoch is not None
        with self._cache_lock:
            self._cache = [(s, p) for s, p in self._cache if s > upto_seq]
        self._fanout("jn_purge", epoch=self._epoch, upto_seq=upto_seq)

    def close(self) -> None:
        for c in self._clients.values():
            c.close()
        self._clients.clear()


class JournalGapError(Exception):
    """The journal's earliest retained record is past what this reader has:
    it must fetch a newer fsimage (from the active peer) before tailing."""
