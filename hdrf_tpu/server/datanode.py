"""DataNode: the data plane daemon.

Re-expression of the reference's DataNode stack — DataNode.java (daemon,
3.7 kLoC), DataXceiverServer.java:44 (accept loop, thread per op),
DataXceiver.java (op dispatch + admission control :313-380), BPServiceActor
(heartbeats + block reports + NN command execution) — around the storage and
reduction layers:

- xceiver loop: thread-per-connection serving WRITE_BLOCK / READ_BLOCK /
  write_reduced / TRANSFER_BLOCK / COPY_BLOCK / BLOCK_CHECKSUM
  (Receiver.java:101-135 dispatch analog)
- write ops route by scheme: ``direct`` -> streaming pipeline; everything
  else -> buffered reduction with reduced block mirroring (block_receiver.py)
- admission control: bounded semaphores per direction, replacing the
  reference's racy static ticket queues (DataXceiver.java:130-133, the
  sleep-loop waits at :313-380)
- heartbeat thread executes NN commands: replicate (DNA_TRANSFER analog ->
  reduced-form push, vs the reference's full-byte reconstruct-and-ship,
  SURVEY.md §3.3 note) and invalidate (delete replica + release chunks)
- block reports: full report on register + periodic; incremental (IBR) on
  every finalize
"""

from __future__ import annotations

import contextlib
import os
import socket
import socketserver
import threading
import time
import uuid
from typing import Iterator

from hdrf_tpu.config import DataNodeConfig
from hdrf_tpu.index.chunk_index import ChunkIndex
from hdrf_tpu.ops import dispatch as ops_dispatch
from hdrf_tpu.proto import datatransfer as dt
from hdrf_tpu.proto.rpc import RpcClient, send_frame
from hdrf_tpu.reduction import scheme as schemes
from hdrf_tpu.reduction.scheme import ReductionContext, ReductionScheme
from hdrf_tpu.server.block_receiver import BlockReceiver
from hdrf_tpu.server.block_sender import BlockSender
from hdrf_tpu.server.status_http import StatusHttpServer
from hdrf_tpu.reduction import accounting
from hdrf_tpu.utils import (device_ledger, fault_injection, flight_archive,
                            flight_recorder, log, metrics, profiler, qos,
                            retry, rollwin, tenants, tracing)
from hdrf_tpu.utils.watchdog import StallWatchdog

_M = metrics.registry("datanode")
_TR = tracing.tracer("datanode")


class PinnedCache:
    """DN-side pinned replica cache (FsDatasetCache.java:67 analog).  The
    reference mmaps + mlocks replica files; here the LOGICAL bytes are
    pinned in RAM (covering reduced blocks too — a cached dedup'd block
    skips reconstruction AND disk), bounded by a byte budget.  Pin/unpin
    is NN-directed via DNA_CACHE/DNA_UNCACHE commands."""

    def __init__(self, capacity: int):
        self._capacity = capacity
        self._lock = threading.Lock()
        self._data: dict[int, bytes] = {}
        self._used = 0

    def set_capacity(self, capacity: int) -> None:
        """Live budget change (dfs.datanode.max.locked.memory is one of
        the reference's reconfigurable keys); shrink evicts nothing —
        pins just stop until usage drains below the new cap."""
        with self._lock:
            self._capacity = capacity

    def pin(self, block_id: int, data: bytes) -> bool:
        with self._lock:
            if block_id in self._data:
                return True
            if self._used + len(data) > self._capacity:
                _M.incr("cache_pin_rejected")
                return False
            self._data[block_id] = data
            self._used += len(data)
            _M.incr("blocks_cached")
            return True

    def unpin(self, block_id: int) -> None:
        with self._lock:
            data = self._data.pop(block_id, None)
            if data is not None:
                self._used -= len(data)
                _M.incr("blocks_uncached")

    def get(self, block_id: int, offset: int = 0,
            length: int = -1) -> bytes | None:
        with self._lock:
            data = self._data.get(block_id)
        if data is None:
            return None
        _M.incr("cache_hits")
        end = len(data) if length < 0 else min(offset + length, len(data))
        return data[offset:end]

    def ids(self) -> list[int]:
        with self._lock:
            return sorted(self._data)

    def used(self) -> int:
        with self._lock:
            return self._used


class DataNode:
    def __init__(self, config: DataNodeConfig, namenode_addr,
                 dn_id: str | None = None):
        """``namenode_addr``: one (host, port) or a list of them — with HA the
        DN reports to EVERY NameNode (the BPOfferService-per-NN pattern: the
        standby needs block reports too, so its block map is warm at
        failover) but executes commands only from the active."""
        self.config = config
        # dn_id is fixed BEFORE the worker wiring below: the DN->worker
        # circuit breaker is registered per edge as "<dn_id>->worker", so
        # MiniCluster DNs sharing one worker address get SEPARATE breakers.
        self.dn_id = dn_id or f"dn-{uuid.uuid4().hex[:8]}"
        self.checksum_chunk = 64 * 1024
        # background-transfer cap (DataTransferThrottler analog): balancer
        # moves, re-replication, EC reconstruction — never client pipelines
        from hdrf_tpu.utils.throttler import Throttler

        self.balance_throttler = Throttler(config.balancer_bandwidth)
        red = config.reduction
        # Layout check/upgrade BEFORE anything opens the store (the
        # reference's Storage.analyzeStorage + doUpgrade at startup): a
        # flat pre-volume dir is migrated to volumes/vol-0 with a
        # rollback snapshot under previous/.
        from hdrf_tpu.storage import version as storage_version

        storage_version.ensure_layout(config.data_dir, "datanode",
                                      storage_version.DN_UPGRADERS)
        backend = ops_dispatch.resolve_backend(red.backend)
        # Seal entropy stage (the reference's rollover LZ4,
        # DataDeduplicator.java:770-781), most-capable-first: the
        # co-located worker process (device-owning; the DN host stays
        # device-free, falling back to the host codec if it dies), else
        # the in-process TPU path, else the host codec default.
        self._worker = None
        self._worker_breaker = None
        self._worker_supervisor = None
        seal_fn = None
        seal_batch_fn = None
        if red.worker_spawn and not red.worker_addr:
            # Supervised co-located worker: the DN owns the process and
            # respawns it with backoff; each respawn repoints the client
            # (fresh ephemeral port) and the breaker's half-open probe
            # re-admits the edge.
            from hdrf_tpu.server.reduction_worker import WorkerSupervisor

            self._worker_supervisor = WorkerSupervisor(
                backend=red.backend,
                base_s=red.worker_respawn_base_s,
                cap_s=red.worker_respawn_cap_s,
                on_respawn=lambda addr: self._worker.set_addr(addr))
            red.worker_addr = list(self._worker_supervisor.start())
        if red.worker_addr:
            from hdrf_tpu.server.reduction_worker import (WorkerClient,
                                                          WorkerError)

            self._worker_breaker = retry.breaker(
                f"{self.dn_id}->worker",
                failure_threshold=red.worker_breaker_failures,
                reset_s=red.worker_breaker_reset_s)
            self._worker = WorkerClient(
                tuple(red.worker_addr),
                deadline_s=red.worker_deadline_s,
                deadline_s_per_mb=red.worker_deadline_s_per_mb,
                breaker=self._worker_breaker)

            def _worker_seal(data: bytes) -> bytes:
                try:
                    return self._worker.compress("lz4", data)
                except (WorkerError, retry.DeadlineExceeded):
                    _M.incr("worker_fallbacks")
                    from hdrf_tpu.utils import codec as codecs

                    return codecs.compress("lz4", data)

            def _worker_seal_batch(datas: list) -> list:
                try:
                    return self._worker.compress_batch("lz4", datas)
                except (WorkerError, retry.DeadlineExceeded):
                    _M.incr("worker_fallbacks")
                    from hdrf_tpu.utils import codec as codecs

                    return [codecs.compress("lz4", d) for d in datas]

            if red.container_codec == "lz4":
                seal_fn = _worker_seal
                seal_batch_fn = _worker_seal_batch
        elif backend == "tpu" and red.container_codec == "lz4":
            seal_fn = (lambda data:
                       ops_dispatch.block_compress("lz4", data, "tpu"))
            seal_batch_fn = (lambda datas:
                             ops_dispatch.block_compress_batch(
                                 "lz4", datas, "tpu"))
        # Volumes (FsVolumeList analog): one ReplicaStore + ContainerStore
        # per configured volume type, replica/chunk placement across them,
        # per-volume failure ejection (storage/volumes.py).
        from hdrf_tpu.storage.volumes import VolumeSet

        self.volume_types = list(config.volume_types
                                 or [config.storage_type])
        self.volumes = VolumeSet(
            config.data_dir, self.volume_types,
            container_kw=dict(container_size=red.container_size,
                              codec=red.container_codec,
                              compress_fn=seal_fn,
                              compress_batch_fn=seal_batch_fn,
                              fsync=red.fsync_containers))
        if config.simulated_dataset:
            from hdrf_tpu.storage.simulated import SimulatedReplicaStore

            self.replicas = SimulatedReplicaStore()
        else:
            self.replicas = self.volumes
        self.containers = self.volumes.containers
        # WAL group-commit window: armed only when the multi-block pipeline
        # is on (depth > 1) — serial writes would just pay the window wait
        self.index = ChunkIndex(
            os.path.join(config.data_dir, "index"),
            group_window_s=(red.group_commit_window_ms / 1000.0
                            if red.pipeline_depth > 1 else 0.0),
            group_max=red.pipeline_max_inflight)
        recon = None
        if red.device_recon and backend == "tpu" and self._worker is None:
            from hdrf_tpu.ops.reconstruct import DeviceReconstructor

            recon = DeviceReconstructor()
            self.containers._on_delete = recon.invalidate
        self.reduction_ctx = ReductionContext(
            config=red, containers=self.containers, index=self.index,
            backend=backend, worker=self._worker, recon=recon)
        # Overload-safety plane (utils/qos.py): one AdmissionController
        # shared by the read and write planes — per-tenant token buckets
        # plus deadline-aware shedding, surfaced on /prom, /health, the
        # flight recorder, and the heartbeat stats.
        self.qos = qos.AdmissionController(
            rate_mb_s=red.qos_tenant_rate_mb_s,
            burst_mb=red.qos_tenant_burst_mb,
            shed_p95_mult=red.shed_p95_mult)
        # Chunk-granular serving engine (server/read_plane.py): shared
        # decoded-chunk cache + coalesced container decodes.  The retire
        # hook drops cached chunks when a container is quarantined or
        # deleted (scrubber/compaction interplay).
        from hdrf_tpu.server.read_plane import ReadPlane

        self.read_plane = ReadPlane(
            self.containers, chunk_cache_mb=red.chunk_cache_mb,
            window_ms=red.read_batch_window_ms,
            max_inflight=red.read_max_inflight, backend=backend,
            qos_ctrl=self.qos)
        self.read_plane.attach_store(self.containers)
        self.reduction_ctx.read_plane = self.read_plane
        # EC cold tier (server/ec_tier.py): stripe store + demote/serve/
        # repair roles; installs the degraded-read fallback hooks on the
        # container stores (AFTER the recon _on_delete wiring above — the
        # tier chains, not replaces, that observer).
        from hdrf_tpu.server.ec_tier import EcTier

        self.ec = EcTier(self)
        # Coded-exchange plane (server/coded_exchange.py): the shared
        # background bulk-transfer sender — QoS control lane + balance
        # throttle + smaller-of LZ4 negotiation — used by EC repair/demote
        # legs and any future rebalance/compaction move.
        from hdrf_tpu.server.coded_exchange import CodedExchange

        self.coded = CodedExchange(self)
        # Multi-block write pipeline (server/write_pipeline.py): shared
        # device batches + overlap scheduling when depth > 1; None keeps
        # the one-block-at-a-time serial path exactly as before.
        self.write_pipeline = None
        # Mesh-sharded reduction plane (parallel/sharded.py): flips the
        # dispatch-layer routing (batched lz4 seals included) and arms the
        # coalescer's MeshReducer below.
        ops_dispatch.set_mesh_plane(red.mesh_plane)
        if red.pipeline_depth > 1:
            from hdrf_tpu.server.write_pipeline import WritePipeline

            self.write_pipeline = WritePipeline(
                red.cdc, backend, depth=red.pipeline_depth,
                max_inflight=red.pipeline_max_inflight,
                mesh_plane=red.mesh_plane,
                mesh_lanes=red.mesh_lanes_per_device,
                mesh_bucket_slots=red.mesh_bucket_slots,
                qos_ctrl=self.qos)
            if self.write_pipeline.mesh_reducer is not None:
                # the device bucket table tracks the authoritative index
                # incrementally: every commit's first-seen fingerprints
                # flow into the next mesh step's refresh dispatch
                self.index.add_commit_listener(
                    self.write_pipeline.mesh_reducer.table.note_new)
            # seal compression off the commit critical path too: an
            # unlucky rollover must not stall the blocks queued behind it
            self.containers.enable_async_seals()
        # Content-adaptive chunk sizing (reduction/accounting.py
        # AdaptiveChunkController): the heartbeat tick feeds it the dedup
        # hit/miss counters; the steps it emits are applied through
        # reconfigure() — the same validated path an operator would use —
        # so geometry never changes behind the config's audit trail.
        self._cdc_controller = None
        if red.cdc_adaptive:
            self._cdc_controller = accounting.AdaptiveChunkController(
                target_mask_bits=red.cdc_target_mask_bits,
                min_size=red.cdc_min_size)
        # Post-retune regression guard (tools/slo_report.py guard): armed
        # after every applied retune with a baseline of recent flight
        # samples; once enough post-retune samples accrue, a regressing
        # window rolls the geometry back through reconfigure().
        self._cdc_guard: dict | None = None
        # Admission control: bounded slots instead of ticket queues.
        self._write_sem = threading.Semaphore(red.max_concurrent_writes)
        self._read_sem = threading.Semaphore(red.max_concurrent_reads)
        self._direct_sem = threading.Semaphore(red.max_concurrent_direct)
        self.cache = PinnedCache(config.cache_capacity)
        # provided storage (aliasmap/InMemoryAliasMap.java): blocks whose
        # bytes live in an external store; persisted regions are reported
        # as PROVIDED replicas and served through the read path
        from hdrf_tpu.storage.aliasmap import InMemoryAliasMap

        self.aliasmap = InMemoryAliasMap(
            os.path.join(config.data_dir, "aliasmap"),
            mount_root=config.provided_mount_root or None)
        from hdrf_tpu.proto.rpc import normalize_addrs

        # Federation (BPOfferService.java:57 per namespace): accept either
        # one nameservice's addr(s) or a LIST of nameservices (list of
        # addr lists).  The DN registers/reports to every NN of every
        # nameservice; block pools are disjoint id ranges, so reports are
        # partitioned per NN by the pool index learned at registration.
        def _is_ns_list(a) -> bool:
            return (isinstance(a, (list, tuple)) and a
                    and isinstance(a[0], (list, tuple)) and a[0]
                    and isinstance(a[0][0], (list, tuple)))

        self._nameservices = ([normalize_addrs(ns) for ns in namenode_addr]
                              if _is_ns_list(namenode_addr)
                              else [normalize_addrs(namenode_addr)])
        self._nns = [RpcClient(a) for ns in self._nameservices for a in ns]
        # RpcClient -> block_pool_index (from registration); None until
        # learned, meaning "send everything, the NN pool-guards anyway"
        self._pool_of: dict[int, int] = {}
        from hdrf_tpu.security import BlockTokenVerifier
        self.tokens = BlockTokenVerifier()
        self._receiver = BlockReceiver(self)
        self._sender = BlockSender(self)
        # coded mirror plane (server/mirror_plane.py): k-of-n segment
        # fan-out with hedged parity legs; mirror_parity=0 degrades to the
        # serial push_reduced relay through this object unchanged
        from hdrf_tpu.server.mirror_plane import MirrorPlane
        self.mirror = MirrorPlane(self)
        # integrity-scrub plane (server/scrubber.py): container/stripe/
        # replica re-verification + garbage census; loop gated on
        # scrub_interval_s > 0, tests drive run_cycle() directly
        from hdrf_tpu.server.scrubber import Scrubber
        self.scrubber = Scrubber(self)
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._ibr_queue: list[tuple[int, int, int, str | None, bool]] = []
        self._ibr_event = threading.Event()
        # Slow-peer detection inputs (DataNodePeerMetrics analog): decayed
        # rolling window of normalized downstream-transfer latencies per
        # peer, plus the same shape per volume over disk-probe durations
        # (DataNodeVolumeMetrics analog).  Both ride heartbeats to the NN.
        self._peer_win = rollwin.WindowMap(window_s=300.0, maxlen=64)
        self._vol_win = rollwin.WindowMap(window_s=300.0, maxlen=64)
        # outright mirror failures per peer (vs merely slow ones above);
        # cumulative counts, shipped in every heartbeat's stats
        self._mirror_fail: dict[str, int] = {}
        self._mirror_fail_lock = threading.Lock()
        self._log = log.get_logger("datanode")
        import time as _time
        # lifeline trigger clocks, PER NN (the reference's lifeline is
        # per-BPServiceActor): a heartbeat landing at one NN must not
        # suppress lifelines to another that is receiving none
        now0 = _time.monotonic()
        self._last_hb_ok = {id(nn): now0 for nn in self._nns}

        # Crash simulation (MiniCluster.kill_datanode): when set, in-flight
        # receivers tear down WITHOUT touching disk (a dead process can't
        # finalize or delete replicas) — see BlockReceiver's teardown.
        self._crashed = False
        self._inflight = 0                       # active xceiver handlers
        self._inflight_cv = threading.Condition()
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:
                outer._conns.add(self.request)
                with outer._inflight_cv:
                    outer._inflight += 1
                try:
                    outer._xceive(self.request)
                finally:
                    outer._conns.discard(self.request)
                    with outer._inflight_cv:
                        outer._inflight -= 1
                        outer._inflight_cv.notify_all()

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = Server((config.host, config.port), Handler)
        self._conns: set[socket.socket] = set()
        # Stall watchdog over in-flight xceiver ops (DataXceiver has no
        # analog; ours exists because the VM's write-burst throttling can
        # stall any op ~35 s — PERF_NOTES round 4) + optional per-daemon
        # status HTTP endpoint (HttpServer2 analog).
        self.watchdog = StallWatchdog(self.dn_id,
                                      budget_s=config.stall_budget_s,
                                      registry=_M)
        # Flight recorder: over-time curve of this DN's key gauges,
        # served as /timeseries (utils/flight_recorder.py); optionally
        # backed by a crash-safe archive so the curve survives restarts
        # (utils/flight_archive.py).
        self.flight_archive = None
        if config.flight_archive_dir:
            arch_dir = config.flight_archive_dir
            if not os.path.isabs(arch_dir):
                arch_dir = os.path.join(config.data_dir, arch_dir)
            self.flight_archive = flight_archive.FlightArchive(
                arch_dir, max_bytes=config.flight_archive_max_mb << 20)
        self.flight = flight_recorder.FlightRecorder(
            self.dn_id, self._flight_sample,
            interval_s=config.flight_interval_s,
            capacity=config.flight_capacity,
            archive=self.flight_archive)
        self._status = None
        if config.status_port is not None:
            self._status = StatusHttpServer(self.dn_id, host=config.host,
                                            port=config.status_port,
                                            watchdog=self.watchdog,
                                            recorder=self.flight)
        from hdrf_tpu.server.shortcircuit import ShortCircuitServer
        self._sc = ShortCircuitServer(
            self, os.path.join(config.data_dir, "sc.sock"))

    # ------------------------------------------------------------ lifecycle

    @property
    def addr(self) -> tuple[str, int]:
        return self._server.server_address

    def start(self) -> "DataNode":
        self._verify_index_containers()
        t = threading.Thread(target=self._server.serve_forever,
                             name=f"{self.dn_id}-xceiver", daemon=True)
        t.start()
        self._threads.append(t)
        self._sc.start()
        self.watchdog.start()
        if self.config.flight_interval_s > 0:
            self.flight.start()
        if self._status is not None:
            self._status.start()
        self._register()
        hb = threading.Thread(target=self._heartbeat_loop,
                              name=f"{self.dn_id}-heartbeat", daemon=True)
        hb.start()
        self._threads.append(hb)
        ll = threading.Thread(target=self._lifeline_loop,
                              name=f"{self.dn_id}-lifeline", daemon=True)
        ll.start()
        self._threads.append(ll)
        ibr = threading.Thread(target=self._ibr_loop,
                               name=f"{self.dn_id}-ibr", daemon=True)
        ibr.start()
        self._threads.append(ibr)
        if self.config.scan_interval_s > 0:
            sc = threading.Thread(target=self._scanner_loop,
                                  name=f"{self.dn_id}-scanner", daemon=True)
            sc.start()
            self._threads.append(sc)
        if self.config.scrub_interval_s > 0:
            sb = threading.Thread(target=self._scrub_loop,
                                  name=f"{self.dn_id}-scrubber", daemon=True)
            sb.start()
            self._threads.append(sb)
        if self.config.volume_check_interval_s > 0 \
                and not self.config.simulated_dataset:
            vc = threading.Thread(target=self._volume_check_loop,
                                  name=f"{self.dn_id}-volcheck", daemon=True)
            vc.start()
            self._threads.append(vc)
        if self.config.lazy_writer_interval_s > 0 \
                and not self.config.simulated_dataset \
                and any(v.storage_type == "RAM_DISK"
                        for v in self.volumes.volumes):
            lw = threading.Thread(target=self._lazy_writer_loop,
                                  name=f"{self.dn_id}-lazywriter",
                                  daemon=True)
            lw.start()
            self._threads.append(lw)
        self._log.info("datanode started", dn_id=self.dn_id,
                       addr=f"{self.addr[0]}:{self.addr[1]}",
                       volumes=len(self.volumes.volumes),
                       backend=self.reduction_ctx.backend)
        return self

    def _lazy_writer_loop(self) -> None:
        """RamDiskAsyncLazyPersistService analog: shadow RAM replicas onto
        DISK, evict persisted ones past the RAM capacity budget."""
        while not self._stop.wait(self.config.lazy_writer_interval_s):
            try:
                self.volumes.lazy_persist_tick(self.config.ram_disk_capacity)
            except Exception:  # noqa: BLE001 — a bad volume must not kill
                _M.incr("lazy_writer_errors")

    def stop(self) -> None:
        self._stop.set()
        self.watchdog.stop()
        self.flight.stop()
        if self.flight_archive is not None:
            self.flight_archive.close()
        if self._status is not None:
            self._status.stop()
        self._sc.stop()
        self._sc.stop_registry()
        self._server.shutdown()
        self._server.server_close()
        self._sever_connections()
        for t in self._threads:
            t.join(timeout=5)
        if self.write_pipeline is not None:
            self.write_pipeline.close()   # before flush: no new dispatches
        self.read_plane.close()           # drain the coalescer's worker
        self.containers.flush_open(on_seal=self.index.seal_container)
        if hasattr(self.containers, "close_async_seals"):
            self.containers.close_async_seals()
        self.index.close()
        if self._worker_supervisor is not None:
            self._worker_supervisor.stop()
        if self._worker is not None:
            self._worker.close()
        for nn in self._nns:
            nn.close()

    def _sever_connections(self) -> None:
        for s in list(self._conns):
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            s.close()

    def await_xceivers(self, timeout: float = 5.0) -> bool:
        """Wait for in-flight xceiver handlers to unwind (severed sockets
        make them exit promptly).  kill_datanode uses this so a restart
        over the same directory never races a dying handler's teardown."""
        with self._inflight_cv:
            return self._inflight_cv.wait_for(
                lambda: self._inflight == 0, timeout)

    # --------------------------------------------------------------- helpers

    def scheme(self, name: str) -> ReductionScheme:
        return schemes.get(name)

    @contextlib.contextmanager
    def write_slot(self) -> Iterator[None]:
        if not self._write_sem.acquire(timeout=300):
            raise TimeoutError("write admission timeout")
        try:
            yield
        finally:
            self._write_sem.release()

    @contextlib.contextmanager
    def direct_slot(self) -> Iterator[None]:
        if not self._direct_sem.acquire(timeout=300):
            raise TimeoutError("direct-write admission timeout")
        try:
            yield
        finally:
            self._direct_sem.release()

    @contextlib.contextmanager
    def read_slot(self) -> Iterator[None]:
        if not self._read_sem.acquire(timeout=300):
            raise TimeoutError("read admission timeout")
        try:
            yield
        finally:
            self._read_sem.release()

    def notify_block_received(self, block_id: int, length: int,
                              gen_stamp: int = -1,
                              storage_type: str | None = None,
                              partial: bool = False) -> None:
        """Incremental block report (IBR) on finalize: queued and delivered
        by a dedicated thread so an unreachable NN can never stall the write
        pipeline's ack (HDFS IBRs are asynchronous for the same reason);
        best-effort — the periodic full report reconciles anything missed.
        Carries the replica's gen stamp so the NN can fence a superseded
        pipeline's late finalize.  ``partial=True`` registers a coded
        mirror SEGMENT (server/mirror_plane.py): never a read location —
        the NN's reconciliation monitor upgrades it in the background."""
        # a (re)finalized replica invalidates any pinned copy: append's
        # copy-on-append rewrites the same block id, and serving the stale
        # pinned bytes would lose the appended region
        self.cache.unpin(block_id)
        # ... and revokes outstanding short-circuit grants for the same
        # reason (a cached client fd still maps the superseded inode)
        self._sc.registry.revoke(block_id)
        if not partial:
            # a FULL replica landing (any path: direct receive, replicate
            # push, ec reconstruct, mirror assemble) shadows any partial
            # mirror segments still held for the block — reclaim them now
            # instead of leaking them as garbage (on_full_replica is
            # idempotent: it only counts when segments were dropped)
            self.mirror.on_full_replica(block_id)
        self._ibr_queue.append((block_id, length, gen_stamp, storage_type,
                                partial))
        self._ibr_event.set()

    def _ibr_loop(self) -> None:
        while not self._stop.is_set():
            self._ibr_event.wait(timeout=0.5)
            self._ibr_event.clear()
            while self._ibr_queue:
                block_id, length, gen_stamp, stype, partial = \
                    self._ibr_queue.pop(0)
                for nn in self._nns:
                    # pool-partitioned like full reports: a foreign NS's
                    # NN would only bounce the IBR off its pool guard
                    pool = self._pool_of.get(id(nn))
                    if pool is not None and block_id >> 48 != pool:
                        continue
                    try:
                        nn.call("block_received", dn_id=self.dn_id,
                                block_id=block_id, length=length,
                                gen_stamp=gen_stamp, storage_type=stype,
                                partial=partial)
                    except (OSError, ConnectionError):
                        _M.incr("ibr_failures")

    # ---------------------------------------------------------- xceiver loop

    def _xceive(self, sock: socket.socket) -> None:
        from hdrf_tpu import security

        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            op, fields = dt.recv_op(sock)
            if op == security.HANDSHAKE_OP:
                # Encrypted connection: run the token-keyed handshake, then
                # read the real op off the AEAD channel.  The authenticated
                # token doubles as the op's token when none is carried.
                sock, hs_token = security.server_handshake(
                    sock, fields, self.tokens._keys)
                op, fields = dt.recv_op(sock)
                fields.setdefault("token", hs_token)
            elif self.config.encrypt_data_transfer:
                _M.incr("plaintext_refused")
                sock.close()
                return  # strict mode: no plaintext ops
        except PermissionError:
            _M.incr("op_auth_failures")
            sock.close()
            return
        except (ConnectionError, OSError):
            sock.close()
            return
        fault_injection.point("datanode.op", op=op)
        trace = fields.get("_trace")
        try:
            if op == "trace_spans":
                # Observability poll (gateway /traces fan-out): serve the
                # local span sink + device ledger, proxying the co-located
                # worker's so callers never need the worker addr.  Served
                # OUTSIDE the xceiver span so polling never pollutes traces.
                self._serve_trace_spans(sock)
                return
            if op == "flight_timeseries":
                # Long-horizon poll (gateway /timeseries?scope=cluster
                # fan-out): ring + archive merged, filtered, tail-limited
                # (utils/flight_archive.py query).  Same no-span rule as
                # trace_spans — polling must not pollute observability.
                send_frame(sock, flight_archive.query(
                    self.flight, self.flight_archive,
                    metric=fields.get("metric"),
                    since=fields.get("since"),
                    limit=int(fields.get("limit") or 2048)))
                return
            with retry.bind_remaining(fields.get(retry.DEADLINE_KEY)), \
                    self.watchdog.track(f"xceiver.{op}"), \
                    _TR.span(f"xceiver.{op}",
                             parent=tuple(trace) if trace else None) as sp:
                sp.annotate("dn_id", self.dn_id)
                self._dispatch_op(sock, op, fields)
        except PermissionError:
            _M.incr("op_auth_failures")
        except qos.ShedError:
            # admission refusals are intentional overload behavior, not
            # op failures — ShedError subclasses IOError, so this clause
            # must sit ABOVE the OSError arm to keep the books honest
            _M.incr("op_sheds")
        except (ConnectionError, OSError):
            _M.incr("op_io_errors")
        except Exception:  # noqa: BLE001 — xceiver thread must not die silently
            _M.incr("op_errors")
        finally:
            sock.close()

    def _serve_trace_spans(self, sock: socket.socket) -> None:
        out = {"daemon": self.dn_id,
               "spans": tracing.all_span_snapshots(),
               "ledger": device_ledger.events_snapshot(),
               "counters": profiler.counters_snapshot()}
        if self._worker is not None:
            from hdrf_tpu.server.reduction_worker import WorkerError

            try:
                w = self._worker.traces()
                out["spans"] = out["spans"] + list(w.get("spans") or ())
                out["ledger"] = out["ledger"] + list(w.get("ledger") or ())
                out["counters"] = (out["counters"]
                                   + list(w.get("counters") or ()))
            except (WorkerError, ConnectionError, OSError,
                    retry.DeadlineExceeded) as e:
                # worker down: local view still serves
                _M.incr("worker_trace_failures")
                self._log.warning("worker trace poll failed",
                                     dn_id=self.dn_id,
                                     trace=tracing.current_context(),
                                     error=f"{type(e).__name__}: {e}")
        send_frame(sock, out)

    def _dispatch_op(self, sock: socket.socket, op, fields: dict) -> None:
        """Xceiver op chain (Receiver.java:101-135 dispatch analog).  The
        caller (_xceive) owns the socket lifetime, the xceiver span, the
        watchdog tracking and the exception accounting."""
        if op == dt.WRITE_BLOCK:
            self.tokens.verify(fields.get("token"), fields["block_id"], "w")
            t_start = time.monotonic()
            if fields["scheme"] == "direct":
                self._receiver.receive_direct(sock, fields)
            else:
                self._receiver.receive_reduced(sock, fields)
            if fields.get("_client"):
                meta = self.replicas.get_meta(fields["block_id"])
                tenants.note_op(fields["_client"], "write",
                                meta.logical_len if meta else 0,
                                latency_s=time.monotonic() - t_start)
        elif op == "write_reduced":
            self.tokens.verify(fields.get("token"), fields["block_id"], "w")
            self._receiver.ingest_reduced(sock, fields)
        elif op == "mirror_segment":
            # coded mirror plane leg: one RS segment of the reduced
            # payload (server/mirror_plane.py); write-gated like any
            # other ingest
            self.tokens.verify(fields.get("token"), fields["block_id"], "w")
            self.mirror.serve_segment(sock, fields)
        elif op == "mirror_segment_read":
            # peer gather leg of a partial-replica assembly
            self.tokens.verify(fields.get("token"), fields["block_id"], "r")
            self.mirror.serve_segment_read(sock, fields)
        elif op == dt.READ_BLOCK:
            self.tokens.verify(fields.get("token"), fields["block_id"], "r")
            self._sender.serve_read(sock, fields)
        elif op == dt.BLOCK_CHECKSUM:
            self.tokens.verify(fields.get("token"), fields["block_id"],
                               "r")
            self._serve_checksum(sock, fields)
        elif op == "replica_info":
            self.tokens.verify(fields.get("token"), fields["block_id"], "r")
            meta = self.replicas.get_meta(fields["block_id"])
            send_frame(sock, {"length": meta.logical_len if meta else -1,
                              "gen_stamp": meta.gen_stamp if meta else -1,
                              "rbw": self.replicas.is_rbw(
                                  fields["block_id"])})
        elif op == "alias_add":
            # provided-storage mount push (the live-cluster form of
            # the reference's offline alias-map generation): persist
            # the regions, report them immediately via IBR.  Gated by
            # per-region WRITE block tokens (minted by the superuser-
            # only rpc_provide_file) — without the check, anyone with
            # DN network access could repoint provided blocks at
            # arbitrary local files
            from hdrf_tpu.storage.aliasmap import FileRegion
            regions = [FileRegion.unpack(v) for v in fields["regions"]]
            tokens = fields.get("tokens") or [None] * len(regions)
            for reg, tok in zip(regions, tokens):
                self.tokens.verify(tok, reg.block_id, "w")
            try:
                for reg in regions:
                    self.aliasmap.check_uri(reg.uri)
            except IOError as e:
                _M.incr("alias_rejects")
                send_frame(sock, {"ok": False, "error": str(e)})
                return
            self.aliasmap.write(regions)
            for reg in regions:
                self.notify_block_received(reg.block_id, reg.length, 0,
                                           storage_type="PROVIDED")
            send_frame(sock, {"ok": True, "count": len(regions)})
        elif op == "reconfigure":
            send_frame(sock, self.reconfigure(fields.get("key", ""),
                                              fields.get("value")))
        elif op == "get_reconfigurable":
            send_frame(sock, {"keys": sorted(self.RECONFIGURABLE)})
        elif op == "disk_balance":
            # intra-DN volume evening (diskbalancer -plan/-execute in
            # one round trip; like the DN protocol, trusted within the
            # deployment perimeter rather than block-token gated)
            plan = self.volumes.plan_moves(
                float(fields.get("threshold", 0.10)))
            moved = self.volumes.execute_moves(plan)
            send_frame(sock, {
                "planned": len(plan), "moved": moved,
                "volumes": [{"vol": v.vol_id, "type": v.storage_type,
                             "used": v.used_bytes(),
                             "failed": v.failed}
                            for v in self.volumes.volumes]})
        elif op == "truncate_replica":
            self.tokens.verify(fields.get("token"), fields["block_id"], "w")
            ok = self.replicas.truncate_replica(
                fields["block_id"], fields["length"],
                new_gs=fields.get("new_gen_stamp"))
            send_frame(sock, {"ok": ok})
        elif op == dt.STRIPE_READ:
            # EC cold tier: hand one local stripe to a gathering peer
            # (DN-protocol trust, like disk_balance — stripe ops never
            # carry client bytes, only already-stored container stripes)
            self.ec.serve_read(sock, fields)
        elif op == dt.STRIPE_WRITE:
            self.ec.serve_write(sock, fields)
        elif op == dt.STRIPE_CODED_READ:
            # coded-exchange partial-sum repair hop (server/ec_tier.py
            # serve_coded_read; same DN-protocol trust as stripe_read)
            self.ec.serve_coded_read(sock, fields)
        else:
            _M.incr("unknown_ops")

    def _serve_checksum(self, sock: socket.socket, fields: dict) -> None:
        from hdrf_tpu.proto.rpc import send_frame

        meta = self.replicas.get_meta(fields["block_id"])
        if meta is None:
            # PROVIDED replica: no stored chunk CRCs — compute them from
            # the external bytes (BlockChecksumHelper recomputes for
            # replicas without meta the same way)
            data = self.aliasmap.read_bytes(fields["block_id"])
            if data is not None:
                from hdrf_tpu import native
                crcs = [int(c) for c in native.crc32c_chunks(
                    data, self.checksum_chunk)]
                send_frame(sock, {"status": 0,
                                  "checksum_chunk": self.checksum_chunk,
                                  "checksums": crcs,
                                  "logical_len": len(data)})
                return
            send_frame(sock, {"status": 1, "error": "KeyError",
                              "message": "no such block"})
            return
        send_frame(sock, {"status": 0, "checksum_chunk": meta.checksum_chunk,
                          "checksums": meta.checksums,
                          "logical_len": meta.logical_len})

    # ------------------------------------------------------- NN interaction

    def _register(self, nn: RpcClient | None = None) -> None:
        """Per-NN error isolation: one dead NN (e.g. the old active after a
        failover) must not block registration/reports to the live ones."""
        ok = 0
        for c in ([nn] if nn else self._nns):
            try:
                resp = c.call("register_datanode", dn_id=self.dn_id,
                              addr=list(self.addr), sc_path=self._sc.path,
                              rack=self.config.rack,
                              storage_type=self.volume_types[0],
                              storage_types=self.volume_types)
                if resp.get("block_keys"):
                    self.tokens.update_keys(resp["block_keys"])
                if "block_pool_index" in resp:
                    self._pool_of[id(c)] = int(resp["block_pool_index"])
                self._send_block_report(c)
                ok += 1
            except (OSError, ConnectionError):
                _M.incr("register_failures")
                self._log.warning("namenode registration failed",
                                  dn_id=self.dn_id, namenode=c.addr)
        if ok == 0 and nn is None:
            raise ConnectionError("no namenode reachable at registration")

    def _send_block_report(self, nn: RpcClient | None = None) -> None:
        report = [list(t) for t in self.replicas.block_report()]
        report.extend([r.block_id, 0, r.length, "PROVIDED"]
                      for r in self.aliasmap.list())
        for c in ([nn] if nn else self._nns):
            pool = self._pool_of.get(id(c))
            rows = (report if pool is None
                    else [t for t in report if t[0] >> 48 == pool])
            try:
                c.call("block_report", dn_id=self.dn_id, blocks=rows)
            except (OSError, ConnectionError):
                if nn is not None:
                    raise  # caller handles (registration path)
                _M.incr("block_report_failures")

    def _heartbeat_loop(self) -> None:
        interval = self.config.heartbeat_interval_s
        last_report = 0.0
        import time as _time

        while not self._stop.wait(interval):
            fault_injection.point("datanode.heartbeat", dn_id=self.dn_id)
            self._cdc_tick()
            stats = self._stats()
            for nn in self._nns:
                try:
                    resp = nn.call("heartbeat", dn_id=self.dn_id, stats=stats)
                    self._last_hb_ok[id(nn)] = _time.monotonic()
                    if resp.get("block_keys"):
                        self.tokens.update_keys(resp["block_keys"])
                    if resp.get("reregister"):
                        self._register(nn)
                        continue
                    # only the active commands; a standby answers with none
                    for cmd in resp.get("commands", []):
                        self._execute(cmd)
                except (OSError, ConnectionError):
                    _M.incr("heartbeat_failures")
                except Exception:  # noqa: BLE001
                    _M.incr("command_errors")
            now = _time.monotonic()
            if now - last_report > self.config.block_report_interval_s:
                try:
                    self._send_block_report()
                except (OSError, ConnectionError):
                    _M.incr("heartbeat_failures")
                last_report = now

    def _cdc_tick(self) -> None:
        """Adaptive-chunking control step (heartbeat cadence): feed the
        controller the cumulative dedup counters; apply whatever ordered
        reconfigure steps it emits through the SAME validated reconfigure
        path an operator uses.  A rejected step (bounds, transient
        min>max the ordering should have prevented) abandons the retune —
        the controller re-decides next window from fresh evidence.

        Every APPLIED retune arms the regression guard (ROADMAP item 5's
        "a bad retune rolls itself back"): the flight ring's most recent
        samples become the baseline; once enough post-retune samples
        accrue, tools/slo_report.py's guard() compares the windows and a
        direction-aware regression reverts the geometry through the same
        reconfigure path, counts ``retune_rollbacks``, and holds the
        controller for two observation windows so the loop cannot flap."""
        ctl = self._cdc_controller
        if ctl is None:
            return
        self._cdc_guard_tick(ctl)
        hit, miss = accounting.dedup_counters()
        cdc = self.reduction_ctx.config.cdc
        old_bits = cdc.mask_bits
        steps = ctl.observe(hit, miss, old_bits)
        applied = False
        for key, value in steps:
            r = self.reconfigure(key, value)
            if not r.get("ok"):
                _M.incr("cdc_retune_rejected")
                self._log.warning("cdc retune step %s=%s rejected: %s",
                                  key, value, r.get("error"))
                return
            accounting.record_retune(key, r["old"], r["new"])
            applied = True
        if applied:
            self._arm_cdc_guard(old_bits, self.reduction_ctx.config.cdc.mask_bits)

    GUARD_GAUGES = ("dedup_ratio", "storage_ratio",
                    "write_p95_ms", "read_p95_ms")
    GUARD_MIN_SAMPLES = 3

    def _arm_cdc_guard(self, old_bits: int, new_bits: int) -> None:
        samples = self.flight.snapshot()["samples"]
        self._cdc_guard = {
            "old_bits": int(old_bits), "new_bits": int(new_bits),
            "baseline": samples[-8:],
            "armed_mono": samples[-1]["mono"] if samples else 0.0}

    def _cdc_guard_tick(self, ctl) -> None:
        """Evaluate an armed retune guard once enough post-retune flight
        samples exist; regress -> revert geometry + hold the controller."""
        guard = self._cdc_guard
        if guard is None or not guard["baseline"]:
            return
        from hdrf_tpu.tools import slo_report

        samples = self.flight.snapshot()["samples"]
        current = [s for s in samples if s["mono"] > guard["armed_mono"]]
        if len(current) < self.GUARD_MIN_SAMPLES:
            return
        self._cdc_guard = None
        verdict = slo_report.guard(guard["baseline"], current,
                                   gauges=self.GUARD_GAUGES)
        if not verdict["regressed"]:
            return
        for key, value in ctl.steps(guard["new_bits"], guard["old_bits"]):
            r = self.reconfigure(key, value)
            if not r.get("ok"):
                _M.incr("cdc_retune_rejected")
                return
        accounting.record_retune_rollback()
        ctl.note_rollback()
        _M.incr("cdc_guard_rollbacks")
        self._log.warning("cdc retune rolled back by regression guard",
                          dn_id=self.dn_id,
                          regressions=[r["metric"]
                                       for r in verdict["rows"]
                                       if r.get("regressed")])

    def _lifeline_loop(self) -> None:
        """DatanodeLifelineProtocol analog: a LOW-COST liveness-only
        channel that keeps a loaded/stalled DN from being declared dead.
        Fires only while the full heartbeat is overdue (the reference
        sends lifelines whenever the service actor falls behind); the
        NN's rpc_lifeline touches the liveness clock and nothing else —
        no stats processing, no command queue, so it stays cheap exactly
        when the node is struggling."""
        import time as _time

        interval = self.config.heartbeat_interval_s
        while not self._stop.wait(interval):
            now = _time.monotonic()
            for nn in self._nns:
                if now - self._last_hb_ok[id(nn)] <= 2 * interval:
                    continue   # heartbeats flowing TO THIS NN: idle
                try:
                    resp = nn.call("lifeline", dn_id=self.dn_id)
                    _M.incr("lifelines_sent")
                    if resp.get("reregister"):
                        # the NN restarted during the stall and has
                        # forgotten us: a liveness touch on an unknown
                        # dn_id keeps nothing alive
                        self._register(nn)
                except (OSError, ConnectionError):
                    _M.incr("lifeline_failures")

    def note_peer_latency(self, dn_id: str, s_per_mb: float) -> None:
        self._peer_win.note(dn_id, s_per_mb)

    def note_mirror_failure(self, dn_id: str) -> None:
        """A pipeline mirror to ``dn_id`` failed outright (vs merely slow):
        counted per peer and shipped in the next heartbeat's stats so the
        NN's outlier detector sees BROKEN mirrors within two heartbeats."""
        with self._mirror_fail_lock:
            self._mirror_fail[dn_id] = self._mirror_fail.get(dn_id, 0) + 1

    @property
    def reduction_degraded(self) -> bool:
        """True while the DN->worker edge is not fully admitted (breaker
        open or probing): writes still succeed via in-process passthrough,
        but the node is running without its co-located reduction worker."""
        return (self._worker_breaker is not None
                and self._worker_breaker.state != "closed")

    def note_volume_latency(self, vol_id: int, seconds: float) -> None:
        """Disk-probe / IO duration sample for slow-volume detection
        (DataNodeVolumeMetrics feeding SlowDiskTracker)."""
        self._vol_win.note(int(vol_id), seconds)

    def _peer_report(self) -> dict:
        """dn_id -> (median s/MB, samples) — rides heartbeats to the NN
        (SlowPeerReports analog)."""
        return {d: [s["median"], s["count"]]
                for d, s in self._peer_win.summaries().items()}

    def peer_latency_summaries(self) -> dict:
        """dn_id -> full rolling-window summary (median/mean/max/p95 s/MB)
        — the coded mirror plane's hedge-deadline input (it scales the
        p95 by mirror_hedge_p95_mult; utils/rollwin.py:58)."""
        return self._peer_win.summaries()

    def _volume_report(self) -> dict:
        """vol_id -> health + IO summary, riding heartbeats (the
        VolumeFailureSummary + SlowDiskReports payload, folded into one)."""
        probes = self._vol_win.summaries()
        out = {}
        for v in self.volumes.volumes:
            p = probes.get(v.vol_id)
            out[str(v.vol_id)] = {
                "storage_type": v.storage_type,
                "failed": v.failed,
                "used_bytes": 0 if v.failed else v.used_bytes(),
                "probe_median_s": p["median"] if p else None,
                "probe_count": p["count"] if p else 0,
            }
        return out

    def _reduction_report(self) -> dict:
        """Per-DN reduction-effectiveness aggregate: chunk-index truth
        (logical vs unique bytes, refcount histogram), container
        utilization deciles, and the process accounting counters.  Pure
        host-side table reads — no device work."""
        acc = self.index.accounting()
        live = self.index.container_live_bytes()
        sizes = {}
        if not self.config.simulated_dataset:
            try:
                sizes = self.containers.container_sizes()
            except OSError:
                pass
        return {
            "logical_bytes": acc["logical_bytes"],
            "unique_chunk_bytes": acc["unique_chunk_bytes"],
            "dedup_ratio": accounting.dedup_ratio(
                acc["logical_bytes"], acc["unique_chunk_bytes"]),
            "refcount_hist": acc["refcount_hist"],
            "container_util_hist": accounting.utilization_hist(live, sizes),
            "counters": accounting.snapshot(),
        }

    def _read_plane_report(self) -> dict:
        """Serving-path aggregate riding heartbeats to /health: decoded-
        container + decoded-chunk cache hit ratios, per-scheme read
        amplification, and the per-tenant rolling SLO summaries
        (utils/tenants.py)."""
        from hdrf_tpu.server import read_plane as read_plane_mod
        from hdrf_tpu.storage import container_store

        return {
            "container_cache_hit_ratio": container_store.cache_hit_ratio(),
            "chunk_cache_hit_ratio": read_plane_mod.chunk_cache_hit_ratio(),
            "chunk_cache_bytes": self.read_plane.cache.bytes_used,
            "read_amplification": accounting.read_amplification_report(),
            "tenants": tenants.summaries(),
            "qos": self.qos.report(),
        }

    @staticmethod
    def _hist_quantile_ms(reg_name: str, key: str, q: float = 0.95) -> float:
        """p-quantile (ms) of one registry histogram, 0.0 when absent."""
        reg = metrics.registry(reg_name)
        with reg._lock:
            h = reg._histograms.get(key)
            return (h.quantile(q) / 1e3) if h is not None else 0.0

    def _flight_sample(self) -> dict:
        """The flight recorder's gauge set — the ~dozen numbers whose
        over-time curve is the honest production story (ROADMAP item 3):
        storage/dedup ratios, cache hit rate, read/write p95, inflight
        ops, breaker states."""
        from hdrf_tpu.server import read_plane as read_plane_mod
        from hdrf_tpu.storage import container_store

        acc = self.index.accounting()
        logical = sum(m[2] for m in self.replicas.block_report())
        physical = (self.replicas.physical_bytes()
                    + self.containers.physical_bytes()
                    + self.ec.store.physical_bytes())
        brs = retry.all_breakers().values()
        states = [b.state for b in brs]
        with self._inflight_cv:
            inflight = self._inflight
        return {
            "storage_ratio": (physical / logical) if logical else 0.0,
            "dedup_ratio": accounting.dedup_ratio(
                acc["logical_bytes"], acc["unique_chunk_bytes"]),
            "container_cache_hit_ratio": container_store.cache_hit_ratio(),
            "chunk_cache_hit_ratio": read_plane_mod.chunk_cache_hit_ratio(),
            "read_p95_ms": self._hist_quantile_ms("read_profiler",
                                                  "read_wall_us"),
            "write_p95_ms": self._hist_quantile_ms("write_profiler",
                                                   "block_wall_us"),
            "inflight": inflight,
            "blocks": len(self.replicas.block_ids()),
            "stalls": self.watchdog.stall_count(),
            "breakers_open": sum(1 for s in states if s == "open"),
            "breakers_half_open": sum(1 for s in states
                                      if s == "half_open"),
            "tenant_count": tenants.tenant_count(),
            # overload plane (ISSUE 14): shed growth is the regression
            # curve — a healthy cluster sheds ~0; the retry-after p50
            # shows whether hints track the actual recovery horizon
            "sheds_total": self.qos.sheds_total(),
            "shed_retry_after_p50_ms": self.qos.shed_retry_after_p50_ms(),
            # integrity-drift curve (ISSUE 12 satellite: garbage growth
            # and corruption rate belong in the /timeseries regressions)
            "garbage_bytes": sum(self.scrubber._last_census.values()),
            "scrub_corrupt_total": self.scrubber.corrupt_total(),
        }

    def _stats(self) -> dict:
        with self._mirror_fail_lock:
            mirror_failures = dict(self._mirror_fail)
        return {
            "reduction_degraded": self.reduction_degraded,
            "mirror_failures": mirror_failures,
            "peer_transfer": self._peer_report(),
            "volumes": self._volume_report(),
            "reduction": self._reduction_report(),
            "read_plane": self._read_plane_report(),
            "stalls": self.watchdog.stall_count(),
            "blocks": len(self.replicas.block_ids()),
            "logical_bytes": sum(m[2] for m in self.replicas.block_report()),
            "physical_bytes": (self.replicas.physical_bytes()
                               + self.containers.physical_bytes()
                               + self.ec.store.physical_bytes()),
            "cached_blocks": self.cache.ids(),
            "cache_used": self.cache.used(),
            "index": self.index.stats(),
            "ec": self.ec.report(),
            "mirror": self.mirror.report(),
            "scrub": self.scrubber.report(),
            "qos": self.qos.report(),
        }

    def _execute(self, cmd: dict) -> None:
        """NN command execution (BPServiceActor.processCommand analog)."""
        if cmd["cmd"] == "invalidate":
            # provided entries purge as ONE map rewrite, not one per
            # block (each remove persists + fsyncs the whole map)
            prov = [b for b in cmd["block_ids"]
                    if self.aliasmap.read(b) is not None]
            if prov:
                self.aliasmap.remove(prov)
            for bid in cmd["block_ids"]:
                self._invalidate(bid)
        elif cmd["cmd"] == "replicate":
            self._replicate(cmd)
        elif cmd["cmd"] == "ec_reconstruct":
            self._ec_reconstruct(cmd)
        elif cmd["cmd"] == "stripe_demote":
            self.ec.demote(cmd)
        elif cmd["cmd"] == "stripe_repair":
            self.ec.repair(cmd)
        elif cmd["cmd"] == "mirror_assemble":
            # no full replica survives: assemble one from any k coded
            # segments gathered off peers (server/mirror_plane.py)
            self.mirror.assemble(cmd["block_id"])
        elif cmd["cmd"] == "recover_block":
            self._recover_block(cmd)
        elif cmd["cmd"] == "cache":
            for bid in cmd["block_ids"]:
                if self.replicas.get_meta(bid) is not None:
                    self.cache.pin(bid, self._sender.read_logical(bid))
        elif cmd["cmd"] == "uncache":
            for bid in cmd["block_ids"]:
                self.cache.unpin(bid)
        elif cmd["cmd"] == "balancer_bandwidth":
            # dfsadmin -setBalancerBandwidth rides the heartbeat (the
            # reference's BalancerBandwidthCommand)
            self.config.balancer_bandwidth = int(cmd["bytes_per_s"])
            self.balance_throttler.set_rate(cmd["bytes_per_s"])
            _M.incr("bandwidth_commands")
        elif cmd["cmd"] == "finalize_upgrade":
            from hdrf_tpu.storage import version as storage_version

            if storage_version.finalize_upgrade(self.config.data_dir):
                _M.incr("upgrades_finalized")

    def _peer_call(self, addr, op: str, **fields) -> dict:
        """One-shot framed request to a peer DN's xceiver (recovery ops)."""
        import socket as _socket

        from hdrf_tpu.proto.rpc import recv_frame

        s = _socket.create_connection(tuple(addr), timeout=10)
        try:
            s.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
            s = dt.secure_socket(s, fields.get("token"),
                                 self.config.encrypt_data_transfer)
            dt.send_op(s, op, **fields)
            return recv_frame(s)
        finally:
            s.close()

    def _recover_block(self, cmd: dict) -> None:
        """Primary-DN block recovery (BlockRecoveryWorker analog): collect
        replica (gen_stamp, length) from every holder, keep the replicas of
        the HIGHEST generation, sync those to the MINIMUM length (every byte
        below it was CRC-verified on each node; bytes above it may be
        missing somewhere), restamp survivors with the recovery gen stamp
        from the NN (so the next full block report doesn't invalidate
        them), then report the synced length to the NN
        (commitBlockSynchronization)."""
        bid = cmd["block_id"]
        rec_gs = cmd["gen_stamp"]
        token = self.tokens.mint(bid, "w")
        infos: dict[str, tuple[int, int]] = {}  # dn_id -> (gs, length)
        peers = {p["dn_id"]: p for p in cmd["peers"]}
        for dn_id, peer in peers.items():
            try:
                if dn_id == self.dn_id:
                    meta = self.replicas.get_meta(bid)
                    r = {"length": meta.logical_len if meta else -1,
                         "gen_stamp": meta.gen_stamp if meta else -1}
                else:
                    r = self._peer_call(tuple(peer["addr"]), "replica_info",
                                        block_id=bid, token=token)
                if r.get("rbw"):
                    # an in-flight writer (or its teardown persist) is
                    # still running on this peer: abort the round — the
                    # NN re-dispatches shortly and the replica will have
                    # settled (initReplicaRecovery's stopWriter analog)
                    _M.incr("block_recovery_rbw_aborts")
                    return
                if r.get("length", -1) >= 0:
                    infos[dn_id] = (r.get("gen_stamp", 0), r["length"])
            except (OSError, ConnectionError, IOError):
                continue
        if infos:
            top = max(gs for gs, _ in infos.values())
            cand = {d: ln for d, (gs, ln) in infos.items() if gs == top}
            new_len = min(cand.values())
        else:
            cand, new_len = {}, 0
        synced = []
        for dn_id in cand:
            try:
                if dn_id == self.dn_id:
                    ok = self.replicas.truncate_replica(bid, new_len,
                                                        new_gs=rec_gs)
                else:
                    ok = self._peer_call(tuple(peers[dn_id]["addr"]),
                                         "truncate_replica", block_id=bid,
                                         length=new_len,
                                         new_gen_stamp=rec_gs,
                                         token=token).get("ok", False)
                if ok:
                    synced.append(dn_id)
            except (OSError, ConnectionError, IOError):
                continue
        from hdrf_tpu.proto.rpc import RpcError

        for nn in self._nns:
            try:
                nn.call("commit_block_sync", path=cmd["path"], block_id=bid,
                        length=new_len if synced else 0, dn_ids=synced,
                        gen_stamp=rec_gs)
                _M.incr("blocks_recovered")
                return
            except (OSError, ConnectionError, RpcError):
                continue  # standby / raced recovery: another NN may accept
        _M.incr("block_recovery_failures")

    # Live reconfiguration (ReconfigurationProtocol.proto /
    # TestDataNodeReconfiguration analog): a whitelist of keys applied
    # without a restart.  Loops read config each tick, so interval
    # changes take effect at the next wakeup.
    RECONFIGURABLE = frozenset({
        "scan_interval_s", "volume_check_interval_s",
        "block_report_interval_s", "cache_capacity",
        "balancer_bandwidth", "scrub_interval_s",
        "cdc_mask_bits", "cdc_min_chunk", "cdc_max_chunk",
    })

    # Live CDC geometry: bounds mirror AdaptiveChunkController's emit
    # range plus headroom for operator-driven reconfigures; the min<=max
    # invariant is checked against the OTHER live field so a retune
    # sequence must order its steps (accounting.py steps()).
    _CDC_BOUNDS = {"cdc_mask_bits": (6, 20),
                   "cdc_min_chunk": (32, 1 << 22),
                   "cdc_max_chunk": (64, 1 << 24)}

    def _reconfigure_cdc(self, key: str, value) -> dict:
        """Apply a live CDC-geometry change to the SHARED CdcConfig (the
        write pipeline and dispatch funnel hold the same object, so new
        cuts pick it up on their next reducer resolution; committed
        fingerprints are content-addressed and stay valid —
        ARCHITECTURE.md decision 15)."""
        cdc = self.reduction_ctx.config.cdc
        field = key[len("cdc_"):]
        old = getattr(cdc, field)
        try:
            cast = int(value)
        except (TypeError, ValueError) as e:
            return {"ok": False, "error": f"bad value for {key}: {e}"}
        lo, hi = self._CDC_BOUNDS[key]
        if not lo <= cast <= hi:
            return {"ok": False,
                    "error": f"{key}={cast} outside [{lo}, {hi}]"}
        mn = cast if field == "min_chunk" else cdc.min_chunk
        mx = cast if field == "max_chunk" else cdc.max_chunk
        if mn > mx:
            return {"ok": False,
                    "error": f"{key}={cast} would leave min_chunk={mn} > "
                             f"max_chunk={mx}; reorder the steps"}
        setattr(cdc, field, cast)
        _M.incr("reconfigurations")
        return {"ok": True, "key": key, "old": old, "new": cast}

    def reconfigure(self, key: str, value) -> dict:
        if key not in self.RECONFIGURABLE:
            return {"ok": False,
                    "error": f"'{key}' is not reconfigurable "
                             f"(allowed: {sorted(self.RECONFIGURABLE)})"}
        if key.startswith("cdc_"):
            return self._reconfigure_cdc(key, value)
        old = getattr(self.config, key)
        try:
            cast = type(old)(value)
        except (TypeError, ValueError) as e:
            return {"ok": False, "error": f"bad value for {key}: {e}"}
        if key.endswith("_interval_s"):
            # the loops wait() on these each tick: 0/negative would turn
            # them into busy-spins, and a loop that was DISABLED at start
            # (interval 0) was never spawned — a new interval could not
            # take effect and must not pretend to
            if cast <= 0:
                return {"ok": False,
                        "error": f"{key} must be > 0 (disabling a loop "
                                 "requires a restart)"}
            thread_of = {"scan_interval_s": "-scanner",
                         "volume_check_interval_s": "-volcheck",
                         "scrub_interval_s": "-scrubber"}
            suffix = thread_of.get(key)
            if suffix is not None and not any(
                    t.name.endswith(suffix) and t.is_alive()
                    for t in self._threads):
                return {"ok": False,
                        "error": f"{key}: that loop was disabled at "
                                 "startup and is not running"}
        setattr(self.config, key, cast)
        if key == "cache_capacity":
            self.cache.set_capacity(int(cast))
        elif key == "balancer_bandwidth":
            self.balance_throttler.set_rate(cast)
        _M.incr("reconfigurations")
        return {"ok": True, "key": key, "old": old, "new": cast}

    def _verify_index_containers(self) -> list[int]:
        """Startup cross-check: with ``fsync_containers=False`` an OS crash
        can leave the (always-fsync'd) chunk index referencing container
        bytes that never reached disk — and since chunks are SHARED, one
        lost container silently corrupts every dedup'd block referencing
        it.  Before the first block report advertises anything, verify each
        referenced container is reachable and drop blocks touching missing
        ones (the NN re-replicates them from healthy peers; at
        replication=1 set fsync_containers=True instead — see
        ReductionConfig)."""
        referenced = set(self.index.container_live_bytes().keys())
        missing = set()
        for c in referenced:
            # max live extent, not mere existence: the typical crash
            # artifact is a truncated raw file, not a missing one
            extent = max((off + ln for off, ln
                          in self.index.live_chunks_in(c).values()),
                         default=0)
            if not self.containers.has_container(c, need_bytes=extent):
                missing.add(c)
        if not missing:
            return []
        bad: list[int] = []
        for bid in self.index.block_ids():
            e = self.index.get_block(bid)
            if e is None:
                continue
            for h in set(e.hashes):
                loc = self.index.chunk_location(h)
                if loc is not None and loc.container_id in missing:
                    bad.append(bid)
                    break
        for bid in bad:
            self._invalidate(bid)
            _M.incr("startup_lost_container_blocks")
        return bad

    def _invalidate(self, block_id: int) -> None:
        self.cache.unpin(block_id)
        self._sc.registry.revoke(block_id)  # cached client fds must drop
        if self.aliasmap.read(block_id) is not None:
            self.aliasmap.remove([block_id])  # provided mount entry
        meta = self.replicas.get_meta(block_id)
        if meta is None:
            return
        self.scheme(meta.scheme).delete(block_id, self.reduction_ctx)
        self.replicas.delete(block_id)
        _M.incr("blocks_invalidated")

    def _replicate(self, cmd: dict) -> None:
        """DNA_TRANSFER: push our replica to targets, in reduced form
        (vs the reference's reconstruct-full-bytes DataTransfer,
        DataNode.java:2533)."""
        block_id = cmd["block_id"]
        meta = self.replicas.get_meta(block_id)
        if meta is None:
            return
        stored = self.replicas.read_data(block_id) if meta.physical_len else b""
        self._receiver.push_reduced(block_id, meta.gen_stamp, meta.scheme,
                                    meta.logical_len, stored, meta.checksums,
                                    cmd["targets"],
                                    throttler=self.balance_throttler)
        _M.incr("blocks_replicated")

    def _ec_reconstruct(self, cmd: dict) -> None:
        """DNA_ERASURE_CODING_RECONSTRUCTION: fan-in k surviving shards from
        peer DNs, RS-decode the lost shard (MXU bit-matmul, ops/rs.py), store
        it locally (ErasureCodingWorker/StripedBlockReconstructor analog —
        fan-in at erasurecode/StripedBlockReader, decode, StripedBlockWriter)."""
        import numpy as np

        from hdrf_tpu.ops import rs

        k, m, cell = rs.parse_policy(cmd["policy"])
        shards: dict[int, np.ndarray] = {}
        for surv in cmd["survivors"]:
            if len(shards) >= k:
                break
            for loc in surv["locations"]:
                try:
                    data = dt.fetch_block(
                        tuple(loc["addr"]), surv["block_id"],
                        token=self.tokens.mint(surv["block_id"], "r"),
                        encrypt=self.config.encrypt_data_transfer)
                    # reconstruction fan-in is a background leg too
                    self.balance_throttler.throttle(len(data))
                    shards[surv["index"]] = np.frombuffer(data, dtype=np.uint8)
                    break
                except (OSError, ConnectionError, IOError):
                    continue
        if len(shards) < k:
            _M.incr("ec_reconstruct_failures")
            return
        rec = rs.rs_decode(shards, k, m, want=[cmd["index"]])[cmd["index"]]
        writer = self.replicas.create_rbw(cmd["block_id"], cmd["gen_stamp"])
        try:
            writer.write(rec.tobytes())
            from hdrf_tpu import native
            crcs = [int(c) for c in native.crc32c_chunks(rec.tobytes(),
                                                         self.checksum_chunk)]
            meta = writer.finalize(rec.size, "direct", crcs,
                                   self.checksum_chunk)
        except Exception:
            writer.abort()
            raise
        self.notify_block_received(cmd["block_id"], meta.logical_len,
                                   meta.gen_stamp)
        _M.incr("ec_blocks_reconstructed")

    # ------------------------------------------------------------ inspection

    def run_directory_scan(self) -> list[str]:
        """DirectoryScanner trigger (tests + admin)."""
        return self.replicas.scan()

    # ---------------------------------------------------------- volume health

    def check_volume(self, root: str | None = None) -> bool:
        """One write+read+unlink probe of a volume root (the
        DatasetVolumeChecker disk check).  True = healthy."""
        probe = os.path.join(root or self.config.data_dir, ".probe")
        try:
            with open(probe, "wb") as f:
                f.write(b"hdrf-volume-probe")
                f.flush()
                os.fsync(f.fileno())
            with open(probe, "rb") as f:
                ok = f.read() == b"hdrf-volume-probe"
            os.unlink(probe)
            return ok
        except OSError:
            return False

    def eject_volume(self, vol_id: int) -> None:
        """Volume failure (DataNode.handleVolumeFailures): drop the volume,
        push an immediate block report so the NN learns the lost replicas
        NOW (not at the next periodic report) and re-replicates."""
        lost = self.volumes.eject(vol_id)
        self._log.warning("volume ejected", dn_id=self.dn_id, vol_id=vol_id,
                          lost_replicas=len(lost))
        if lost:
            try:
                self._send_block_report()
            except (OSError, ConnectionError):
                pass  # periodic report will carry it

    def _volume_check_loop(self) -> None:
        """Async disk health (DatasetVolumeChecker + ThrottledAsyncChecker
        analog), per volume: a volume failing 3 consecutive probes is
        EJECTED (blocks re-replicate from peers, the DN keeps serving the
        rest); the DN exits only when the last volume has failed — the
        reference's failed.volumes.tolerated behavior."""
        import time as _time

        fails = {v.vol_id: 0 for v in self.volumes.volumes}
        while not self._stop.wait(self.config.volume_check_interval_s):
            for v in self.volumes.volumes:
                if v.failed:
                    continue
                t0 = _time.perf_counter()
                ok = self.check_volume(v.root)
                if ok:
                    # probe duration feeds slow-volume detection: a disk
                    # that still answers but slowly is exactly what the
                    # 3-strikes ejection below can never see
                    self.note_volume_latency(v.vol_id,
                                             _time.perf_counter() - t0)
                    fails[v.vol_id] = 0
                    _M.incr("volume_checks_ok")
                    continue
                fails[v.vol_id] += 1
                _M.incr("volume_checks_failed")
                if fails[v.vol_id] >= 3:
                    self.eject_volume(v.vol_id)
            if self.volumes.alive_count() == 0:
                _M.incr("volume_failures_fatal")
                threading.Thread(target=self.stop, daemon=True).start()
                return

    # ----------------------------------------------------------- block scanner

    def _scanner_loop(self) -> None:
        """BlockScanner/VolumeScanner analog: rolling checksum verification of
        finalized replicas at a throttled rate; corrupt replicas are reported
        to the NN (markBlockAsCorrupt path) which drops the location and lets
        the redundancy monitor re-replicate from a good copy."""
        cursor = 0
        # interval re-read each tick: scan_interval_s is live-reconfigurable
        while not self._stop.wait(self.config.scan_interval_s):
            try:
                bids = sorted(self.replicas.block_ids())
                if not bids:
                    continue
                bid = bids[cursor % len(bids)]
                cursor += 1
                bad = self.verify_block(bid)
                if bad:
                    _M.incr("scanner_corrupt_found")
                    self._log.warning("scanner found corrupt replica",
                                      dn_id=self.dn_id, block_id=bid)
                    for nn in self._nns:
                        try:
                            nn.call("bad_block", dn_id=self.dn_id,
                                    block_id=bid)
                        except (OSError, ConnectionError):
                            _M.incr("scanner_errors")
                    self._invalidate(bid)
            except (OSError, ConnectionError):
                _M.incr("scanner_errors")
            except Exception:  # noqa: BLE001
                _M.incr("scanner_errors")

    def _scrub_loop(self) -> None:
        """Integrity-scrub driver (server/scrubber.py): one full cycle per
        wakeup; interval re-read each tick (live-reconfigurable)."""
        _SCRUB = metrics.registry("scrub")
        while not self._stop.wait(self.config.scrub_interval_s):
            try:
                self.scrubber.run_cycle()
            except (OSError, ConnectionError):
                _SCRUB.incr("scrub_errors")
            except Exception:  # noqa: BLE001
                _SCRUB.incr("scrub_errors")

    def verify_block(self, block_id: int) -> bool:
        """True if the replica is corrupt (stored checksums don't match).
        Reduced replicas verify their reconstructed logical bytes — corruption
        in the chunk store surfaces here too."""
        from hdrf_tpu import native

        meta = self.replicas.get_meta(block_id)
        if meta is None or not meta.checksums:
            return False
        if meta.scheme == "direct":
            data = self.replicas.read_data(block_id)
        else:
            stored = (self.replicas.read_data(block_id)
                      if meta.physical_len else b"")
            data = self.scheme(meta.scheme).reconstruct(
                block_id, stored, meta.logical_len, self.reduction_ctx)
        crcs = [int(c) for c in native.crc32c_chunks(data,
                                                     meta.checksum_chunk)]
        _M.incr("blocks_scanned")
        return crcs != list(meta.checksums)
