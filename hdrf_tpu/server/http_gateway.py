"""HTTP gateway: the WebHDFS REST surface + status pages.

Re-expression of the reference's HTTP layer — `hdfs/web/WebHdfsFileSystem`
(client) + the NN/DN webapps (`webapps/{hdfs,datanode}`) and JMX endpoints —
as one stateless gateway process over the control/data protocols:

  GET    /webhdfs/v1/<path>?op=LISTSTATUS
  GET    /webhdfs/v1/<path>?op=GETFILESTATUS
  GET    /webhdfs/v1/<path>?op=OPEN[&offset=N&length=N]
  PUT    /webhdfs/v1/<path>?op=MKDIRS
  PUT    /webhdfs/v1/<path>?op=CREATE[&scheme=S][&ec=P]     (body = bytes)
  PUT    /webhdfs/v1/<path>?op=RENAME&destination=<dst>
  DELETE /webhdfs/v1/<path>?op=DELETE
  GET    /status      cluster overview (datanode report, live counts)
  GET    /metrics     all metric registries (JMX/metrics2 analog)
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, unquote, urlparse

from hdrf_tpu.client.filesystem import HdrfClient
from hdrf_tpu.utils import metrics

_M = metrics.registry("http_gateway")
PREFIX = "/webhdfs/v1"


class HttpGateway:
    def __init__(self, namenode_addr: tuple[str, int], host: str = "127.0.0.1",
                 port: int = 0):
        self._nn_addr = namenode_addr
        gateway = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def _json(self, code: int, obj) -> None:
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _html(self, body: str) -> None:
                data = body.encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/html; charset=utf-8")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _bytes(self, data: bytes) -> None:
                self.send_response(200)
                self.send_header("Content-Type", "application/octet-stream")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _dispatch(self, method: str) -> None:
                _M.incr("requests")
                u = urlparse(self.path)
                q = {k: v[0] for k, v in parse_qs(u.query).items()}
                try:
                    if u.path == "/explorer":
                        return self._html(gateway.explorer(
                            q.get("path", "/")))
                    if u.path == "/status":
                        return self._json(200, gateway.status())
                    if u.path == "/metrics":
                        return self._json(200, gateway.metrics())
                    if not u.path.startswith(PREFIX):
                        return self._json(404, {"error": "not found"})
                    path = unquote(u.path[len(PREFIX):]) or "/"
                    op = q.get("op", "").upper()
                    with HdrfClient(gateway._nn_addr, name="http-gw") as c:
                        return self._op(c, method, op, path, q)
                except Exception as e:  # noqa: BLE001 — HTTP boundary
                    # RPC errors carry the server-side exception name
                    # (RemoteException analog); map it onto HTTP semantics.
                    name = getattr(e, "error", type(e).__name__)
                    code = {"FileNotFoundError": 404, "IsADirectoryError": 400,
                            "NotADirectoryError": 400, "FileExistsError": 409,
                            "PermissionError": 403}.get(name, 500)
                    self._json(code, {"error": name, "message": str(e)})

            def _op(self, c: HdrfClient, method: str, op: str, path: str,
                    q: dict) -> None:
                if method == "GET" and op == "LISTSTATUS":
                    self._json(200, {"FileStatuses": {
                        "FileStatus": c.ls(path)}})
                elif method == "GET" and op == "GETFILESTATUS":
                    self._json(200, {"FileStatus": c.stat(path)})
                elif method == "GET" and op == "OPEN":
                    data = c.read(path, offset=int(q.get("offset", 0)),
                                  length=int(q.get("length", -1)))
                    self._bytes(data)
                elif method == "PUT" and op == "MKDIRS":
                    self._json(200, {"boolean": c.mkdir(path)})
                elif method == "PUT" and op == "CREATE":
                    n = int(self.headers.get("Content-Length", 0))
                    body = self.rfile.read(n)
                    c.write(path, body, scheme=q.get("scheme"),
                            ec=q.get("ec"))
                    self._json(201, {"length": len(body)})
                elif method == "PUT" and op == "RENAME":
                    self._json(200, {"boolean": c.rename(
                        path, q["destination"])})
                elif method == "DELETE" and op == "DELETE":
                    self._json(200, {"boolean": c.delete(path)})
                else:
                    self._json(400, {"error": "UnsupportedOperationException",
                                     "message": f"{method} {op}"})

            def do_GET(self):
                self._dispatch("GET")

            def do_PUT(self):
                self._dispatch("PUT")

            def do_DELETE(self):
                self._dispatch("DELETE")

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="http-gateway", daemon=True)

    @property
    def addr(self) -> tuple[str, int]:
        return self._server.server_address

    def start(self) -> "HttpGateway":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    def status(self) -> dict:
        with HdrfClient(self._nn_addr, name="http-gw") as c:
            report = c.datanode_report()
        return {"datanodes": report,
                "live": sum(1 for d in report if d["alive"]),
                "dead": sum(1 for d in report if not d["alive"])}

    def metrics(self) -> dict:
        with HdrfClient(self._nn_addr, name="http-gw") as c:
            return c._call("metrics")

    def explorer(self, path: str) -> str:
        """Minimal namespace browser (the NN webapp's explorer.html analog).
        Paths are URL-quoted inside hrefs AND html-escaped as attribute
        values: legal filenames contain &, #, %, quotes — unencoded they
        break links and open an attribute-injection (XSS) hole."""
        import html
        from urllib.parse import quote

        def href(url: str) -> str:
            return html.escape(url, quote=True)

        with HdrfClient(self._nn_addr, name="http-gw") as c:
            entries = c.ls(path)
        base = path.rstrip("/")
        rows = []
        for e in sorted(entries, key=lambda x: (x["type"] != "dir", x["name"])):
            name = html.escape(e["name"])
            child = f"{base}/{e['name']}"
            if e["type"] == "dir":
                url = "/explorer?path=" + quote(child, safe="")
                link = f'<a href="{href(url)}">{name}/</a>'
                size = ""
            else:
                url = "/webhdfs/v1" + quote(child) + "?op=OPEN"
                link = f'<a href="{href(url)}">{name}</a>'
                size = f"{e.get('length', 0):,}"
            extra = e.get("scheme", "") if e["type"] == "file" else ""
            rows.append(f"<tr><td>{link}</td><td align=right>{size}</td>"
                        f"<td>{html.escape(str(extra))}</td></tr>")
        up = base.rsplit("/", 1)[0] or "/"
        up_url = "/explorer?path=" + quote(up, safe="")
        return (f"<html><head><title>hdrf {html.escape(path)}</title></head>"
                f"<body><h2>hdrf_tpu — {html.escape(path)}</h2>"
                f'<p><a href="{href(up_url)}">[up]</a> '
                f'<a href="/status">[status]</a> '
                f'<a href="/metrics">[metrics]</a></p>'
                f"<table border=0 cellpadding=4>"
                f"<tr><th>name</th><th>size</th><th>scheme</th></tr>"
                f"{''.join(rows)}</table></body></html>")
