"""HTTP gateway: the WebHDFS REST surface + status pages.

Re-expression of the reference's HTTP layer — `hdfs/web/WebHdfsFileSystem`
(client, 4.0 kLoC) + the NN/DN webapps (`webapps/{hdfs,datanode}`) and JMX
endpoints — as one stateless gateway process over the control/data
protocols, with the reference's protocol shapes:

- **Two-step CREATE/APPEND/OPEN** (`WebHdfsFileSystem.java:136`'s redirect
  dance): the namespace op answers `307 Temporary Redirect` with a
  Location (or, with ``noredirect=true``, `200 {"Location": ...}`), and
  the client re-issues the op WITH data against the redirect target — so
  bulk bytes never ride the first request (the reference redirects to the
  chosen DataNode's web server; this gateway redirects to its own
  data-serving endpoint, the op shape and client contract identical).
- **Delegation tokens in query params**: ``&delegation=<urlsafe-b64>``
  authenticates any op (token-selector analog);
  ``op=GETDELEGATIONTOKEN`` issues one.  ``user.name=<u>`` carries the
  simple-auth identity otherwise.
- FileSystem-parity ops: LISTSTATUS, GETFILESTATUS, GETCONTENTSUMMARY,
  GETHOMEDIRECTORY, OPEN (ranged), CREATE, APPEND, MKDIRS, RENAME,
  DELETE, TRUNCATE, SETPERMISSION, SETOWNER, SETREPLICATION,
  CREATESYMLINK, GETDELEGATIONTOKEN, RENEWDELEGATIONTOKEN,
  CANCELDELEGATIONTOKEN.

  GET  /status   cluster overview; GET /metrics  JMX/metrics2 analog;
  GET  /prom     Prometheus text exposition (gateway + NameNode registries,
                 the PrometheusMetricsSink analog);
  GET  /traces   cross-daemon trace assembly: local + NameNode + every live
                 DataNode's spans, device-ledger events and profiler
                 counter tracks merged by trace_id (``?trace_id=`` filters;
                 ``?format=chrome`` renders Chrome/Perfetto trace_event
                 JSON with counter tracks) — the pull-model replacement for
                 the reference's HTrace span receivers;
  GET  /stacks   live thread stacks (HttpServer2 StackServlet analog);
  GET  /timeseries  the NameNode flight recorder's bounded gauge ring
                 (utils/flight_recorder.py; per-DN rings on each DN's own
                 status endpoint);
  /dfshealth /datanode /journal /explorer  web UIs.
"""

from __future__ import annotations

import base64
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, quote, unquote, urlparse

import msgpack

from hdrf_tpu.client.filesystem import HdrfClient
from hdrf_tpu.utils import (device_ledger, flight_archive, log, metrics,
                            prom, tracing)

_M = metrics.registry("http_gateway")
_LOG = log.get_logger("http_gateway")
PREFIX = "/webhdfs/v1"


def encode_token(token: dict) -> str:
    """Delegation token -> URL-safe string (the reference's
    Token.encodeToUrlString)."""
    return base64.urlsafe_b64encode(msgpack.packb(token)).decode()


def decode_token(s: str) -> dict:
    return msgpack.unpackb(base64.urlsafe_b64decode(s.encode()), raw=False)


class HttpGateway:
    def __init__(self, namenode_addr: tuple[str, int], host: str = "127.0.0.1",
                 port: int = 0, oauth2_introspect_url: str | None = None,
                 gate_token_issue: bool = False):
        """``oauth2_introspect_url``: RFC 7662 endpoint; when set,
        ``Authorization: Bearer`` tokens authenticate requests (the
        server-side counterpart of the reference's web/oauth2 client
        providers) and the introspected username becomes the acting
        identity.  ``gate_token_issue``: refuse GETDELEGATIONTOKEN to
        unauthenticated callers — without it the op mints a token for
        whatever ``user.name`` claims, which is only acceptable on
        simple-auth clusters (the reference gates issuance behind
        Kerberos)."""
        self._nn_addr = namenode_addr
        self._introspect_url = oauth2_introspect_url
        self._gate_token_issue = gate_token_issue
        self._bearer_cache: dict[str, tuple[str, float]] = {}
        gateway = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def _json(self, code: int, obj) -> None:
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _html(self, body: str) -> None:
                data = body.encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/html; charset=utf-8")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _bytes(self, data: bytes) -> None:
                self.send_response(200)
                self.send_header("Content-Type", "application/octet-stream")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _text(self, body: str, ctype: str) -> None:
                data = body.encode()
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _dispatch(self, method: str) -> None:
                _M.incr("requests")
                u = urlparse(self.path)
                q = {k: v[0] for k, v in parse_qs(u.query).items()}
                try:
                    if u.path == "/explorer":
                        return self._html(gateway.explorer(
                            q.get("path", "/")))
                    if u.path in ("/", "/dfshealth"):
                        return self._html(gateway.dfshealth())
                    if u.path == "/datanode":
                        return self._html(gateway.datanode_page(
                            q.get("id", "")))
                    if u.path == "/journal":
                        return self._html(gateway.journal_page())
                    if u.path == "/status":
                        return self._json(200, gateway.status())
                    if u.path == "/health":
                        return self._json(200, gateway.health())
                    if u.path == "/metrics":
                        return self._json(200, gateway.metrics())
                    if u.path == "/prom":
                        return self._text(gateway.prom_text(),
                                          "text/plain; version=0.0.4")
                    if u.path == "/traces":
                        out = gateway.traces(trace_id=q.get("trace_id"))
                        if q.get("format") == "chrome":
                            out = tracing.chrome_trace(
                                out["spans"], out["ledger"],
                                trace_id=q.get("trace_id"),
                                counters=out.get("counters", []))
                        return self._json(200, out)
                    if u.path == "/stacks":
                        return self._json(200, gateway.stacks())
                    if u.path == "/timeseries":
                        return self._json(200, gateway.timeseries(
                            scope=q.get("scope"),
                            metric=q.get("metric"),
                            since=q.get("since"),
                            step=q.get("step")))
                    if u.path == "/fsck":
                        return self._json(200, gateway.fsck())
                    if u.path == "/contention":
                        return self._json(200, gateway.contention())
                    if not u.path.startswith(PREFIX):
                        return self._json(404, {"error": "not found"})
                    path = unquote(u.path[len(PREFIX):]) or "/"
                    op = q.get("op", "").upper()
                    # _bearer is a GATEWAY-INTERNAL marker: strip any
                    # attacker-supplied query param of that name before the
                    # Bearer branch may set it (otherwise ?_bearer=1 would
                    # spoof an authenticated caller past gate_token_issue)
                    q.pop("_bearer", None)
                    auth = self.headers.get("Authorization", "")
                    if auth.startswith("Bearer "):
                        user = gateway._bearer_user(auth[7:])
                        if user is None:
                            return self._json(401, {
                                "error": "AccessControlException",
                                "message": "invalid bearer token"})
                        q["user.name"] = user
                        q["_bearer"] = "1"
                    with gateway._client(q) as c:
                        return self._op(c, method, op, path, q)
                except Exception as e:  # noqa: BLE001 — HTTP boundary
                    # RPC errors carry the server-side exception name
                    # (RemoteException analog); map it onto HTTP semantics.
                    name = getattr(e, "error", type(e).__name__)
                    code = {"FileNotFoundError": 404, "IsADirectoryError": 400,
                            "NotADirectoryError": 400, "FileExistsError": 409,
                            "PermissionError": 403}.get(name, 500)
                    self._json(code, {"error": name, "message": str(e)})

            def _redirect(self, path: str, q: dict) -> None:
                """Step 1 of the two-step write/open protocol: answer with
                the data endpoint's URL (307, or JSON with noredirect) —
                the client re-issues the op THERE with the payload."""
                # drain any body a non-conforming client sent on step 1:
                # unread bytes would be parsed as the next request line on
                # this keep-alive connection (HTTP/1.1 desync)
                self._body()
                keep = {k: v for k, v in q.items()
                        if k not in ("noredirect", "_bearer")}
                keep["step"] = "2"
                loc = (f"http://{self.headers.get('Host', 'localhost')}"
                       f"{PREFIX}{quote(path)}?"
                       + "&".join(f"{k}={quote(str(v), safe='')}"
                                  for k, v in keep.items()))
                if q.get("noredirect", "").lower() == "true":
                    return self._json(200, {"Location": loc})
                body = b""
                self.send_response(307)
                self.send_header("Location", loc)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _body(self) -> bytes:
                n = int(self.headers.get("Content-Length", 0))
                return self.rfile.read(n)

            def _op(self, c: HdrfClient, method: str, op: str, path: str,
                    q: dict) -> None:
                two_step = "step" not in q
                if method == "GET" and op == "LISTSTATUS":
                    self._json(200, {"FileStatuses": {
                        "FileStatus": c.ls(path)}})
                elif method == "GET" and op == "GETFILESTATUS":
                    self._json(200, {"FileStatus": c.stat(path)})
                elif method == "GET" and op == "GETCONTENTSUMMARY":
                    self._json(200, {"ContentSummary":
                                     c._call("content_summary", path=path)})
                elif method == "GET" and op == "GETHOMEDIRECTORY":
                    self._json(200, {"Path": f"/user/{c.user}"})
                elif method == "GET" and op == "OPEN":
                    if two_step:
                        # the reference always redirects OPEN to the data
                        # endpoint; GET clients follow 307 transparently
                        return self._redirect(path, q)
                    data = c.read(path, offset=int(q.get("offset", 0)),
                                  length=int(q.get("length", -1)))
                    self._bytes(data)
                elif method == "PUT" and op == "MKDIRS":
                    self._json(200, {"boolean": c.mkdir(path)})
                elif method == "PUT" and op == "CREATE":
                    if two_step:
                        return self._redirect(path, q)
                    body = self._body()
                    c.write(path, body, scheme=q.get("scheme"),
                            ec=q.get("ec"))
                    self._json(201, {"length": len(body)})
                elif method == "POST" and op == "APPEND":
                    if two_step:
                        return self._redirect(path, q)
                    body = self._body()
                    c.append(path, body)
                    self._json(200, {"length": len(body)})
                elif method == "POST" and op == "TRUNCATE":
                    ok = c._call("truncate", path=path,
                                 new_length=int(q["newlength"]))
                    self._json(200, {"boolean": ok})
                elif method == "PUT" and op == "RENAME":
                    self._json(200, {"boolean": c.rename(
                        path, q["destination"])})
                elif method == "PUT" and op == "SETPERMISSION":
                    c._call("set_permission", path=path,
                            mode=int(q.get("permission", "755"), 8))
                    self._json(200, {})
                elif method == "PUT" and op == "SETOWNER":
                    c._call("set_owner", path=path,
                            owner=q.get("owner", ""),
                            group=q.get("group", ""))
                    self._json(200, {})
                elif method == "PUT" and op == "SETREPLICATION":
                    ok = c._call("set_replication", path=path,
                                 replication=int(q.get("replication", 3)))
                    self._json(200, {"boolean": ok})
                elif method == "PUT" and op == "CREATESYMLINK":
                    c._call("create_symlink", link=path,
                            target=q["destination"])
                    self._json(200, {})
                elif method == "DELETE" and op == "DELETE":
                    self._json(200, {"boolean": c.delete(path)})
                elif method == "GET" and op == "GETFILECHECKSUM":
                    fc = c.get_file_checksum(path)
                    self._json(200, {"FileChecksum": {
                        "algorithm": fc["algorithm"],
                        "bytes": fc["bytes"],
                        "length": fc["length"]}})
                elif method == "PUT" and op == "ALLOWSNAPSHOT":
                    c.allow_snapshot(path)
                    self._json(200, {})
                elif method == "GET" and op == "GETSNAPSHOTDIFF":
                    # oldsnapshotname is REQUIRED (an omitted/typo'd param
                    # must not silently diff the current tree against
                    # itself and report "nothing changed") — and its
                    # absence is the CALLER's error: a 400 with the
                    # parameter named, not a KeyError-shaped 500.
                    if "oldsnapshotname" not in q:
                        return self._json(400, {
                            "error": "IllegalArgumentException",
                            "message": "GETSNAPSHOTDIFF requires the "
                                       "oldsnapshotname parameter"})
                    rep = c.snapshot_diff(
                        path, q["oldsnapshotname"],
                        q.get("snapshotname", ""))
                    self._json(200, {"SnapshotDiffReport": {
                        "snapshotRoot": rep["path"],
                        "fromSnapshot": rep["from"],
                        "toSnapshot": rep["to"],
                        "diffList": rep["entries"]}})
                elif method == "PUT" and op == "CREATESNAPSHOT":
                    c.create_snapshot(path, q["snapshotname"])
                    self._json(200, {"Path":
                                     f"{path}/.snapshot/"
                                     f"{q['snapshotname']}"})
                elif method == "DELETE" and op == "DELETESNAPSHOT":
                    c.delete_snapshot(path, q["snapshotname"])
                    self._json(200, {})
                elif method == "GET" and op == "GETDELEGATIONTOKEN":
                    # With gate_token_issue, issuance requires an already
                    # AUTHENTICATED identity (bearer or existing
                    # delegation token) — otherwise any HTTP caller could
                    # mint a token for any claimed user.name (the
                    # reference gates this leg behind Kerberos; plain
                    # simple-auth deployments leave the gate off)
                    if gateway._gate_token_issue and "_bearer" not in q:
                        # a delegation param only authenticates if the NN
                        # VERIFIES it (decode_token alone checks nothing —
                        # a forged {'owner':'root'} blob must not pass)
                        ok = False
                        if "delegation" in q:
                            try:
                                ok = c._nn.call(
                                    "check_delegation_token",
                                    token=decode_token(q["delegation"]))
                            except Exception:  # noqa: BLE001
                                ok = False
                        if not ok:
                            return self._json(403, {
                                "error": "AccessControlException",
                                "message": "token issuance requires an "
                                           "authenticated caller"})
                    tok = c._nn.call("get_delegation_token",
                                     renewer=q.get("renewer", c.user),
                                     owner=c.user)
                    self._json(200, {"Token":
                                     {"urlString": encode_token(tok)}})
                elif method == "PUT" and op == "RENEWDELEGATIONTOKEN":
                    exp = c._nn.call("renew_delegation_token",
                                     token=decode_token(q["token"]))
                    self._json(200, {"long": exp})
                elif method == "PUT" and op == "CANCELDELEGATIONTOKEN":
                    c._nn.call("cancel_delegation_token",
                               token=decode_token(q["token"]))
                    self._json(200, {})
                else:
                    self._json(400, {"error": "UnsupportedOperationException",
                                     "message": f"{method} {op}"})

            def do_POST(self):
                self._dispatch("POST")

            def do_GET(self):
                self._dispatch("GET")

            def do_PUT(self):
                self._dispatch("PUT")

            def do_DELETE(self):
                self._dispatch("DELETE")

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="http-gateway", daemon=True)

    @property
    def addr(self) -> tuple[str, int]:
        return self._server.server_address

    def _bearer_user(self, token: str) -> str | None:
        """RFC 7662 introspection with a short positive cache; None =
        inactive/invalid.  No introspection endpoint configured = no
        bearer auth (the header is rejected rather than trusted)."""
        import time as _t
        import urllib.parse
        import urllib.request

        if not self._introspect_url:
            return None
        hit = self._bearer_cache.get(token)
        if hit and hit[1] > _t.monotonic():
            return hit[0]
        try:
            req = urllib.request.Request(
                self._introspect_url,
                data=urllib.parse.urlencode({"token": token}).encode(),
                method="POST",
                headers={"Content-Type":
                         "application/x-www-form-urlencoded"})
            with urllib.request.urlopen(req, timeout=10) as r:
                out = json.loads(r.read())
        except OSError:
            return None
        if not out.get("active"):
            return None
        user = out.get("username") or out.get("sub") or "oauth2-user"
        self._bearer_cache[token] = (user, _t.monotonic() + 30.0)
        if len(self._bearer_cache) > 1024:
            self._bearer_cache.clear()   # crude bound; entries re-fetch
        _M.incr("bearer_auths")
        return user

    def _client(self, q: dict) -> HdrfClient:
        """Per-request client with the caller's identity: a delegation
        token from the query params (its owner becomes the acting user —
        the token-selector analog) or simple-auth ``user.name``."""
        tok = None
        user = q.get("user.name")
        if "delegation" in q:
            tok = decode_token(q["delegation"])
            user = tok.get("owner") or user
        c = HdrfClient(self._nn_addr, name="http-gw", user=user)
        if tok is not None:
            c._dtoken = tok
        return c

    def start(self) -> "HttpGateway":
        self._thread.start()
        _LOG.info("http gateway started",
                  addr=f"{self.addr[0]}:{self.addr[1]}")
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    def status(self) -> dict:
        with HdrfClient(self._nn_addr, name="http-gw") as c:
            report = c.datanode_report()
            cluster = c._call("cluster_status")
        return {"datanodes": report,
                "live": sum(1 for d in report if d["alive"]),
                "dead": sum(1 for d in report if not d["alive"]),
                "dedup_ratio": cluster.get("dedup_ratio"),
                "slow_peers": cluster.get("slow_peers"),
                "slow_volumes": cluster.get("slow_volumes"),
                # EC cold tier: striped census + stripe-tier footprint
                "ec_demoted_blocks": cluster.get("ec_demoted_blocks", 0),
                "striped_containers": cluster.get("striped_containers", 0),
                "stripe_logical_bytes":
                    cluster.get("stripe_logical_bytes", 0),
                "stripe_physical_bytes":
                    cluster.get("stripe_physical_bytes", 0)}

    def health(self) -> dict:
        """Cluster health verdict for load balancers / dashboards: DN
        liveness buckets, safemode, the outlier detector's slow-peer /
        slow-volume flags (slow_nodes_report RPC) and the cluster-wide
        reduction effectiveness — one JSON fetch, no namespace access
        required (the dfshealth JMX-scrape replacement)."""
        try:
            with HdrfClient(self._nn_addr, name="http-gw") as c:
                cluster = c._call("cluster_status")
                slow = c._call("slow_nodes_report")
        except (OSError, ConnectionError):
            _M.incr("health_nn_unreachable")
            _LOG.warning("health probe: namenode unreachable",
                         namenode=str(self._nn_addr))
            return {"status": "unreachable", "namenode": str(self._nn_addr)}
        degraded_nodes = slow.get("degraded_nodes") or []
        fsck_violations = int(cluster.get("fsck_violations", 0))
        scrub_corrupt = int(cluster.get("scrub_corrupt_total", 0))
        degraded = (cluster["dead"] > 0 or cluster["safemode"]
                    or cluster["under_replicated"] > 0
                    or slow["slow_peers"] or slow["slow_volumes"]
                    or bool(degraded_nodes)
                    # integrity plane: invariant-census violations or
                    # scrub-confirmed corruption flip the verdict too
                    or fsck_violations > 0 or scrub_corrupt > 0)
        return {"status": "degraded" if degraded else "healthy",
                "role": cluster["role"],
                "safemode": cluster["safemode"],
                "live": cluster["live"], "dead": cluster["dead"],
                "blocks": cluster["blocks"],
                "under_replicated": cluster["under_replicated"],
                "slow_peers": slow["slow_peers"],
                "slow_volumes": slow["slow_volumes"],
                # DNs running passthrough (worker breaker open/probing):
                # writes succeed but reduction is off on these nodes
                "degraded_nodes": degraded_nodes,
                "mirror_failures": slow.get("mirror_failures") or {},
                "dedup_ratio": cluster["dedup_ratio"],
                "dedup_logical_bytes": cluster["dedup_logical_bytes"],
                "dedup_unique_bytes": cluster["dedup_unique_bytes"],
                # EC cold tier (physical/logical ≈ (k+m)/k for striped
                # containers vs the replicated tier's factor)
                "ec_demoted_blocks": cluster.get("ec_demoted_blocks", 0),
                "striped_containers": cluster.get("striped_containers", 0),
                "stripe_logical_bytes":
                    cluster.get("stripe_logical_bytes", 0),
                "stripe_physical_bytes":
                    cluster.get("stripe_physical_bytes", 0),
                # integrity plane (ISSUE 12): the census + scrub verdicts
                # behind the degraded expression above
                "fsck_violations": fsck_violations,
                "scrub_corrupt_total": scrub_corrupt,
                # overload plane: admission sheds are intentional refusals
                # (kept out of the degraded verdict — a shedding cluster is
                # protecting itself, not failing)
                "qos_sheds_total": cluster.get("qos_sheds_total", 0),
                "garbage_bytes": cluster.get("garbage_bytes", 0),
                "scrub_repairs_triggered":
                    cluster.get("scrub_repairs_triggered", 0),
                # observer plane (ISSUE 20): one row per configured NN —
                # role, applied txid and tail lag, so a dashboard sees the
                # read replicas and their staleness without namespace access
                "namenodes": self._namenode_rows()}

    def _namenode_rows(self) -> list[dict]:
        """Per-NN role/txid/lag rows for ``/health`` via each endpoint's
        ``ha_state`` (the haadmin -haStatus analog; unreachable NNs get a
        ``reachable: False`` row rather than poisoning the probe)."""
        from hdrf_tpu.proto.rpc import RpcClient, normalize_addrs

        rows = []
        for addr in normalize_addrs(self._nn_addr):
            try:
                with RpcClient(addr, timeout=2.0) as c:
                    st = c.call("ha_state")
            except (OSError, ConnectionError):
                rows.append({"addr": f"{addr[0]}:{addr[1]}",
                             "reachable": False})
                continue
            rows.append({"addr": f"{addr[0]}:{addr[1]}", "reachable": True,
                         "role": st.get("role"),
                         "applied_txid": st.get("applied_txid",
                                                st.get("seq", 0)),
                         "lag_s": st.get("lag_s", 0.0)})
        return rows

    def fsck(self) -> dict:
        """Gateway face of the NN invariant census (``rpc_fsck``): runs the
        reconciliation NOW and relays the per-class verdict."""
        try:
            with HdrfClient(self._nn_addr, name="http-gw") as c:
                return c._call("fsck")
        except (OSError, ConnectionError):
            _M.incr("fsck_nn_unreachable")
            return {"status": "unreachable",
                    "namenode": str(self._nn_addr)}

    def metrics(self) -> dict:
        with HdrfClient(self._nn_addr, name="http-gw") as c:
            return c._call("metrics")

    def prom_text(self) -> str:
        """Prometheus exposition over the gateway's own registries merged
        with the NameNode's (same-name registries keep the gateway-local
        view; they are the same families either way)."""
        snaps = dict(metrics.all_snapshots())
        try:
            with HdrfClient(self._nn_addr, name="http-gw") as c:
                for name, snap in c._call("metrics").items():
                    snaps.setdefault(name, snap)
        except (OSError, ConnectionError):
            _M.incr("prom_nn_unreachable")
        return prom.render(snaps)

    def traces(self, trace_id: str | None = None) -> dict:
        """Cross-daemon trace assembly: this process's spans + ledger +
        profiler counter samples, the NameNode's (trace_spans RPC), and
        every live DataNode's (trace_spans xceiver op; each DN proxies its
        co-located worker).  Spans dedupe by span_id, ledger events and
        counter samples by (proc, id) — a daemon polled twice (e.g. NN
        also reachable as a peer) merges clean."""
        import socket as _socket

        from hdrf_tpu.proto import datatransfer as dt
        from hdrf_tpu.proto.rpc import recv_frame
        from hdrf_tpu.utils import profiler

        spans = list(tracing.all_span_snapshots())
        ledger = list(device_ledger.events_snapshot())
        counters = list(profiler.counters_snapshot())
        report = []
        try:
            with HdrfClient(self._nn_addr, name="http-gw") as c:
                report = c.datanode_report()
                nn = c._call("trace_spans")
                spans.extend(nn.get("spans") or ())
                ledger.extend(nn.get("ledger") or ())
                counters.extend(nn.get("counters") or ())
        except (OSError, ConnectionError):
            _M.incr("traces_nn_unreachable")
        for d in report:
            if not d.get("alive"):
                continue
            try:
                with _socket.create_connection(
                        tuple(d["addr"]), timeout=5.0) as s:
                    dt.send_op(s, "trace_spans")
                    out = recv_frame(s)
                spans.extend(out.get("spans") or ())
                ledger.extend(out.get("ledger") or ())
                counters.extend(out.get("counters") or ())
            except (OSError, ConnectionError):
                _M.incr("traces_dn_unreachable")
        seen_sp: set = set()
        seen_ev: set = set()
        seen_ct: set = set()
        uspans = [s for s in spans
                  if s.get("span_id") not in seen_sp
                  and not seen_sp.add(s.get("span_id"))]
        uledger = [e for e in ledger
                   if (e.get("proc"), e.get("id")) not in seen_ev
                   and not seen_ev.add((e.get("proc"), e.get("id")))]
        ucounters = [c for c in counters
                     if (c.get("proc"), c.get("id")) not in seen_ct
                     and not seen_ct.add((c.get("proc"), c.get("id")))]
        if trace_id is not None:
            uspans = [s for s in uspans if s.get("trace_id") == trace_id]
            uledger = [e for e in uledger
                       if e.get("trace_id") == trace_id]
            ucounters = []  # counter samples have no trace affinity
        return {"spans": uspans, "ledger": uledger, "counters": ucounters}

    def stacks(self) -> dict:
        """Gateway-process thread stacks (per-daemon stacks live on each
        daemon's own status endpoint)."""
        from hdrf_tpu.utils.watchdog import thread_stacks

        return {"daemon": "http_gateway", "threads": thread_stacks()}

    def contention(self) -> dict:
        """The NN's control-plane contention table (rpc_contention RPC:
        per-method calls/p99/lock-share + the instrumented namesystem
        lock's books, ISSUE 18) — one fetch for the storm-triage
        dashboard."""
        try:
            with HdrfClient(self._nn_addr, name="http-gw") as c:
                return c._call("contention")
        except (OSError, ConnectionError):
            _M.incr("contention_nn_unreachable")
            return {"status": "unreachable", "namenode": str(self._nn_addr)}

    def timeseries(self, scope: str | None = None,
                   metric: str | None = None, since=None,
                   step=None) -> dict:
        """The flight-data query plane (the time-series slo_report plots).

        Default scope: the NameNode flight recorder's ring+archive
        (flight_query RPC), ``?metric=``/``?since=`` projected
        server-side.  ``?scope=cluster``: pull every live DN's
        ring+archive too (flight_timeseries xceiver op, the /traces
        fan-out pattern), align the per-daemon streams into one cluster
        series with proper per-gauge merge semantics — quantile-class
        gauges take the MAX across nodes, per-node tallies SUM, ratios
        average (utils/flight_archive.py merge_cluster) — and, when
        ``?step=`` is given, downsample to min/max/mean/last rollup
        buckets so an archive of any length renders in one bounded
        response."""
        since_f = float(since) if since is not None else None
        step_f = float(step) if step is not None else None
        try:
            with HdrfClient(self._nn_addr, name="http-gw") as c:
                nn = c._call("flight_query", metric=metric, since=since_f)
                report = (c.datanode_report()
                          if scope == "cluster" else [])
        except (OSError, ConnectionError):
            _M.incr("timeseries_nn_unreachable")
            return {"daemon": "namenode", "interval_s": 0.0, "capacity": 0,
                    "samples": [], "error": "namenode unreachable"}
        if scope != "cluster":
            if step_f:
                nn["rollup"] = flight_archive.rollup(nn["samples"], step_f)
                nn["samples"] = []
            return nn
        import socket as _socket

        from hdrf_tpu.proto import datatransfer as dt
        from hdrf_tpu.proto.rpc import recv_frame

        series = [("namenode", nn.get("samples") or [])]
        for d in report:
            if not d.get("alive"):
                continue
            try:
                with _socket.create_connection(
                        tuple(d["addr"]), timeout=5.0) as s:
                    dt.send_op(s, "flight_timeseries",
                               metric=metric, since=since_f)
                    out = recv_frame(s)
                series.append((out.get("daemon") or d.get("dn_id", "dn"),
                               out.get("samples") or []))
            except (OSError, ConnectionError):
                _M.incr("timeseries_dn_unreachable")
        bucket = step_f or 1.0
        merged = flight_archive.merge_cluster(series, step_s=bucket)
        out = {"scope": "cluster", "step_s": bucket,
               "daemons": [name for name, _ in series],
               "samples": merged}
        if step_f:
            out["rollup"] = flight_archive.rollup(merged, step_f)
        return out

    # ------------------------------------------------------------- web UIs

    _NAV = ('<p><a href="/dfshealth">[overview]</a> '
            '<a href="/explorer?path=%2F">[explorer]</a> '
            '<a href="/journal">[journal]</a> '
            '<a href="/status">[status.json]</a> '
            '<a href="/metrics">[metrics.json]</a> '
            '<a href="/prom">[prom]</a> '
            '<a href="/traces">[traces]</a></p>')

    @staticmethod
    def _page(title: str, body: str) -> str:
        import html

        return (f"<html><head><title>{html.escape(title)}</title>"
                "<style>body{font-family:sans-serif;margin:2em}"
                "table{border-collapse:collapse}"
                "td,th{border:1px solid #ccc;padding:4px 10px}"
                "th{background:#eee}</style></head>"
                f"<body><h2>{html.escape(title)}</h2>"
                f"{HttpGateway._NAV}{body}</body></html>")

    @staticmethod
    def _gb(n) -> str:
        return f"{(n or 0) / 2**30:.2f} GB"

    def dfshealth(self) -> str:
        """NameNode overview (webapps/hdfs/dfshealth.html analog): safemode,
        HA role, capacity, block totals, and the live/dead/decommissioning
        DataNode table with per-DN drill-down links."""
        import html
        from urllib.parse import quote

        with HdrfClient(self._nn_addr, name="http-gw") as c:
            cs = c._call("cluster_status")
            report = c.datanode_report()
        rows = []
        for d in sorted(report, key=lambda x: x["dn_id"]):
            st = d.get("stats") or {}
            state = "live" if d["alive"] else "dead"
            url = "/datanode?id=" + quote(d["dn_id"], safe="")
            rows.append(
                f'<tr><td><a href="{html.escape(url, quote=True)}">'
                f'{html.escape(d["dn_id"])}</a></td>'
                f"<td>{html.escape(':'.join(map(str, d['addr'])))}</td>"
                f"<td>{state}</td><td align=right>{d['blocks']}</td>"
                f"<td align=right>{self._gb(st.get('logical_bytes'))}</td>"
                f"<td align=right>{self._gb(st.get('physical_bytes'))}</td>"
                "</tr>")
        summary = (
            f"<table><tr><th>role</th><td>{html.escape(cs['role'])}</td></tr>"
            f"<tr><th>safemode</th><td>{'ON' if cs['safemode'] else 'off'}"
            "</td></tr>"
            f"<tr><th>blocks</th><td>{cs['blocks']}</td></tr>"
            f"<tr><th>under-replicated</th><td>{cs['under_replicated']}"
            "</td></tr>"
            f"<tr><th>pending replication</th>"
            f"<td>{cs['pending_replication']}</td></tr>"
            f"<tr><th>logical data</th>"
            f"<td>{self._gb(cs['logical_bytes'])}</td></tr>"
            f"<tr><th>physical (reduced) data</th>"
            f"<td>{self._gb(cs['physical_bytes'])}</td></tr>"
            f"<tr><th>edit log seq</th><td>{cs['editlog_seq']}</td></tr>"
            f"<tr><th>datanodes</th><td>{cs['live']} live / {cs['dead']} "
            f"dead / {cs['decommissioning']} decommissioning</td></tr>"
            "</table>")
        dn_table = ("<h3>DataNodes</h3><table><tr><th>id</th><th>addr</th>"
                    "<th>state</th><th>blocks</th><th>logical</th>"
                    "<th>physical</th></tr>" + "".join(rows) + "</table>")
        return self._page("hdrf_tpu NameNode", summary + dn_table)

    def datanode_page(self, dn_id: str) -> str:
        """Per-DataNode detail (webapps/datanode analog), rendered from the
        stats the DN ships in heartbeats: replica/container bytes, pinned
        cache, chunk-index state, peer-latency reports."""
        import html

        with HdrfClient(self._nn_addr, name="http-gw") as c:
            report = c.datanode_report()
        d = next((x for x in report if x["dn_id"] == dn_id), None)
        if d is None:
            return self._page(f"datanode {dn_id}", "<p>unknown datanode</p>")
        st = d.get("stats") or {}
        idx = st.get("index") or {}
        rows = [
            ("state", "live" if d["alive"] else "dead"),
            ("address", ":".join(map(str, d["addr"]))),
            ("blocks", d["blocks"]),
            ("logical bytes", self._gb(st.get("logical_bytes"))),
            ("physical bytes", self._gb(st.get("physical_bytes"))),
            ("cached blocks", len(st.get("cached_blocks") or [])),
            ("cache used", self._gb(st.get("cache_used"))),
        ] + [(f"index {k}", v) for k, v in sorted(idx.items())] + [
            (f"peer {p} median s/MB", f"{m:.3f} ({n} samples)")
            for p, (m, n) in sorted((st.get("peer_transfer") or {}).items())
        ]
        body = "<table>" + "".join(
            f"<tr><th>{html.escape(str(k))}</th>"
            f"<td>{html.escape(str(v))}</td></tr>" for k, v in rows) \
            + "</table>"
        return self._page(f"hdrf_tpu DataNode {dn_id}", body)

    def journal_page(self) -> str:
        """JournalNode quorum state (webapps/journal analog): per-node
        epoch, accepted/committed sequence, storage dir."""
        import html

        from hdrf_tpu.proto.rpc import RpcClient

        with HdrfClient(self._nn_addr, name="http-gw") as c:
            cs = c._call("cluster_status")
        addrs = cs.get("journal_addrs") or []
        if not addrs:
            body = ("<p>no quorum journal configured (shared-directory "
                    f"edit log; seq {cs['editlog_seq']})</p>")
            return self._page("hdrf_tpu Journal", body)
        rows = []
        for a in addrs:
            addr = (a[0], int(a[1]))
            try:
                # short probe timeout: a packet-dropping (not refusing) JN
                # must not stall the page for the default 30 s per node
                with RpcClient(addr, timeout=2.0) as jc:
                    s = jc.call("jn_state")
                cells = [f"{a[0]}:{a[1]}", "up"] + [
                    str(s.get(k)) for k in ("promised", "wepoch",
                                            "last_seq", "earliest")]
            except (OSError, ConnectionError) as e:
                cells = [f"{a[0]}:{a[1]}", f"down ({type(e).__name__})",
                         "-", "-", "-", "-"]
            rows.append("<tr>" + "".join(
                f"<td>{html.escape(c)}</td>" for c in cells) + "</tr>")
        body = ("<table><tr><th>node</th><th>state</th>"
                "<th>promised epoch</th><th>write epoch</th>"
                "<th>last seq</th><th>earliest</th></tr>"
                + "".join(rows) + "</table>")
        return self._page("hdrf_tpu Journal", body)

    def explorer(self, path: str) -> str:
        """Minimal namespace browser (the NN webapp's explorer.html analog).
        Paths are URL-quoted inside hrefs AND html-escaped as attribute
        values: legal filenames contain &, #, %, quotes — unencoded they
        break links and open an attribute-injection (XSS) hole."""
        import html
        from urllib.parse import quote

        def href(url: str) -> str:
            return html.escape(url, quote=True)

        with HdrfClient(self._nn_addr, name="http-gw") as c:
            entries = c.ls(path)
        base = path.rstrip("/")
        rows = []
        for e in sorted(entries, key=lambda x: (x["type"] != "dir", x["name"])):
            name = html.escape(e["name"])
            child = f"{base}/{e['name']}"
            if e["type"] == "dir":
                url = "/explorer?path=" + quote(child, safe="")
                link = f'<a href="{href(url)}">{name}/</a>'
                size = ""
            else:
                url = "/webhdfs/v1" + quote(child) + "?op=OPEN"
                link = f'<a href="{href(url)}">{name}</a>'
                size = f"{e.get('length', 0):,}"
            extra = e.get("scheme", "") if e["type"] == "file" else ""
            rows.append(f"<tr><td>{link}</td><td align=right>{size}</td>"
                        f"<td>{html.escape(str(extra))}</td></tr>")
        up = base.rsplit("/", 1)[0] or "/"
        up_url = "/explorer?path=" + quote(up, safe="")
        return (f"<html><head><title>hdrf {html.escape(path)}</title></head>"
                f"<body><h2>hdrf_tpu — {html.escape(path)}</h2>"
                f'<p><a href="{href(up_url)}">[up]</a> '
                f'<a href="/status">[status]</a> '
                f'<a href="/metrics">[metrics]</a></p>'
                f"<table border=0 cellpadding=4>"
                f"<tr><th>name</th><th>size</th><th>scheme</th></tr>"
                f"{''.join(rows)}</table></body></html>")
