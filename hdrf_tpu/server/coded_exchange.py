"""Coded-exchange primitive: the background bulk-transfer plane.

Every background bulk move the cluster makes — repair gather legs and
stripe pushes today (server/ec_tier.py:292 `_gather`, `_place`), rebalance
and compaction moves tomorrow — shares three needs the foreground data
path does not: the bytes are *derived* (recomputable, so aggressive coding
is safe), the links are otherwise idle (so compression compute is free),
and the traffic must NEVER shed a tenant (so it rides the QoS control
lane, not a tenant bucket).  This module is that shared seam, the
Compressed Coded Distributed Computing shape (arXiv 1805.01993; arXiv
1802.03049's coded shuffles) folded onto this repo's existing planes:

- ``pack_many`` / ``unpack`` — smaller-of LZ4 negotiation for exchange
  intermediates through the batched codec dispatch
  (ops/dispatch.py:262 ``block_compress_batch``: one device program on
  the TPU backend via ops/lz4_tpu.py ``compress_many``, the host oracle
  elsewhere).  Each payload ships with an ``enc`` flag; raw wins ties,
  so a peer that never asked (``accept_enc`` absent) or an incompressible
  intermediate costs zero extra bytes — mixed versions stay
  byte-identical.
- :class:`CodedExchange` — the DN-side sender: binds the QoS control lane
  (utils/qos.py ``background()`` — admitted, audited, never shed), paces
  under the balance throttle (DataTransferThrottler.java:28 analog the
  balancer already owns), and books the exchange byte ledger.
- ``book_repair_wire`` — the ``repair_wire_ratio`` counter family in the
  ec registry (bytes-on-wire at the repairing owner / bytes rebuilt): the
  measured face of ROADMAP item 4's acceptance bar, shared by the live
  repair path and the bench harnesses so both stamp the same counters.

Total wire bytes across a partial-sum repair are conserved (k XOR
contributions exist somewhere); the win this plane measures is the
repairing OWNER's ingress — k×stripe_len drops to |missing|×stripe_len —
with the remainder spread over otherwise-idle holder->holder hops
(``coded_relay_bytes`` keeps that honest).
"""

from __future__ import annotations

import time

from hdrf_tpu.ops import dispatch
from hdrf_tpu.utils import fault_injection, metrics, qos

_M = metrics.registry("coded_exchange")
_EC = metrics.registry("ec")

# below this, LZ4 block framing can't win — don't even try the codec
_MIN_PACK = 64


def backend_for(red) -> str:
    """Codec backend for exchange intermediates: the reduction config's
    backend when it resolves to the TPU (compress_many batches there),
    the native host codec otherwise."""
    b = dispatch.resolve_backend(getattr(red, "backend", "native"))
    return b if b == "tpu" else "native"


def pack_many(datas: list[bytes], backend: str = "native"
              ) -> list[tuple[bytes, int]]:
    """Smaller-of LZ4 negotiation for a batch of exchange intermediates.

    Returns ``[(payload, enc), ...]`` aligned with ``datas``: ``enc=1``
    payloads are LZ4 blocks strictly smaller than the raw bytes, ``enc=0``
    payloads ARE the raw bytes (ties and incompressible inputs ship raw,
    so negotiation can only save).  The whole batch compresses through ONE
    ``block_compress_batch`` dispatch — on-TPU ``compress_many`` when the
    backend is tpu, per the idle-accelerator premise of background work."""
    if not datas:
        return []
    datas = [bytes(d) for d in datas]
    candidates = [d for d in datas if len(d) >= _MIN_PACK]
    blobs: dict[int, bytes] = {}
    if candidates:
        if backend == "tpu" and len({len(d) for d in candidates}) != 1:
            backend = "native"  # compress_many batches equal lengths only
        packed = dispatch.block_compress_batch("lz4", candidates, backend)
        it = iter(packed)
        blobs = {i: next(it) for i, d in enumerate(datas)
                 if len(d) >= _MIN_PACK}
    out: list[tuple[bytes, int]] = []
    for i, raw in enumerate(datas):
        blob = blobs.get(i)
        if blob is not None and len(blob) < len(raw):
            out.append((blob, 1))
            _M.incr("packed_intermediates")
            _M.incr("pack_saved_bytes", len(raw) - len(blob))
        else:
            out.append((raw, 0))
            _M.incr("incompressible_intermediates")
    _M.incr("pack_raw_bytes", sum(len(d) for d in datas))
    _M.incr("pack_wire_bytes", sum(len(p) for p, _ in out))
    return out


def pack(data: bytes, backend: str = "native") -> tuple[bytes, int]:
    """Single-payload face of :func:`pack_many`."""
    return pack_many([data], backend)[0]


def unpack(payload: bytes, enc: int, usize: int) -> bytes:
    """Invert :func:`pack`: ``enc=0`` payloads are already the raw bytes;
    ``enc=1`` decodes through the host LZ4 oracle (byte-serial output
    dependence — see block_decompress_batch's rationale)."""
    if not enc:
        return bytes(payload)
    from hdrf_tpu.utils import codec

    return codec.decompress("lz4", bytes(payload), int(usize))


def book_repair_wire(wire_bytes: int, rebuilt_bytes: int,
                     relay_bytes: int = 0) -> None:
    """Stamp the ec registry's repair wire ledger: cumulative
    bytes-on-wire at the repairing owner, bytes rebuilt, and the
    ``repair_wire_ratio`` gauge (wire / rebuilt — the classic full gather
    runs at ~k, the coded partial-sum path at ~1 before compression).
    Shared by the live repair path and the bench harnesses."""
    _EC.incr("repair_wire_bytes", int(wire_bytes))
    _EC.incr("repair_rebuilt_bytes", int(rebuilt_bytes))
    if relay_bytes:
        _EC.incr("coded_relay_bytes", int(relay_bytes))
    rebuilt = _EC.counter("repair_rebuilt_bytes")
    if rebuilt > 0:
        _EC.gauge("repair_wire_ratio",
                  _EC.counter("repair_wire_bytes") / rebuilt)


class CodedExchange:
    """DN-side exchange sender: control lane + throttle + byte ledger.

    ``send`` is one background peer exchange — admitted through the DN's
    QoS gate under :data:`qos.BACKGROUND_TENANT` (so the audit trail
    proves the lane and foreground tenants can never be shed or debited
    for it), paced by the balance throttle the NN already budgets, and
    counted in the coded_exchange registry."""

    def __init__(self, dn) -> None:
        self._dn = dn

    @property
    def compress_on(self) -> bool:
        red = self._dn.reduction_ctx.config
        return bool(getattr(red, "coded_exchange_compress", True))

    @property
    def backend(self) -> str:
        return backend_for(self._dn.reduction_ctx.config)

    def lane(self):
        """The background control-lane context (re-exported so callers
        that only schedule — the scrubber's decode checks — need not
        import qos themselves)."""
        return qos.background()

    def send(self, addr, op: str, nbytes: int, **fields) -> dict:
        """One throttled, control-lane peer exchange.  ``nbytes`` is the
        payload size to pace under the balance throttle: the push bytes
        for writes, the expected response bytes for gather-style reads
        (the link cost either way)."""
        dn = self._dn
        with qos.background():
            fault_injection.point("coded_exchange.send", dn_id=dn.dn_id,
                                  op=op, tenant=qos.current_tenant())
            dn.qos.admit(qos.current_tenant(), op)
            dn.balance_throttler.throttle(max(int(nbytes), 0))
            t0 = time.monotonic()
            resp = dn._peer_call(addr, op, **fields)
            _M.incr("exchange_ops")
            _M.incr("exchange_wire_bytes", max(int(nbytes), 0))
            _M.observe("exchange_us", (time.monotonic() - t0) * 1e6)
        return resp
