"""Permission model: owner/group/mode, POSIX ACLs, caller context.

Re-expression of the reference's permission plane:

- ``FSPermissionChecker.java:49`` (681 LoC) — per-call checker walking the
  inode chain: EXECUTE on every ancestor, the requested access on the
  target, owner/superuser for attribute changes.
- ``AclStorage.java:65`` / ``FSDirAclOp.java`` — POSIX-draft ACLs: named
  user/group entries masked by the mask entry, plus DEFAULT entries on
  directories that seed their children's access ACLs.
- ``UserGroupInformation`` — the caller identity; here a per-thread call
  context populated by the RPC layer from ``_user``/``_groups`` kwargs
  (the wire is the trust boundary, as with the reference's SASL-backed
  UGI).  In-process callers carry no identity and act as the superuser —
  matching the reference, where the NN's own threads bypass checking.

Permissions are evaluated the HDFS way: the superuser (the NN process
owner) bypasses everything; otherwise owner bits, then named-user entries
(& mask), then owner-group + named-group entries (& mask, any grant wins),
then other bits.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

READ, WRITE, EXECUTE = 4, 2, 1


@dataclass
class Attrs:
    """Inode security attributes (INodeAttributes analog)."""

    owner: str
    group: str
    mode: int
    # Access ACL: list of [kind, name, perm] with kind in
    # ("user", "group", "mask", "other"); name == "" refers to the owner
    # entry ("user::perm") / owner group ("group::perm").
    acl: list = field(default_factory=list)
    # Default ACL (directories only): entries new children inherit.
    dacl: list = field(default_factory=list)
    xattrs: dict = field(default_factory=dict)  # name -> bytes
    # Storage policy name (hot/warm/cold/all_ssd/one_ssd) or None =
    # inherit from the nearest ancestor (BlockStoragePolicySuite analog —
    # the reference stores the policy id in the inode header).
    policy: str | None = None

    def pack(self) -> list:
        return [self.owner, self.group, self.mode, self.acl, self.dacl,
                {k: bytes(v) for k, v in self.xattrs.items()}, self.policy]

    @staticmethod
    def unpack(v: list | None, owner="hdrf", group="supergroup",
               mode=0o755) -> "Attrs":
        if not v:
            return Attrs(owner, group, mode)
        return Attrs(v[0], v[1], v[2], [list(e) for e in v[3]],
                     [list(e) for e in v[4]], dict(v[5]),
                     v[6] if len(v) > 6 else None)


class DirNode(dict):
    """Directory inode: a dict of children + security attributes.  Keeps
    ``isinstance(node, dict)`` true everywhere the namespace walks."""

    def __init__(self, *a, attrs: Attrs | None = None,
                 inode_id: int = 0, **kw):
        super().__init__(*a, **kw)
        self.attrs = attrs or Attrs("hdrf", "supergroup", 0o755)
        self.inode_id = inode_id  # stable identity for snapshot diff


_CTX = threading.local()


def set_caller(user: str | None, groups: list[str] | None) -> None:
    _CTX.user = user
    _CTX.groups = list(groups or [])


def caller() -> tuple[str | None, list[str]]:
    return getattr(_CTX, "user", None), getattr(_CTX, "groups", [])


def effective_entries(attrs: Attrs):
    """(named_users, named_groups, mask) from the access ACL."""
    named_u: dict[str, int] = {}
    named_g: dict[str, int] = {}
    mask = None
    for kind, name, perm in attrs.acl:
        if kind == "user" and name:
            named_u[name] = perm
        elif kind == "group" and name:
            named_g[name] = perm
        elif kind == "mask":
            mask = perm
    if mask is None and (named_u or named_g):
        mask = (attrs.mode >> 3) & 7
    return named_u, named_g, mask


def allows(attrs: Attrs, user: str, groups: list[str], want: int) -> bool:
    """The FSPermissionChecker access algorithm for one inode."""
    if user == attrs.owner:
        return (attrs.mode >> 6) & want == want
    named_u, named_g, mask = effective_entries(attrs)
    if user in named_u:
        perm = named_u[user] if mask is None else named_u[user] & mask
        return perm & want == want
    in_group = attrs.group in groups or attrs.group == user
    grp_perm = (attrs.mode >> 3) & 7
    candidates = []
    if in_group:
        candidates.append(grp_perm if mask is None else grp_perm & mask)
    for g, p in named_g.items():
        if g in groups:
            candidates.append(p if mask is None else p & mask)
    if candidates:  # any granting entry wins (POSIX ACL group class)
        return any(c & want == want for c in candidates)
    return attrs.mode & want == want


def inherit_attrs(parent: Attrs, user: str, group: str | None,
                  is_dir: bool, umode: int | None = None) -> Attrs:
    """Attributes for a new child: owner = caller, group = parent's group
    (BSD semantics, what HDFS does), default ACL of the parent becomes the
    child's access ACL (and default ACL again for directories)."""
    mode = umode if umode is not None else (0o755 if is_dir else 0o644)
    acl = [list(e) for e in parent.dacl]
    dacl = [list(e) for e in parent.dacl] if is_dir else []
    group = group or parent.group
    return Attrs(user, group, mode, acl, dacl)


def acl_spec_parse(spec: str) -> list:
    """'user:alice:rwx,group::r-x,mask::rw-' -> entries.  The setfacl
    format (minus default: prefix, which callers split off)."""
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        bits = part.split(":")
        if len(bits) != 3:
            raise ValueError(f"bad ACL entry {part!r}")
        kind, name, p = bits
        if kind not in ("user", "group", "mask", "other"):
            raise ValueError(f"bad ACL kind {kind!r}")
        perm = 0
        for ch, v in (("r", READ), ("w", WRITE), ("x", EXECUTE)):
            if ch in p:
                perm |= v
        out.append([kind, name, perm])
    return out


def acl_to_strings(attrs: Attrs) -> list[str]:
    def fmt(perm):
        return "".join(c if perm & v else "-"
                       for c, v in (("r", 4), ("w", 2), ("x", 1)))

    out = [f"user::{fmt((attrs.mode >> 6) & 7)}"]
    for kind, name, perm in attrs.acl:
        if kind in ("user", "group") and name:
            out.append(f"{kind}:{name}:{fmt(perm)}")
    out.append(f"group::{fmt((attrs.mode >> 3) & 7)}")
    _, _, mask = effective_entries(attrs)
    if mask is not None:
        out.append(f"mask::{fmt(mask)}")
    out.append(f"other::{fmt(attrs.mode & 7)}")
    for kind, name, perm in attrs.dacl:
        out.append(f"default:{kind}:{name}:{fmt(perm)}")
    return out
