"""Block write path: packet ingest, pipeline mirroring, reduction hook.

Re-expression of BlockReceiver.java:

- ``receive_direct``: the stock streaming path — packets forwarded to the
  mirror as received (BlockReceiver.java:635-641 ``mirrorPacketTo``), written
  to the local replica, per-packet acks upstream (PacketResponder,
  BlockReceiver.java:1509).  The final empty packet's ack aggregates the
  whole downstream chain (durability); earlier acks are flow control.
- ``receive_reduced``: the reduction path.  The reference buffers the block
  into a direct ByteBuffer ``bf1`` (BlockReceiver.java:877-897), acks, and
  reduces asynchronously (DDRunner) — while every pipeline node re-runs
  reduction on the raw stream independently.  Here DN1 buffers, reduces
  ONCE, then ships the *reduced form* downstream ("reduced Block Mirroring",
  the IEEE-paper capability missing from the reference snapshot; SURVEY.md §0
  fact 3) and acks the last packet only after local commit + downstream ack.
- Mirror-side ingest of the reduced form is ``ingest_reduced``: for dedup
  schemes the mirror receives the ordered hash list, answers with the set of
  chunks it lacks (one round trip), and receives exactly those bytes — the
  "chunk index delta".

Checksums: crc32c per ``checksum_chunk`` of the LOGICAL bytes are computed on
ingest and stored in BlockMeta (the reference writes the checksum meta file
even in reduction mode, BlockReceiver.java:924-986) so readers can verify
end-to-end regardless of the stored form.

Every ingest path opens a utils/profiler.py BlockTimeline and attributes its
wall time to named phases (``recv``/``checksum``/``container_io``/
``mirror_stream``/``ack`` here; ``dedup_lookup``/``wal_commit`` land from
reduction/dedup.py and index/chunk_index.py; ``device_wait`` from the device
ledger) — the decomposition the gap-attribution report and ROADMAP item 1's
pipeline refactor are measured by.
"""

from __future__ import annotations

import queue
import socket
import threading
import time
from typing import TYPE_CHECKING

from hdrf_tpu import native
from hdrf_tpu.proto import datatransfer as dt
from hdrf_tpu.proto.rpc import recv_frame, send_frame
from hdrf_tpu.utils import (fault_injection, log, metrics, profiler, qos,
                            retry, tracing)

if TYPE_CHECKING:
    from hdrf_tpu.server.datanode import DataNode

_M = metrics.registry("block_receiver")
_TR = tracing.tracer("datanode")
_LOG = log.get_logger("block_receiver")


def _checksums(data: bytes, chunk: int) -> list[int]:
    return [int(c) for c in native.crc32c_chunks(data, chunk)]


class MirrorLegFailed(IOError):
    """A downstream mirror hop failed; ``dn_id`` names the ACTUAL broken
    peer — propagated back through the per-hop status frame that rides
    ahead of the fixed 9-byte ack — so the NN outlier feed never blames
    ``targets[0]`` for a failure two relay hops down."""

    def __init__(self, msg: str, dn_id: str | None = None):
        super().__init__(msg)
        self.dn_id = dn_id


def _connect(addr: list | tuple, dn=None, block_id: int | None = None,
             token: dict | None = None) -> socket.socket:
    """Mirror-leg socket; encrypts when this DN is configured to (the
    reference's DN->DN SASL legs — tokens minted from the shared block keys
    when the incoming op's token isn't reusable)."""
    # connect timeout clamped by the ambient deadline budget (a mirror
    # leg may never outlive what's left of the end-to-end write budget)
    s = socket.create_connection((addr[0], addr[1]),
                                 timeout=retry.effective_budget(60.0))
    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    if dn is not None and dn.config.encrypt_data_transfer:
        if not token or not token.get("sig"):
            token = dn.tokens.mint(block_id, "w")
        s = dt.secure_socket(s, token, True)
    return s


class BlockReceiver:
    def __init__(self, dn: "DataNode"):
        self._dn = dn

    def _note_peer(self, target: dict, seconds: float, nbytes: int) -> None:
        """Record a downstream-transfer latency sample for slow-peer
        detection (DataNodePeerMetrics feeding SlowPeerTracker.java:56),
        normalized to seconds per MB ACTUALLY SENT.  ``seconds`` must cover
        only the downstream portion: the push_reduced leg passes its whole
        duration (all of it is downstream transfer); the direct pipeline
        passes the accumulated mirror write + ack-drain time so upstream
        recv/disk slowness is never misattributed to the peer."""
        dn_id = target.get("dn_id")
        if dn_id and nbytes > 0:
            self._dn.note_peer_latency(
                dn_id, seconds / max(nbytes / 2**20, 1e-3))

    # ------------------------------------------------------------ direct path

    def receive_direct(self, sock: socket.socket, fields: dict) -> None:
        """Stock pipeline: stream packets to disk + mirror, ack per packet."""
        dn = self._dn
        block_id, gen_stamp = fields["block_id"], fields["gen_stamp"]
        targets = fields.get("targets", [])
        mirror_sock = None
        with profiler.block_timeline(block_id) as tl, \
                dn.direct_slot():  # bounded concurrent streaming writes
            with profiler.phase("container_io"):
                writer = dn.replicas.create_rbw(
                    block_id, gen_stamp,
                    storage_type=fields.get("storage_type"))
            try:
                if targets:
                    mirror_sock = _connect(targets[0]["addr"], dn, block_id,
                                           fields.get("token"))
                    # each hop rewrites the routing hint to ITS target's
                    # slot type (the NN annotates every target)
                    dt.send_op(mirror_sock, dt.WRITE_BLOCK,
                               **{**fields, "targets": targets[1:],
                                  "storage_type":
                                  targets[0].get("storage_type")})
                crcs: list[int] = []
                tail = b""
                cchunk = dn.checksum_chunk
                forwarded = 0
                drained = 0   # mirror acks consumed by flush barriers
                fwd_bytes = 0
                mirror_t = 0.0  # downstream-only time (write + ack drain)
                for seqno, data, flags in profiler.timed_iter(
                        "recv", dt.iter_packets_ex(sock)):
                    last = bool(flags & dt.FLAG_LAST)
                    fault_injection.point("block_receiver.packet",
                                          block_id=block_id, seqno=seqno,
                                          dn_id=dn.dn_id)
                    if mirror_sock is not None:
                        _mt0 = time.perf_counter()
                        with profiler.phase("mirror_stream"):
                            dt.write_packet(mirror_sock, seqno, data,
                                            flags=flags)
                        mirror_t += time.perf_counter() - _mt0
                        forwarded += 1
                        fwd_bytes += len(data)
                    if data:
                        with profiler.phase("container_io"):
                            writer.write(data)
                        with profiler.phase("checksum"):
                            tail += data
                            while len(tail) >= cchunk:
                                crcs.append(native.crc32c(tail[:cchunk]))
                                tail = tail[cchunk:]
                    if not last and flags & (dt.FLAG_FLUSH | dt.FLAG_SYNC):
                        # hflush/hsync barrier: every downstream node must
                        # have processed the prefix before we ack (the
                        # PipelineAck semantics hflush depends on) — drain
                        # the mirror's acks up to this packet, then expose
                        # the visible length (+fsync for hsync) locally.
                        status = dt.ACK_SUCCESS
                        if mirror_sock is not None:
                            _mt0 = time.perf_counter()
                            with profiler.phase("mirror_stream"):
                                while drained < forwarded:
                                    _, down = dt.read_ack(mirror_sock)
                                    status = max(status, down)
                                    drained += 1
                            mirror_t += time.perf_counter() - _mt0
                        vis_crcs = crcs + ([native.crc32c(tail)]
                                           if tail else [])
                        with profiler.phase("container_io"):
                            writer.flush_visible(
                                vis_crcs, cchunk,
                                sync=bool(flags & dt.FLAG_SYNC))
                        with profiler.phase("ack"):
                            dt.send_ack(sock, seqno, status)
                    elif not last:
                        with profiler.phase("ack"):
                            dt.send_ack(sock, seqno)
                    else:
                        if tail:
                            crcs.append(native.crc32c(tail))
                        status = dt.ACK_SUCCESS
                        if mirror_sock is not None:
                            # Drain ALL mirror acks (one per forwarded packet);
                            # the final one carries the aggregated downstream
                            # status — earlier ones are flow control.
                            _mt0 = time.perf_counter()
                            with profiler.phase("mirror_stream"):
                                for _ in range(forwarded - drained):
                                    _, down = dt.read_ack(mirror_sock)
                                    status = max(status, down)
                            mirror_t += time.perf_counter() - _mt0
                            self._note_peer(targets[0], mirror_t, fwd_bytes)
                        with profiler.phase("container_io"):
                            meta = writer.finalize(writer.bytes_written,
                                                   "direct", crcs, cchunk)
                        writer = None
                        tl.nbytes = meta.logical_len
                        with profiler.phase("ack"):
                            dn.notify_block_received(block_id,
                                                     meta.logical_len,
                                                     meta.gen_stamp)
                            dt.send_ack(sock, seqno, status)
                        _M.incr("blocks_received_direct")
            except (ConnectionError, OSError, IOError):
                # Pipeline died mid-stream (client/upstream crash): persist
                # the acked prefix as a partial replica instead of dropping
                # it — the RBW-persistence behavior lease recovery's length
                # sync depends on (BlockRecoveryWorker syncs the MINIMUM
                # replica length across the pipeline; a dropped prefix here
                # would silently shrink that to zero).  Every buffered packet
                # passed its CRC, so the prefix is a safe sync candidate.
                if writer is not None and writer.bytes_written > 0 \
                        and not dn._crashed:
                    # _crashed: a crash simulation (MiniCluster
                    # kill_datanode) — a dead process cannot finalize, and
                    # doing so here would race the restarted DN's recovery
                    # scan over the same directory
                    if tail:
                        crcs.append(native.crc32c(tail))
                    meta = writer.finalize(writer.bytes_written, "direct",
                                           crcs, cchunk)
                    writer = None
                    dn.notify_block_received(block_id, meta.logical_len,
                                             meta.gen_stamp)
                    _M.incr("partial_replicas_persisted")
                raise
            finally:
                if writer is not None:
                    if dn._crashed:
                        writer.detach()   # crash sim: leave rbw + sidecar
                    else:
                        writer.abort()
                if mirror_sock is not None:
                    mirror_sock.close()

    # ----------------------------------------------------------- reduced path

    def receive_reduced(self, sock: socket.socket, fields: dict) -> None:
        """Reduce-path ingest.  The admission slot is acquired BEFORE any
        buffering (the reference gates at op dispatch, DataXceiver.java:
        349-380 — gating after the buffer fills is the unbounded-memory
        failure mode SURVEY §7(b) warns about): at most
        ``max_concurrent_writes`` blocks are ever buffered.

        With a co-located reduction worker configured, packets are
        FORWARDED to the worker as they arrive (client -> DN -> worker ->
        HBM is one pipeline; the worker stages bytes to device mid-stream)
        and only (cuts, digests) come back; otherwise the block buffers
        locally (bf1 analog) and reduces in-process.

        Memory honesty (r3 verdict weak #7): even on the worker path the
        DN ALSO accumulates the block host-side (``parts``) — container
        appends need the unique chunks' bytes after the worker answers,
        and re-fetching them from the worker would double the IPC.  So
        "the DN host stays device-free" holds, but peak host memory is
        ~2x block per in-flight write across the two processes, bounded
        by the admission slots acquired above."""
        dn = self._dn
        block_id, gen_stamp = fields["block_id"], fields["gen_stamp"]
        scheme_name = fields["scheme"]
        targets = fields.get("targets", [])
        scheme = dn.scheme(scheme_name)
        tenant = fields.get("_client")
        t_start = time.monotonic()
        # Overload gate BEFORE the slot and the buffer (utils/qos.py): a
        # shed burns neither an admission slot nor pipeline work.  The
        # write protocol has no pre-stream response frame, so the client
        # streams regardless — consume the packet run (flow control only,
        # nothing buffered) and answer every packet with an ACK_SHED whose
        # seqno field carries the retry-after hint in ms
        # (proto/datatransfer.py ACK_SHED).  Unattributed ingests (mirror
        # relays re-entering as write ops) are internal and never shed.
        if tenant is not None:
            try:
                dn.qos.admit(tenant, "write")
            except qos.ShedError as e:
                _M.incr("write_sheds")
                hint_ms = int(max(e.retry_after_s, 0.0) * 1e3)
                for _seqno, _data, _last in dt.iter_packets(sock):
                    dt.send_ack(sock, hint_ms, dt.ACK_SHED)
                raise
        with profiler.block_timeline(block_id) as tl, \
                dn.write_slot(), \
                qos.bind_tenant(tenant):  # admission BEFORE buffering
            parts: list[bytes] = []
            last_seqno = [0]
            # each next() wait on the client stream is one "recv" span
            packets = profiler.timed_iter("recv", dt.iter_packets(sock))

            def stream():
                for seqno, data, last in packets:
                    last_seqno[0] = seqno
                    # same per-packet crash window as the direct path (the
                    # resilience fault matrix kills the worker mid-stream
                    # from here); a RAISING handler aborts the write like
                    # any other client-stream error
                    fault_injection.point("block_receiver.packet",
                                          block_id=block_id, seqno=seqno,
                                          dn_id=dn.dn_id)
                    # ack (flow control) and buffer BEFORE yielding: a
                    # consumer abandoning the generator mid-yield (worker
                    # death) must lose neither the ack nor the bytes
                    if not last:
                        with profiler.phase("ack"):
                            dt.send_ack(sock, seqno)
                    if data:
                        parts.append(data)
                        yield data

            precomputed = None
            worker_down = False
            crcs = None
            use_worker = (dn.reduction_ctx.worker is not None
                          and getattr(scheme, "container_codec", None)
                          is not None)
            # multi-block pipeline (pipeline_depth > 1): acks + CRC move to
            # a pump thread, the device dispatch to the shared coalescer
            pipelined = (not use_worker
                         and dn.write_pipeline is not None
                         and getattr(scheme, "container_codec", None)
                         is not None)
            if use_worker:
                from hdrf_tpu.server.reduction_worker import WorkerError

                try:
                    precomputed = dn.reduction_ctx.worker.reduce_stream(
                        stream(), dn.reduction_ctx.config.cdc)
                    _M.incr("worker_reduces")
                except (WorkerError, retry.DeadlineExceeded) as e:
                    # WORKER failed, hung past its deadline budget, or its
                    # breaker is open (zero-cost refusal) — client-stream
                    # errors propagate as their own types and abort the
                    # write as before.  Degraded mode: drain the remaining
                    # packets and compute in-process (passthrough).
                    _M.incr("worker_fallbacks")
                    _M.incr("degraded_writes")
                    _LOG.warning("worker reduce failed; degraded write",
                                 dn_id=dn.dn_id, block_id=block_id,
                                 trace=tracing.current_context(),
                                 error=f"{type(e).__name__}: {e}")
                    worker_down = True
                    for _ in stream():
                        pass
            elif pipelined:
                data, crcs, precomputed = self._drain_pipelined(
                    sock, tl, block_id, packets, parts, last_seqno)
            else:
                for _ in stream():
                    pass
            if not pipelined:
                with profiler.phase("buffer_assemble"):
                    data = b"".join(parts)
                tl.nbytes = len(data)
            if worker_down:
                # compute here WITHOUT re-trying the dead worker (the
                # scheme would otherwise reconnect per block while the
                # admission slot is held)
                import numpy as _np

                from hdrf_tpu.ops import dispatch as _dispatch

                precomputed = _dispatch.chunk_and_fingerprint(
                    _np.frombuffer(data, dtype=_np.uint8),
                    dn.reduction_ctx.config.cdc, dn.reduction_ctx.backend)
            # parent: the ambient xceiver span when _xceive opened one
            # (Tracer.span falls back to it), else resume the wire context
            # directly (continueTraceSpan, Receiver.java:94-98)
            with _TR.span("reduce_block",
                          parent=tuple(fields["_trace"])
                          if fields.get("_trace")
                          and tracing.current_context() is None
                          else None) as sp:
                sp.annotate("block_id", block_id)
                sp.annotate("scheme", scheme_name)
                status = self._store_and_mirror(
                    block_id, gen_stamp, scheme_name, data, targets,
                    precomputed=precomputed, crcs=crcs)
            with profiler.phase("ack"):
                dt.send_ack(sock, last_seqno[0], status)
            if tenant is not None:
                # deficit bucket debit + write service estimator feed:
                # actual bytes are only known after the stream landed
                dn.qos.charge(tenant, "write", len(data),
                              latency_s=time.monotonic() - t_start)
        _M.incr("blocks_received_reduced")

    def _drain_pipelined(self, sock: socket.socket, tl, block_id: int,
                         packets, parts: list[bytes], last_seqno: list):
        """Pipelined ingest (``pipeline_depth`` > 1, no co-located worker).

        Two moves off the connection thread's critical path:

        - flow-control acks and incremental CRC run on a per-connection
          pump thread bound to this block's timeline (the inline ``ack``
          slice was 5.1% of smoke wall; the CRC now overlaps the client-
          stream ``recv`` waits — the transport-hiding PERF_NOTES round 4
          says is the only host overlap available);
        - the fully-buffered block goes to the DN's shared WritePipeline;
          its device dispatch is ENQUEUED before the pump join below, so
          block K+1's device work is in flight while block K's host
          commit runs on its own connection thread.

        The pump is the sole socket writer until joined; the caller sends
        the final ack only after this returns.  Returns
        ``(data, crcs, (cuts, digests))``."""
        dn = self._dn
        pump_q: queue.Queue = queue.Queue()
        crcs: list[int] = []
        pump_err: list[BaseException] = []

        def _pump():
            tail = b""
            cchunk = dn.checksum_chunk
            with profiler.bind_timeline(tl):
                while True:
                    item = pump_q.get()
                    if item is None:
                        break
                    if pump_err:
                        continue  # drain so the recv loop never blocks
                    seqno, part = item
                    try:
                        if seqno is not None:
                            with profiler.phase("ack"):
                                dt.send_ack(sock, seqno)
                        if part:
                            with profiler.phase("checksum"):
                                tail += part
                                while len(tail) >= cchunk:
                                    crcs.append(int(native.crc32c(
                                        tail[:cchunk])))
                                    tail = tail[cchunk:]
                    except BaseException as e:  # noqa: BLE001 — re-raised
                        pump_err.append(e)
                if not pump_err and tail:
                    with profiler.phase("checksum"):
                        crcs.append(int(native.crc32c(tail)))

        with profiler.phase("pipeline_submit"):  # thread spawn is host work
            pump = threading.Thread(target=_pump, name="recv-pump",
                                    daemon=True)
            pump.start()
        try:
            for seqno, data, last in packets:
                last_seqno[0] = seqno
                fault_injection.point("block_receiver.packet",
                                      block_id=block_id, seqno=seqno,
                                      dn_id=dn.dn_id)
                # hand ack + CRC to the pump BEFORE buffering continues —
                # same loss-safety as stream(): the bytes land in ``parts``
                # on this thread regardless of what the pump does
                pump_q.put((None if last else seqno, data))
                if data:
                    parts.append(data)
        finally:
            pump_q.put(None)  # pump exits even if the client stream died
        with profiler.phase("buffer_assemble"):
            data = b"".join(parts)
        tl.nbytes = len(data)
        import numpy as _np

        with profiler.phase("pipeline_submit"):
            fut = dn.write_pipeline.submit(
                block_id, _np.frombuffer(data, dtype=_np.uint8), tl)
        # residual pump work (tail CRC chunks) runs under the dispatch just
        # enqueued; the join wait is checksum time from this thread's view
        with profiler.phase("checksum"):
            pump.join()
        if pump_err:
            raise pump_err[0]
        return data, crcs, fut.result()

    def _store_and_mirror(self, block_id: int, gen_stamp: int, scheme_name: str,
                          data: bytes, targets: list,
                          precomputed=None, crcs=None) -> int:
        dn = self._dn
        scheme = dn.scheme(scheme_name)
        if crcs is None:
            with profiler.phase("checksum"):
                crcs = _checksums(data, dn.checksum_chunk)
        with metrics.registry("datanode").time("reduce_us"):
            # no host phase around reduce itself: the native path records
            # "reduce_compute" at the dispatch choke point, the worker path
            # records "device_wait" at its final drain, and the in-process
            # jax path is attributed by the device ledger
            if precomputed is not None:
                # (cuts, digests) from the worker/pipeline path; the mesh
                # plane adds a third element — the on-device dedup-probe
                # verdict set that lets dedup_commit skip the host index
                # walk for probe-negative chunks.
                cuts, digs, *rest = precomputed
                stored = scheme.reduce_with(block_id, data, cuts, digs,
                                            dn.reduction_ctx,
                                            probe=rest[0] if rest else None)
            else:
                stored = scheme.reduce(block_id, data, dn.reduction_ctx)
        with profiler.phase("container_io"):
            writer = dn.replicas.create_rbw(block_id, gen_stamp)
        try:
            with profiler.phase("container_io"):
                if stored:
                    writer.write(stored)
                meta = writer.finalize(len(data), scheme_name, crcs,
                                       dn.checksum_chunk)
        except (OSError, ValueError) as e:
            # storage-layer failure (disk IO / corrupt state): clean up the
            # rbw, log with the active trace, and re-raise — the xceiver
            # accounts it.  Anything else propagates with the rbw left for
            # the startup recovery scan (no silent broad catch).
            _LOG.error("reduced store failed", dn_id=dn.dn_id,
                       block_id=block_id,
                       trace=tracing.current_context(),
                       error=f"{type(e).__name__}: {e}")
            if dn._crashed:
                writer.detach()   # crash sim: dead processes delete nothing
            else:
                writer.abort()
            raise
        with profiler.phase("ack"):
            dn.notify_block_received(block_id, meta.logical_len,
                                     meta.gen_stamp)
        status = dt.ACK_SUCCESS
        if targets:
            try:
                failed_dn = dn.mirror.push(block_id, gen_stamp, scheme_name,
                                           len(data), stored, crcs, targets)
                if failed_dn:
                    # every leg we drove landed, but a deeper relay hop
                    # broke: the per-hop status frame carried its dn_id up
                    self._note_mirror_failure(
                        self._target_named(targets, failed_dn), block_id,
                        IOError("downstream relay leg failed"))
            except (OSError, ConnectionError, retry.DeadlineExceeded) as e:
                # Mirror failed; local copy is durable — the NN's redundancy
                # monitor re-replicates (§3.5).  Matches pipeline-recovery
                # semantics: report success for the local replica.
                if not getattr(e, "already_attributed", False):
                    self._note_mirror_failure(
                        self._target_named(targets,
                                           getattr(e, "dn_id", None)),
                        block_id, e)
        return status

    @staticmethod
    def _target_named(targets: list, dn_id: str | None) -> dict:
        """The target dict matching ``dn_id``; falls back to targets[0]
        (a direct-leg failure carries no deeper attribution)."""
        if dn_id:
            for t in targets:
                if t.get("dn_id") == dn_id:
                    return t
            return {"dn_id": dn_id}
        return targets[0]

    def _note_mirror_failure(self, target: dict, block_id: int,
                             e: BaseException) -> None:
        """Outright mirror-leg failure: per-peer attribution rides the
        next heartbeat (DataNode.note_mirror_failure) so the NN's outlier
        detector flags BROKEN mirrors, not just slow ones."""
        _M.incr("mirror_failures")
        dn_id = target.get("dn_id")
        if dn_id:
            self._dn.note_mirror_failure(dn_id)
        _LOG.warning("mirror push failed", dn_id=self._dn.dn_id,
                     peer=dn_id, block_id=block_id,
                     trace=tracing.current_context(),
                     error=f"{type(e).__name__}: {e}")

    # -------------------------------------------- reduced mirroring (push side)

    def push_reduced(self, block_id: int, gen_stamp: int, scheme_name: str,
                     logical_len: int, stored: bytes, crcs: list[int],
                     targets: list, throttler=None) -> str | None:
        """Ship the reduced form to targets[0], which relays to the rest.
        Used by both pipeline mirroring and NN-commanded re-replication
        (transferBlock, DataNode.java:2361 — which the reference serves by
        reconstructing FULL bytes, §3.3 note).  ``throttler`` caps the
        send rate on background legs (balancer moves, re-replication —
        DataTransferThrottler's role); client pipeline legs pass None.

        Returns the dn_id of a FAILED deeper relay hop when the local leg
        succeeded anyway (propagated up through the per-hop status frame),
        None when the whole chain landed; raises :class:`MirrorLegFailed`
        carrying the broken hop's dn_id otherwise."""
        dn = self._dn
        scheme = dn.scheme(scheme_name)
        push_t0 = time.perf_counter()
        mirror = _connect(targets[0]["addr"], dn, block_id)
        try:
            with profiler.phase("mirror_stream"):
                if getattr(scheme, "container_codec", None) is not None:
                    # dedup family: hashes + need-list negotiation + chunk
                    # delta
                    entry = dn.index.get_block(block_id)
                    if entry is None:
                        raise IOError(
                            f"block {block_id} missing from chunk index")
                    dt.send_op(mirror, "write_reduced", block_id=block_id,
                               gen_stamp=gen_stamp, scheme=scheme_name,
                               logical_len=logical_len, checksums=crcs,
                               checksum_chunk=dn.checksum_chunk,
                               token=dn.tokens.mint(block_id, "w"),
                               hashes=entry.hashes, targets=targets[1:])
                    # indices into unique hash list
                    need = recv_frame(mirror)["need"]
                    uniq = list(dict.fromkeys(entry.hashes))
                    needed_hashes = [uniq[i] for i in need]
                    with profiler.phase("dedup_lookup"):
                        locs = dn.index.lookup_chunks(needed_hashes)
                    chunk_locs = [(locs[h].container_id, locs[h].offset,
                                   locs[h].length) for h in needed_hashes]
                    with profiler.phase("container_io"):
                        chunks = dn.containers.read_chunks(chunk_locs)
                    seqno = 0
                    sent_bytes = 0
                    for chunk in chunks:
                        if throttler is not None:
                            throttler.throttle(len(chunk))
                        # the mid-chunk-delta crash window: a mirror dying
                        # between packets of the delta stream
                        fault_injection.point("block_receiver.mirror_push",
                                              block_id=block_id,
                                              seqno=seqno, dn_id=dn.dn_id,
                                              peer=targets[0].get("dn_id"))
                        dt.write_packet(mirror, seqno, chunk)
                        sent_bytes += len(chunk)
                        seqno += 1
                    dt.write_packet(mirror, seqno, b"", last=True)
                    hop = recv_frame(mirror)  # per-hop status frame
                    _, status = dt.read_ack(mirror)
                else:
                    # direct/compress family: ship the stored bytes as-is
                    dt.send_op(mirror, "write_reduced", block_id=block_id,
                               gen_stamp=gen_stamp, scheme=scheme_name,
                               logical_len=logical_len, checksums=crcs,
                               checksum_chunk=dn.checksum_chunk,
                               token=dn.tokens.mint(block_id, "w"),
                               hashes=None, targets=targets[1:])
                    # symmetric need-frame (always empty here)
                    recv_frame(mirror)
                    dt.stream_bytes(mirror, stored, dn.config.packet_size,
                                    throttle=throttler.throttle
                                    if throttler is not None else None)
                    sent_bytes = len(stored)
                    hop = recv_frame(mirror)  # per-hop status frame
                    _, status = dt.read_ack(mirror)
            failed_dn = hop.get("failed_dn") if isinstance(hop, dict) else None
            if status != dt.ACK_SUCCESS:
                raise MirrorLegFailed(
                    f"mirror returned status {status}",
                    dn_id=failed_dn or targets[0].get("dn_id"))
            self._note_peer(targets[0], time.perf_counter() - push_t0,
                            max(sent_bytes, 1))
            _M.incr("reduced_mirror_pushes")
            return failed_dn
        finally:
            mirror.close()

    # ------------------------------------------- reduced mirroring (ingest side)

    def ingest_reduced(self, sock: socket.socket, fields: dict) -> None:
        """Mirror side of push_reduced: store the reduced form WITHOUT
        re-running reduction (the whole point of reduced block mirroring)."""
        dn = self._dn
        block_id, gen_stamp = fields["block_id"], fields["gen_stamp"]
        scheme_name, logical_len = fields["scheme"], fields["logical_len"]
        crcs, cchunk = fields["checksums"], fields["checksum_chunk"]
        hashes, targets = fields["hashes"], fields.get("targets", [])
        with profiler.block_timeline(block_id, nbytes=logical_len):
            self._ingest_reduced_inner(sock, dn, block_id, gen_stamp,
                                       scheme_name, logical_len, crcs, cchunk,
                                       hashes, targets)
        _M.incr("blocks_ingested_reduced")

    def _ingest_reduced_inner(self, sock, dn, block_id, gen_stamp, scheme_name,
                              logical_len, crcs, cchunk, hashes,
                              targets) -> None:
        # ingest-entry crash window (the fault matrix kills the mirror
        # right here, before any frame goes back upstream)
        fault_injection.point("block_receiver.ingest_reduced",
                              block_id=block_id, gen_stamp=gen_stamp,
                              dn_id=dn.dn_id)
        existing = dn.replicas.get_meta(block_id)
        if existing is not None and existing.gen_stamp > gen_stamp:
            # stale-generation push (a re-push raced a pipeline-recovery
            # gen bump, updatePipeline/FSNamesystem.java analog): refuse
            # before any container append — accepting would roll the
            # replica back behind its recovered generation
            _M.incr("stale_gen_rejected")
            raise IOError(f"stale gen_stamp {gen_stamp} < "
                          f"{existing.gen_stamp} for block {block_id}")
        stored = b""
        if hashes is not None:
            hashes = [bytes(h) for h in hashes]
            uniq = list(dict.fromkeys(hashes))
            with profiler.phase("dedup_lookup"):
                known = dn.index.lookup_chunks(uniq)
            need = [i for i, h in enumerate(uniq) if known[h] is None]
            # torn need-frame window: the mirror dying mid-negotiation
            # (upstream sees a half-written frame / reset socket)
            fault_injection.point("block_receiver.need_frame",
                                  block_id=block_id, dn_id=dn.dn_id)
            send_frame(sock, {"need": need})
            chunks = [data for _, data, last in profiler.timed_iter(
                "recv", dt.iter_packets(sock)) if data]
            if len(chunks) != len(need):
                raise IOError(f"expected {len(need)} chunks, got {len(chunks)}")
            with profiler.phase("container_io"):
                locs = dn.containers.append_chunks(
                    chunks, on_seal=dn.index.seal_container)
            new_chunks = {uniq[i]: loc for i, loc in zip(need, locs)}
            dn.index.commit_block(block_id, logical_len, hashes, new_chunks)
        else:
            send_frame(sock, {"need": []})
            with profiler.phase("recv"):
                stored = dt.collect_packets(sock)
        with profiler.phase("container_io"):
            writer = dn.replicas.create_rbw(block_id, gen_stamp)
        try:
            with profiler.phase("container_io"):
                if stored:
                    writer.write(stored)
                meta = writer.finalize(logical_len, scheme_name, list(crcs),
                                       cchunk)
        except (OSError, ValueError) as e:
            # same contract as _store_and_mirror: typed cleanup + traced
            # log + re-raise (no silent broad catch)
            _LOG.error("reduced ingest failed", dn_id=dn.dn_id,
                       block_id=block_id,
                       trace=tracing.current_context(),
                       error=f"{type(e).__name__}: {e}")
            if dn._crashed:
                writer.detach()   # crash sim: dead processes delete nothing
            else:
                writer.abort()
            raise
        with profiler.phase("ack"):
            dn.notify_block_received(block_id, meta.logical_len,
                                     meta.gen_stamp)
        # a full replica supersedes any coded segments held for the block
        # (re-push upgrade path of the partial-replica lifecycle)
        dn.mirror.on_full_replica(block_id)
        status = dt.ACK_SUCCESS
        failed_dn = None
        if targets:  # relay down the chain
            try:
                failed_dn = self.push_reduced(block_id, gen_stamp,
                                              scheme_name, logical_len,
                                              stored, list(crcs), targets)
            except (OSError, ConnectionError, retry.DeadlineExceeded) as e:
                failed_dn = getattr(e, "dn_id", None) \
                    or targets[0].get("dn_id")
                self._note_mirror_failure(
                    self._target_named(targets, failed_dn), block_id, e)
        with profiler.phase("ack"):
            # per-hop status frame ahead of the fixed 9-byte ack: carries
            # the failing downstream dn_id so upstream hops (and
            # ultimately the primary's outlier feed) blame the ACTUAL
            # broken peer, not targets[0]
            send_frame(sock, {"status": int(status), "failed_dn": failed_dn})
            dt.send_ack(sock, 0, status)
