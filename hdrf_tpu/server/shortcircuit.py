"""Short-circuit local reads: Unix-domain fd passing.

Re-expression of the reference's short-circuit stack — client
`hdfs/shortcircuit/ShortCircuitCache.java:72` + DN `ShortCircuitRegistry`
(REQUEST_SHORT_CIRCUIT_FDS op over a DomainSocket, fd passed with
SCM_RIGHTS, libhadoop JNI underneath) — in ~100 lines, because Python's
``socket.send_fds`` wraps the same kernel facility directly.

The DataNode listens on ``<data_dir>/sc.sock``.  A local client asks for a
block's fds; the DN replies with the replica metadata (scheme, lengths,
checksums) and, when the replica has a physical data file whose bytes ARE the
logical bytes (direct scheme), the open file descriptor.  Reduced replicas
(dedup/compress) answer metadata-only and the client falls back to the TCP
read path — reconstruction must run on the DN where the chunk store lives.
"""

from __future__ import annotations

import array
import json
import os
import socket
import threading
from typing import TYPE_CHECKING

from hdrf_tpu.utils import metrics

if TYPE_CHECKING:
    from hdrf_tpu.server.datanode import DataNode

_M = metrics.registry("shortcircuit")
MAX_REQ = 4096


def _entok(token: dict | None) -> dict | None:
    """Block token for the JSON request: the HMAC sig is bytes, hex it."""
    if token is None:
        return None
    t = dict(token)
    t["sig"] = bytes(t["sig"]).hex()
    return t


def _detok(token: dict | None) -> dict | None:
    if token is None or "sig" not in token:
        return token
    t = dict(token)
    try:
        t["sig"] = bytes.fromhex(t["sig"])
    except (TypeError, ValueError):
        pass  # malformed sig: verification will reject it
    return t


class ShortCircuitServer:
    """DN side: serve REQUEST_SHORT_CIRCUIT_FDS on a unix socket."""

    def __init__(self, dn: "DataNode", sock_path: str):
        self._dn = dn
        self.path = sock_path
        if os.path.exists(sock_path):
            os.unlink(sock_path)
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.bind(sock_path)
        self._sock.listen(16)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._serve,
                                        name="dn-shortcircuit", daemon=True)

    def start(self) -> "ShortCircuitServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()
        if os.path.exists(self.path):
            os.unlink(self.path)

    def _serve(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _handle(self, conn: socket.socket) -> None:
        try:
            req = json.loads(conn.recv(MAX_REQ).decode())
            block_id = req["block_id"]
            # Same gate as the TCP read path: when block tokens are enabled,
            # REQUEST_SHORT_CIRCUIT_FDS requires a READ token (the reference
            # enforces this in DataXceiver.requestShortCircuitFds) — a local
            # process that can reach sc.sock must not bypass authorization.
            try:
                self._dn.tokens.verify(_detok(req.get("token")), block_id, "r")
            except PermissionError:
                _M.incr("token_rejected")
                payload = json.dumps({"status": "denied"}).encode()
                conn.sendall(len(payload).to_bytes(4, "little") + payload)
                return
            meta = self._dn.replicas.get_meta(block_id)
            if meta is None:
                payload = json.dumps({"status": "no_block"}).encode()
                conn.sendall(len(payload).to_bytes(4, "little") + payload)
                return
            resp = {"status": "ok", "scheme": meta.scheme,
                    "logical_len": meta.logical_len,
                    "physical_len": meta.physical_len,
                    "checksum_chunk": meta.checksum_chunk,
                    "checksums": meta.checksums,
                    "fd": meta.scheme == "direct" and meta.physical_len > 0}
            # Length-prefixed reply: checksum lists for large blocks run to
            # tens of KB, far past any single recv.  The fd rides the
            # ancillary data of the 4-byte prefix send.
            payload = json.dumps(resp).encode()
            prefix = len(payload).to_bytes(4, "little")
            if resp["fd"]:
                fd = os.open(self._dn.replicas.data_path(block_id),
                             os.O_RDONLY)
                try:
                    socket.send_fds(conn, [prefix], [fd])
                finally:
                    os.close(fd)  # receiver holds its own copy
                conn.sendall(payload)
                _M.incr("fds_passed")
            else:
                conn.sendall(prefix + payload)
                _M.incr("metadata_only")
        except (OSError, ValueError, KeyError):
            _M.incr("errors")
        finally:
            conn.close()


def read_local(sock_path: str, block_id: int, offset: int,
               length: int, token: dict | None = None) -> bytes | None:
    """Client side: fetch the replica fd over the unix socket and pread the
    range directly — zero copies through the DN process.  Returns None when
    short-circuit isn't possible (reduced replica, dead socket, remote DN,
    missing/invalid block token)."""
    try:
        conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        conn.settimeout(10)
        conn.connect(sock_path)
    except OSError:
        return None
    fds: list[int] = []
    try:
        conn.sendall(json.dumps({"block_id": block_id,
                                 "token": _entok(token)}).encode())
        prefix, fds, _, _ = socket.recv_fds(conn, 4, 1)
        while len(prefix) < 4:
            more = conn.recv(4 - len(prefix))
            if not more:
                return None
            prefix += more
        want = int.from_bytes(prefix[:4], "little")
        buf = bytearray()
        while len(buf) < want:
            piece = conn.recv(want - len(buf))
            if not piece:
                return None
            buf += piece
        resp = json.loads(bytes(buf).decode())
        if resp.get("status") != "ok" or not resp.get("fd") or not fds:
            return None
        end = resp["logical_len"] if length < 0 else min(
            offset + length, resp["logical_len"])
        data = os.pread(fds[0], end - offset, offset)
        if len(data) != end - offset:
            return None  # truncated replica: fall back, let the scanner act
        if not _verify(data, offset, resp):
            _M.incr("checksum_failures")
            return None  # corrupt local replica: fall back to another copy
        _M.incr("local_reads")
        _M.incr("local_bytes", len(data))
        return data
    except (OSError, ValueError):
        return None
    finally:
        for fd in fds:
            os.close(fd)
        conn.close()


def _verify(data: bytes, offset: int, resp: dict) -> bool:
    """The same end-to-end crc32c verification the TCP read path applies
    (client/filesystem.py) — a passed fd must not bypass it."""
    from hdrf_tpu import native

    cchunk = resp.get("checksum_chunk", 0)
    stored = resp.get("checksums") or []
    if not cchunk or not stored or offset % cchunk:
        return True  # unaligned range: verified end-to-end only via TCP path
    logical = resp["logical_len"]
    first = offset // cchunk
    for i in range((len(data) + cchunk - 1) // cchunk):
        piece = data[i * cchunk:(i + 1) * cchunk]
        full = len(piece) == cchunk or offset + len(data) == logical
        if full and first + i < len(stored):
            if native.crc32c(piece) != stored[first + i]:
                return False
    return True
