"""Short-circuit local reads: Unix-domain fd passing + shm slot revocation.

Re-expression of the reference's short-circuit stack — client
`hdfs/shortcircuit/ShortCircuitCache.java:72` + DN
`ShortCircuitRegistry.java:83` with `ShortCircuitShm` (REQUEST_SHORT_CIRCUIT_FDS
over a DomainSocket, fd passed with SCM_RIGHTS, a shared-memory segment of
per-replica slots the DN flips to revoke) — because Python's
``socket.send_fds`` and ``mmap`` wrap the same kernel facilities directly.

The DataNode listens on ``<data_dir>/sc.sock``.  A local client asks for a
block's fds; the DN replies with the replica metadata (scheme, lengths,
checksums) and, when the replica has a physical data file whose bytes ARE the
logical bytes (direct scheme), the open file descriptor.  Reduced replicas
(dedup/compress) answer metadata-only and the client falls back to the TCP
read path — reconstruction must run on the DN where the chunk store lives.

Revocation (the registry half the fd pass alone lacks): a client may CACHE
granted fds (``ShortCircuitCache``); a cached fd can outlive the replica
(delete) or serve stale bytes (append supersede).  So each grant carries a
SLOT in a shared-memory segment the client obtained from the DN (one shm
fd-passed per client connection set, slots byte-sized); the DN's registry
flips the slot to 0 when the replica is invalidated or superseded, and the
client checks its slot BEFORE every cached-fd read — invalid means drop the
fd and re-request (falling back to TCP when the block is gone)."""

from __future__ import annotations

import array
import json
import mmap
import os
import socket
import threading
from typing import TYPE_CHECKING

from hdrf_tpu.utils import metrics, profiler, tenants

if TYPE_CHECKING:
    from hdrf_tpu.server.datanode import DataNode

_M = metrics.registry("shortcircuit")
MAX_REQ = 4096
SHM_SLOTS = 4096


class ShortCircuitRegistry:
    """DN-side grant registry (ShortCircuitRegistry.java:83 analog): shm
    segments per client, slot allocation per granted fd, revocation by
    slot write."""

    def __init__(self, directory: str):
        self._dir = directory
        self._lock = threading.Lock()
        self._next_shm = 0
        self._shms: dict[int, mmap.mmap] = {}
        self._free: dict[int, list[int]] = {}
        # per-slot generation: a recycled slot gets a NEW generation, so a
        # client still holding the old grant fails its gen compare instead
        # of being re-validated by an unrelated grant (the ABA hazard)
        self._gen: dict[tuple[int, int], int] = {}
        # block_id -> [(shm_id, slot)] of outstanding grants
        self._grants: dict[int, list[tuple[int, int]]] = {}

    def alloc_shm(self) -> tuple[int, int]:
        """Create a slot segment; returns (shm_id, fd).  The fd is passed
        to the client (both sides mmap the same file); the backing file is
        unlinked immediately — it lives as long as the fds/mmaps do.  The
        caller must arrange ``free_shm`` when the owning client goes away
        (the server ties it to the alloc connection's lifetime — the
        DomainSocketWatcher role)."""
        with self._lock:
            shm_id = self._next_shm
            self._next_shm += 1
        path = os.path.join(self._dir, f".scshm-{shm_id}")
        fd = os.open(path, os.O_CREAT | os.O_RDWR | os.O_EXCL, 0o600)
        os.ftruncate(fd, SHM_SLOTS)
        os.unlink(path)
        mm = mmap.mmap(fd, SHM_SLOTS)
        with self._lock:
            self._shms[shm_id] = mm
            self._free[shm_id] = list(range(SHM_SLOTS - 1, -1, -1))
        _M.incr("shms_allocated")
        return shm_id, fd

    def free_shm(self, shm_id: int) -> None:
        """Client went away: release its segment and every grant in it."""
        with self._lock:
            mm = self._shms.pop(shm_id, None)
            self._free.pop(shm_id, None)
            for bid in list(self._grants):
                kept = [(s, sl) for s, sl in self._grants[bid]
                        if s != shm_id]
                if kept:
                    self._grants[bid] = kept
                else:
                    del self._grants[bid]
            for key in [k for k in self._gen if k[0] == shm_id]:
                del self._gen[key]
            if mm is not None:
                mm.close()
                _M.incr("shms_freed")

    def release(self, shm_id: int, slot: int, gen: int) -> None:
        """Client voluntarily dropped a cached fd (eviction, failed pread)
        — reclaim the slot (ReleaseShortCircuitAccessSlot analog); without
        this, long-lived clients touching many blocks would drain the
        segment and silently degrade to uncached reads.  The GENERATION
        must match: a release racing a concurrent revoke+re-grant of the
        same slot would otherwise free ANOTHER grant's slot and
        double-insert it into the free list."""
        with self._lock:
            mm = self._shms.get(shm_id)
            if mm is None or self._gen.get((shm_id, slot)) != gen:
                return   # stale release: the slot moved on
            for bid, grants in list(self._grants.items()):
                if (shm_id, slot) in grants:
                    grants.remove((shm_id, slot))
                    if not grants:
                        del self._grants[bid]
                    mm[slot] = 0
                    self._free[shm_id].append(slot)
                    _M.incr("slots_released")
                    return

    def grant(self, shm_id: int, block_id: int) -> tuple[int, int] | None:
        """Allocate + validate a slot for a granted fd; returns
        (slot, generation) or None when the shm is unknown or full (the
        client must then use the fd single-shot, uncached)."""
        with self._lock:
            mm = self._shms.get(shm_id)
            free = self._free.get(shm_id)
            if mm is None or not free:
                return None
            slot = free.pop()
            key = (shm_id, slot)
            gen = self._gen.get(key, 0) % 255 + 1   # 1..255, never 0
            self._gen[key] = gen
            mm[slot] = gen
            self._grants.setdefault(block_id, []).append(key)
            _M.incr("slots_granted")
            return slot, gen

    def revoke(self, block_id: int) -> int:
        """Replica deleted or superseded: invalidate every outstanding
        grant's slot so cached fds are dropped before the next read."""
        with self._lock:
            grants = self._grants.pop(block_id, [])
            for shm_id, slot in grants:
                mm = self._shms.get(shm_id)
                if mm is not None:
                    mm[slot] = 0
                    self._free[shm_id].append(slot)
            if grants:
                _M.incr("slots_revoked", len(grants))
            return len(grants)

    def close(self) -> None:
        with self._lock:
            for mm in self._shms.values():
                mm.close()
            self._shms.clear()
            self._grants.clear()
            self._gen.clear()


def _entok(token: dict | None) -> dict | None:
    """Block token for the JSON request: the HMAC sig is bytes, hex it."""
    if token is None:
        return None
    t = dict(token)
    t["sig"] = bytes(t["sig"]).hex()
    return t


def _detok(token: dict | None) -> dict | None:
    if token is None or "sig" not in token:
        return token
    t = dict(token)
    try:
        t["sig"] = bytes.fromhex(t["sig"])
    except (TypeError, ValueError):
        pass  # malformed sig: verification will reject it
    return t


class ShortCircuitServer:
    """DN side: serve REQUEST_SHORT_CIRCUIT_FDS on a unix socket."""

    def __init__(self, dn: "DataNode", sock_path: str):
        self._dn = dn
        self.path = sock_path
        if os.path.exists(sock_path):
            os.unlink(sock_path)
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.bind(sock_path)
        self._sock.listen(16)
        self.registry = ShortCircuitRegistry(os.path.dirname(sock_path)
                                             or ".")
        # open liveness (alloc_shm) connections: stop() must sever them so
        # clients learn the registry died — daemon handler threads outlive
        # an in-process restart and would otherwise keep the channel open
        self._live_conns: set = set()
        self._live_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._serve,
                                        name="dn-shortcircuit", daemon=True)

    def start(self) -> "ShortCircuitServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()
        with self._live_lock:
            conns = list(self._live_conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        if os.path.exists(self.path):
            os.unlink(self.path)

    def _serve(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def stop_registry(self) -> None:
        self.registry.close()

    def _handle(self, conn: socket.socket) -> None:
        try:
            req = json.loads(conn.recv(MAX_REQ).decode())
            if req.get("op") == "alloc_shm":
                # hand the client its slot segment (ShortCircuitShm): the
                # fd rides the ancillary data, the id routes future
                # grants.  The connection then STAYS OPEN as the client's
                # liveness channel (DomainSocketWatcher role): EOF means
                # the client is gone and its segment + grants are freed.
                shm_id, fd = self.registry.alloc_shm()
                try:
                    with self._live_lock:
                        self._live_conns.add(conn)
                    payload = json.dumps({"status": "ok",
                                          "shm_id": shm_id}).encode()
                    prefix = len(payload).to_bytes(4, "little")
                    try:
                        socket.send_fds(conn, [prefix], [fd])
                    finally:
                        os.close(fd)
                    conn.sendall(payload)
                    try:
                        while conn.recv(1):
                            pass   # client never writes; EOF = disconnect
                    except OSError:
                        pass
                finally:
                    # freed on ANY exit — a client killed mid-handshake
                    # must not leak the segment
                    with self._live_lock:
                        self._live_conns.discard(conn)
                    self.registry.free_shm(shm_id)
                return
            if req.get("op") == "release":
                self.registry.release(int(req["shm_id"]), int(req["slot"]),
                                      int(req.get("gen", -1)))
                payload = json.dumps({"status": "ok"}).encode()
                conn.sendall(len(payload).to_bytes(4, "little") + payload)
                return
            block_id = req["block_id"]
            # Same gate as the TCP read path: when block tokens are enabled,
            # REQUEST_SHORT_CIRCUIT_FDS requires a READ token (the reference
            # enforces this in DataXceiver.requestShortCircuitFds) — a local
            # process that can reach sc.sock must not bypass authorization.
            try:
                self._dn.tokens.verify(_detok(req.get("token")), block_id, "r")
            except PermissionError:
                _M.incr("token_rejected")
                payload = json.dumps({"status": "denied"}).encode()
                conn.sendall(len(payload).to_bytes(4, "little") + payload)
                return
            # The fd-grant serve is a (tiny) read too: its timeline rings
            # beside the TCP serve_read ones so short-circuit latency is
            # attributed on the same read families.
            with profiler.read_timeline(block_id):
                with profiler.phase("index_lookup"):
                    meta = self._dn.replicas.get_meta(block_id)
                if meta is None:
                    payload = json.dumps({"status": "no_block"}).encode()
                    conn.sendall(len(payload).to_bytes(4, "little") + payload)
                    return
                resp = {"status": "ok", "scheme": meta.scheme,
                        "logical_len": meta.logical_len,
                        "physical_len": meta.physical_len,
                        "checksum_chunk": meta.checksum_chunk,
                        "checksums": meta.checksums,
                        # never pass an fd for an in-flight (hflush-visible)
                        # replica: its rbw file is still growing and the
                        # granted checksums would go stale — network reads
                        # serve the visible prefix instead
                        "fd": (meta.scheme == "direct"
                               and meta.physical_len > 0
                               and not self._dn.replicas.is_rbw(block_id))}
                if resp["fd"] and "shm_id" in req:
                    # revocable grant: the slot index + generation the client
                    # must check before every cached-fd read
                    g = self.registry.grant(int(req["shm_id"]), block_id)
                    if g is not None:
                        resp["slot"], resp["slot_gen"] = g
                # Length-prefixed reply: checksum lists for large blocks run
                # to tens of KB, far past any single recv.  The fd rides the
                # ancillary data of the 4-byte prefix send.
                payload = json.dumps(resp).encode()
                prefix = len(payload).to_bytes(4, "little")
                # Book the op BEFORE the reply hits the wire so a client
                # that just read its payload observes the tenant counter.
                tenants.note_op(req.get("_client"), "read_sc")
                with profiler.phase("net_send"):
                    if resp["fd"]:
                        fd = os.open(self._dn.replicas.data_path(block_id),
                                     os.O_RDONLY)
                        try:
                            socket.send_fds(conn, [prefix], [fd])
                        finally:
                            os.close(fd)  # receiver holds its own copy
                        conn.sendall(payload)
                        _M.incr("fds_passed")
                    else:
                        conn.sendall(prefix + payload)
                        _M.incr("metadata_only")
        except (OSError, ValueError, KeyError):
            _M.incr("errors")
        finally:
            conn.close()


def _request(sock_path: str, req: dict,
             keep_conn: bool = False
             ) -> tuple[dict | None, list[int], socket.socket | None]:
    """One round trip on the unix socket; returns (response, passed fds,
    connection).  The caller owns any returned fds; the connection is
    returned open only with ``keep_conn`` (the shm liveness channel),
    otherwise closed."""
    try:
        conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        conn.settimeout(10)
        conn.connect(sock_path)
    except OSError:
        return None, [], None
    fds: list[int] = []
    try:
        conn.sendall(json.dumps(req).encode())
        prefix, fds, _, _ = socket.recv_fds(conn, 4, 1)
        fds = list(fds)
        while len(prefix) < 4:
            more = conn.recv(4 - len(prefix))
            if not more:
                raise OSError("short prefix")
            prefix += more
        want = int.from_bytes(prefix[:4], "little")
        buf = bytearray()
        while len(buf) < want:
            piece = conn.recv(want - len(buf))
            if not piece:
                raise OSError("short body")
            buf += piece
        resp = json.loads(bytes(buf).decode())
        if keep_conn:
            return resp, fds, conn
        conn.close()
        return resp, fds, None
    except (OSError, ValueError):
        for fd in fds:
            os.close(fd)
        conn.close()
        return None, [], None


class ShortCircuitCache:
    """Client-side fd cache (ShortCircuitCache.java:72 analog): granted
    fds are kept and re-used across reads, each guarded by its shm slot —
    the DN zeroes the slot when the replica is deleted/superseded, and the
    next read drops the stale fd and re-requests instead of serving stale
    bytes."""

    def __init__(self):
        self._lock = threading.Lock()
        # sock_path -> (shm mmap|None, shm_id|None, liveness conn|None)
        self._shm: dict[str, tuple] = {}
        # (sock_path, block_id) -> (fd, slot, slot_gen, resp meta); only
        # slot-guarded grants are cached — an unguarded fd would be
        # unrevocable and could serve stale bytes forever
        self._fds: dict[tuple[str, int], tuple[int, int, int, dict]] = {}

    def _shm_for(self, sock_path: str):
        with self._lock:
            if sock_path in self._shm:
                return self._shm[sock_path]
        # the connection stays OPEN both ways: the DN frees the segment on
        # our EOF, and WE learn the DN died/restarted from its EOF — an
        # orphaned mmap would otherwise keep stale gen values forever
        resp, fds, conn = _request(sock_path, {"op": "alloc_shm"},
                                   keep_conn=True)
        mm = shm_id = None
        if resp and resp.get("status") == "ok" and fds:
            try:
                mm = mmap.mmap(fds[0], SHM_SLOTS)
                shm_id = resp["shm_id"]
            except (OSError, ValueError):
                mm = shm_id = None
        for fd in fds:
            os.close(fd)
        if mm is None:
            # transient failure: do NOT cache it, the next read retries
            if conn is not None:
                conn.close()
            return (None, None, None)
        conn.setblocking(False)
        with self._lock:
            if sock_path in self._shm:   # lost a setup race: keep first
                conn.close()
                mm.close()
            else:
                self._shm[sock_path] = (mm, shm_id, conn)
            return self._shm[sock_path]

    def _dn_alive(self, sock_path: str, conn) -> bool:
        """Poll the liveness connection: EOF/error means the DN (or its
        registry) is gone — every grant from it is void."""
        if conn is None:
            return False
        try:
            if conn.recv(1) == b"":
                raise OSError("EOF")
            return True           # DN never writes; data would be a bug
        except (BlockingIOError, InterruptedError):
            return True
        except OSError:
            with self._lock:
                ent = self._shm.pop(sock_path, None)
                dead = [k for k in self._fds if k[0] == sock_path]
                fds = [self._fds.pop(k)[0] for k in dead]
            for fd in fds:
                os.close(fd)
            if ent is not None:
                if ent[2] is not None:
                    ent[2].close()
                if ent[0] is not None:
                    ent[0].close()
            _M.incr("shm_channels_lost")
            return False

    def _drop(self, key: tuple[str, int], release: bool = True) -> None:
        with self._lock:
            ent = self._fds.pop(key, None)
            shm = self._shm.get(key[0])
        if ent is None:
            return
        os.close(ent[0])
        if release and shm is not None and shm[1] is not None:
            # hand the slot back (ReleaseShortCircuitAccessSlot): not
            # doing so would drain the segment over a client's lifetime;
            # the generation guards against racing a revoke+re-grant
            _request(key[0], {"op": "release", "shm_id": shm[1],
                              "slot": ent[1], "gen": ent[2]})

    def read(self, sock_path: str, block_id: int, offset: int,
             length: int, token: dict | None = None,
             client_name: str | None = None) -> bytes | None:
        key = (sock_path, block_id)
        with self._lock:
            ent = self._fds.get(key)
        mm, shm_id, conn = self._shm_for(sock_path)
        if ent is not None:
            fd, slot, gen, resp = ent
            if mm is None or not self._dn_alive(sock_path, conn):
                # DN gone/restarted: _dn_alive dropped every cached fd;
                # try a fresh segment right away (restart case)
                mm, shm_id, conn = self._shm_for(sock_path)
            elif mm[slot] != gen:
                # revoked (slot zeroed) or recycled to another grant (gen
                # mismatch): either way this fd may map dead bytes; the
                # slot is already back in the DN's free list
                _M.incr("cached_fd_revoked")
                self._drop(key, release=False)
            else:
                out = self._pread(fd, offset, length, resp)
                if out is not None:
                    _M.incr("cached_fd_reads")
                    return out
                self._drop(key)  # stale/corrupt: refetch below
        req = {"block_id": block_id, "token": _entok(token)}
        if client_name:
            req["_client"] = client_name  # tenant attribution (utils/tenants.py)
        if shm_id is not None:
            req["shm_id"] = shm_id
        resp, fds, _ = _request(sock_path, req)
        if not resp or resp.get("status") != "ok" or not resp.get("fd") \
                or not fds:
            for fd in fds:
                os.close(fd)
            return None
        fd = fds[0]
        for extra in fds[1:]:
            os.close(extra)
        out = self._pread(fd, offset, length, resp)
        slot, gen = resp.get("slot"), resp.get("slot_gen")
        if out is None or slot is None or gen is None:
            # no revocation guard (shm full/unavailable): single-use fd —
            # caching it would make delete/append invisible to this client
            os.close(fd)
            return out
        with self._lock:
            old = self._fds.get(key)
            self._fds[key] = (fd, slot, gen, resp)
        if old is not None:
            os.close(old[0])
        return out

    @staticmethod
    def _pread(fd: int, offset: int, length: int,
               resp: dict) -> bytes | None:
        end = resp["logical_len"] if length < 0 else min(
            offset + length, resp["logical_len"])
        try:
            data = os.pread(fd, end - offset, offset)
        except OSError:
            return None
        if len(data) != end - offset:
            return None  # truncated replica: fall back, let the scanner act
        if not _verify(data, offset, resp):
            _M.incr("checksum_failures")
            return None  # corrupt local replica: fall back to another copy
        _M.incr("local_reads")
        _M.incr("local_bytes", len(data))
        return data

    def close(self) -> None:
        with self._lock:
            for fd, _, _, _ in self._fds.values():
                os.close(fd)
            self._fds.clear()
            for mm, _, conn in self._shm.values():
                if conn is not None:
                    conn.close()   # EOF -> DN frees the segment + grants
                if mm is not None:
                    mm.close()
            self._shm.clear()


def read_local(sock_path: str, block_id: int, offset: int,
               length: int, token: dict | None = None,
               client_name: str | None = None) -> bytes | None:
    """Uncached one-shot short-circuit read: fd fetched, pread, closed —
    no shm allocation (a throwaway segment per call would grow the DN's
    registry for nothing)."""
    req = {"block_id": block_id, "token": _entok(token)}
    if client_name:
        req["_client"] = client_name  # tenant attribution (utils/tenants.py)
    resp, fds, _ = _request(sock_path, req)
    if not resp or resp.get("status") != "ok" or not resp.get("fd") \
            or not fds:
        for fd in fds:
            os.close(fd)
        return None
    try:
        return ShortCircuitCache._pread(fds[0], offset, length, resp)
    finally:
        for fd in fds:
            os.close(fd)


def _verify(data: bytes, offset: int, resp: dict) -> bool:
    """The same end-to-end crc32c verification the TCP read path applies
    (client/filesystem.py) — a passed fd must not bypass it."""
    from hdrf_tpu import native

    cchunk = resp.get("checksum_chunk", 0)
    stored = resp.get("checksums") or []
    if not cchunk or not stored or offset % cchunk:
        return True  # unaligned range: verified end-to-end only via TCP path
    logical = resp["logical_len"]
    first = offset // cchunk
    for i in range((len(data) + cchunk - 1) // cchunk):
        piece = data[i * cchunk:(i + 1) * cchunk]
        full = len(piece) == cchunk or offset + len(data) == logical
        if full and first + i < len(stored):
            if native.crc32c(piece) != stored[first + i]:
                return False
    return True
