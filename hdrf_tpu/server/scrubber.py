"""Continuous integrity scrub + garbage census for one DataNode.

Re-expresses the reference's background verification stack —
VolumeScanner.java:47 (rolling block verification at a throttled byte
rate, dfs.block.scanner.volume.bytes.per.second), DirectoryScanner.java:56
(disk-vs-memory reconciliation sweep), BlockScanner.java:41 (per-volume
scanner lifecycle) — over the reduction layers the shadow-block design
added, where the reference's checks cannot see:

- **Sealed containers** (storage/container_store.py:308): decode and
  re-verify a sampled fraction of live chunk digests against the chunk
  index (index/chunk_index.py:508 ``live_chunks_in`` — fingerprints ARE
  the SHA-256 digests, so one hash per sampled chunk is the whole
  oracle).  One corrupt shared chunk silently poisons every block that
  references it, which is exactly why the sample walks the INDEX, not
  the replica files.
- **EC stripes** (storage/stripe_store.py:139): CRC every local stripe
  (owner stripes against the WAL manifest's ``crcs``; foreign stripes
  against a first-scrub CRC baseline, since the manifest lives with the
  owner), plus a rotating any-k decode spot-check per cycle
  (server/ec_tier.py:280 ``_gather``) proving the group still decodes
  to the manifest geometry.
- **Replica invariants** (storage/replica_store.py): a reduced replica
  must be exactly 0 stored bytes with live index entries behind it; a
  direct replica must match its recorded length + CRCs (one deep
  ``verify_block`` per cycle, rotating — the scanner's rolling cursor,
  VolumeScanner.java:539, at census cadence).
- **Garbage census**: zero-refcount dead chunk bytes (the index's
  ``_apply b"del"`` removes dead chunks outright, so garbage = container
  payload − live bytes), orphan appended bytes from dedup-race loser
  commits (index/chunk_index.py:287 ``commit_block`` returns the losers;
  the index attributes their bytes per container), aged ``*.tmp`` files
  from crashed tmp+fsync+replace writes (container seal, stripe put,
  mirror-segment put), and mirror segments still held after a
  full-replica upgrade (server/mirror_plane.py:470).

Detection turns into response (tentpole c): a scrub-confirmed corrupt
container is **quarantined** (files renamed aside — never served again,
surviving restarts), every block referencing it is invalidated and
``bad_block``-reported so the NN's redundancy monitor re-replicates from
healthy peers (server/namenode.py rpc_bad_block); a corrupt stripe is
quarantined and repaired locally when this DN owns the group's manifest,
else ``bad_stripe``-reported so the NN's ``_check_stripe_repair`` monitor
schedules the owner's re-decode.  Both count ``scrub_repairs_triggered``.

Cadence/veto discipline follows the DN's other background monitors
(server/datanode.py:1382 ``_scanner_loop``): injectable clock, byte-rate
throttle (utils/throttler.py), and a health veto — a cycle is skipped
while the node is reduction-degraded or any of its breaker edges is open
(scrubbing a sick node would add load exactly when it can least afford
it, the DataNode.java:2533 background-work discipline).
"""

from __future__ import annotations

import hashlib
import os
import random
import time

from hdrf_tpu.storage import stripe_store
from hdrf_tpu.utils import fault_injection, metrics, qos, retry
from hdrf_tpu.utils.throttler import Throttler

_S = metrics.registry("scrub")

#: tmp-orphan sweep targets, relative to their owning store roots
_TMP_SUFFIX = ".tmp"
#: quarantined-aside suffix: outside every store's served-name patterns
#: (*.raw / *.sealed / *.stripe), so a quarantined file can never be
#: opened by a read path again, across restarts too
QUAR_SUFFIX = ".quar"


class Scrubber:
    """One DataNode's integrity-scrub plane.  ``run_cycle`` is driven by
    the DN's ``-scrubber`` thread (server/datanode.py start()); tests call
    it directly for determinism (the sample_once pattern of
    utils/flight_recorder.py)."""

    def __init__(self, dn, clock=time.monotonic):
        self._dn = dn
        self._clock = clock
        self._rng = random.Random(0x5C12B)
        self._throttler = Throttler(
            int(dn.config.scrub_rate_mb_s * (1 << 20)))
        # foreign stripes carry no local manifest: first scrub records a
        # CRC baseline, later scrubs detect bit-rot against it
        self._stripe_crcs: dict[tuple[str, int, int], int] = {}
        # rotating cursors (VolumeScanner.java:539's position analog)
        self._decode_cursor = 0
        self._replica_cursor = 0
        # census gauges from the last completed cycle (heartbeat payload)
        self._last_census: dict[str, int] = {}
        self._cycles = 0

    # ------------------------------------------------------------- cycle

    def _vetoed(self) -> bool:
        """Health/breaker veto: never add scrub load to a sick node."""
        if self._dn.reduction_degraded:
            return True
        return any(b.state == "open"
                   for b in retry.all_breakers().values())

    def run_cycle(self) -> dict:
        """One full scrub pass; returns the census it gauged."""
        if self._vetoed():
            _S.incr("scrub_cycles_vetoed")
            return dict(self._last_census)
        t0 = self._clock()
        self._throttler.set_rate(
            int(self._dn.config.scrub_rate_mb_s * (1 << 20)))
        self._scrub_containers()
        self._scrub_stripes()
        self._scrub_replicas()
        census = self._census()
        self._cycles += 1
        _S.incr("scrub_cycles")
        _S.observe("scrub_cycle_us", (self._clock() - t0) * 1e6)
        self._last_census = census
        return census

    # --------------------------------------------------- sealed containers

    def _scrub_containers(self) -> None:
        """Sampled chunk-digest re-verification of every sealed container
        the index references."""
        dn = self._dn
        frac = max(0.0, min(1.0, dn.config.scrub_sample_frac))
        for cid in sorted(dn.index.container_live_bytes()):
            if not dn.index.is_sealed(cid):
                continue  # open lane: still mutating under the writer
            if dn.index.stripe_manifest(cid) is not None:
                # demoted to stripes: the sealed file is gone and reads go
                # through the any-k fallback — one corrupt stripe would
                # read as "container corrupt" here and quarantine a
                # REPAIRABLE group.  The stripe sweep + decode spot-check
                # below own this container's integrity story.
                continue
            live = dn.index.live_chunks_in(cid)
            if not live:
                continue
            sample = [h for h in sorted(live)
                      if frac >= 1.0 or self._rng.random() < frac]
            if not sample:
                sample = [min(live)]  # never skip a container outright
            try:
                fault_injection.point("scrub.container", cid=cid)
                data = dn.containers.read_container(cid)
            except (OSError, IOError, ValueError):
                self._on_corrupt_container(cid)
                continue
            self._throttler.throttle(len(data))
            ok = True
            for h in sample:
                off, ln = live[h]
                if hashlib.sha256(data[off:off + ln]).digest() != h:
                    ok = False
                    break
            _S.incr("scrub_bytes_verified",
                    sum(live[h][1] for h in sample))
            if not ok:
                self._on_corrupt_container(cid)

    def _on_corrupt_container(self, cid: int) -> None:
        """Quarantine + fire the re-replication monitor (tentpole c):
        the container's files are renamed aside (never served again),
        every block referencing it is invalidated here and bad_block-
        reported so the NN re-replicates from healthy peers."""
        dn = self._dn
        _S.incr("scrub_corrupt|class=container")
        dn._log.warning("scrub found corrupt container",
                        dn_id=dn.dn_id, cid=cid)
        dn.containers.quarantine(cid)
        bad = []
        for bid in dn.index.block_ids():
            e = dn.index.get_block(bid)
            if e is None:
                continue
            for h in set(e.hashes):
                loc = dn.index.chunk_location(h)
                if loc is not None and loc.container_id == cid:
                    bad.append(bid)
                    break
        for bid in bad:
            for nn in dn._nns:
                try:
                    nn.call("bad_block", dn_id=dn.dn_id, block_id=bid)
                except (OSError, ConnectionError):
                    _S.incr("scrub_errors")
            dn._invalidate(bid)
            _S.incr("scrub_repairs_triggered")

    # ------------------------------------------------------------ stripes

    def _scrub_stripes(self) -> None:
        """CRC every local stripe; rotate one any-k decode spot-check per
        cycle across this DN's owned stripe groups."""
        dn = self._dn
        for owner, cid, idx, nbytes in dn.ec.store.iter_stripes():
            self._throttler.throttle(nbytes)
            try:
                fault_injection.point("scrub.stripe", owner=owner,
                                      cid=cid, idx=idx)
                data = dn.ec.store.read_stripe(owner, cid, idx)
            except (OSError, IOError):
                self._on_corrupt_stripe(owner, cid, idx)
                continue
            from hdrf_tpu import native

            crc = int(native.crc32c(data))
            want = None
            if owner == dn.dn_id:
                man = dn.index.stripe_manifest(cid)
                if man is not None and idx < len(man["crcs"]):
                    want = int(man["crcs"][idx])
            if want is None:
                key = (owner, cid, idx)
                want = self._stripe_crcs.setdefault(key, crc)
            _S.incr("scrub_bytes_verified", nbytes)
            if crc != want:
                self._on_corrupt_stripe(owner, cid, idx)
        # rotating owner-side any-k decode spot-check: proves the group
        # still reconstructs the exact sealed bytes the manifest describes
        manifests = dn.index.stripe_manifests()
        if manifests:
            cids = sorted(manifests)
            cid = cids[self._decode_cursor % len(cids)]
            self._decode_cursor += 1
            man = manifests[cid]
            with qos.background():
                # scrub gathers are background bulk traffic: the control
                # lane keeps them out of every tenant's admission ledger
                got = dn.ec._gather(cid, man)
            try:
                blob = stripe_store.reconstruct_container(got, man)
                if len(blob) != int(man["length"]):
                    raise stripe_store.StripeCorrupt(
                        f"decode length {len(blob)} != {man['length']}")
                _S.incr("scrub_decode_checks")
                _S.incr("scrub_bytes_verified", len(blob))
            except (stripe_store.StripeCorrupt, ValueError):
                _S.incr("scrub_corrupt|class=stripe")
                _S.incr("scrub_decode_failures")

    def _on_corrupt_stripe(self, owner: str, cid: int, idx: int) -> None:
        """Quarantine the stripe file; repair locally when this DN owns
        the manifest (server/ec_tier.py repair with ourselves as the
        replacement target), else bad_stripe-report so the NN's
        _check_stripe_repair monitor schedules the owner's re-decode."""
        dn = self._dn
        _S.incr("scrub_corrupt|class=stripe")
        dn._log.warning("scrub found corrupt stripe", dn_id=dn.dn_id,
                        owner=owner, cid=cid, idx=idx)
        dn.ec.store.quarantine(owner, cid, idx)
        self._stripe_crcs.pop((owner, cid, idx), None)
        if owner == dn.dn_id and dn.index.stripe_manifest(cid) is not None:
            host, port = dn.addr
            with qos.background():
                # the scrub-triggered repair response runs on the same
                # control lane as NN-scheduled repairs
                dn.ec.repair({"cid": cid, "missing": [idx],
                              "targets": [[dn.dn_id, host, port]]})
        else:
            for nn in dn._nns:
                try:
                    nn.call("bad_stripe", dn_id=dn.dn_id, owner=owner,
                            cid=cid, idx=idx)
                    break
                except (OSError, ConnectionError):
                    _S.incr("scrub_errors")
        _S.incr("scrub_repairs_triggered")

    # ----------------------------------------------------------- replicas

    def _scrub_replicas(self) -> None:
        """Replica invariants for every finalized replica, plus one deep
        length+CRC verification per cycle (rotating cursor)."""
        dn = self._dn
        bids = sorted(dn.replicas.block_ids())
        for bid in bids:
            if dn.replicas.is_rbw(bid):
                continue
            meta = dn.replicas.get_meta(bid)
            if meta is None:
                continue
            fault_injection.point("scrub.replica", block_id=bid)
            if meta.scheme != "direct" and meta.physical_len == 0:
                # reduced replica: its bytes ARE the index entry — a
                # missing entry or dangling chunk ref is a corrupt replica
                entry = dn.index.get_block(bid)
                dangling = entry is None or any(
                    dn.index.chunk_location(h) is None
                    for h in set(entry.hashes))
                if dangling:
                    self._on_corrupt_replica(bid)
        if bids:
            bid = bids[self._replica_cursor % len(bids)]
            self._replica_cursor += 1
            meta = dn.replicas.get_meta(bid)
            if meta is not None and not dn.replicas.is_rbw(bid):
                self._throttler.throttle(max(1, meta.logical_len))
                try:
                    bad = dn.verify_block(bid)
                except (OSError, IOError, ValueError):
                    bad = True
                _S.incr("scrub_bytes_verified", meta.logical_len)
                if bad:
                    self._on_corrupt_replica(bid)

    def _on_corrupt_replica(self, bid: int) -> None:
        dn = self._dn
        _S.incr("scrub_corrupt|class=replica")
        dn._log.warning("scrub found corrupt replica",
                        dn_id=dn.dn_id, block_id=bid)
        for nn in dn._nns:
            try:
                nn.call("bad_block", dn_id=dn.dn_id, block_id=bid)
            except (OSError, ConnectionError):
                _S.incr("scrub_errors")
        dn._invalidate(bid)
        _S.incr("scrub_repairs_triggered")

    # ------------------------------------------------------------- census

    def _tmp_dirs(self) -> list[str]:
        dn = self._dn
        dirs = [v.containers._dir for v in dn.volumes.volumes
                if not v.failed]
        dirs.append(dn.ec.store._dir)
        dirs.append(dn.mirror._store._root)
        return dirs

    def _census(self) -> dict:
        """Gauge the four garbage classes; reclaim what is safely dead
        (aged tmp orphans, segments shadowed by a full replica)."""
        dn = self._dn
        fault_injection.point("scrub.census", dn_id=dn.dn_id)
        now = time.time()
        age_s = dn.config.scrub_tmp_age_s
        tmp_bytes = 0
        quar_bytes = 0
        for d in self._tmp_dirs():
            try:
                names = os.listdir(d)
            except OSError:
                continue
            for name in names:
                path = os.path.join(d, name)
                if name.endswith(_TMP_SUFFIX):
                    try:
                        st = os.stat(path)
                    except OSError:
                        continue
                    if now - st.st_mtime >= age_s:
                        try:
                            os.unlink(path)
                            _S.incr("scrub_tmp_reclaimed")
                            _S.incr("scrub_tmp_reclaimed_bytes", st.st_size)
                        except OSError:
                            tmp_bytes += st.st_size
                    else:
                        tmp_bytes += st.st_size
                elif name.endswith(QUAR_SUFFIX):
                    try:
                        quar_bytes += os.path.getsize(path)
                    except OSError:
                        continue
        # dead-chunk + orphan-loser bytes: container payload minus live
        # (deleted chunks leave the index entirely, chunk_index._apply
        # b"del", so the delta IS the dead set); the index's per-container
        # loser attribution splits the orphan class out of the delta
        live = dn.index.container_live_bytes()
        orphans = dn.index.orphan_bytes()
        dead_bytes = 0
        orphan_bytes = 0
        for v in dn.volumes.volumes:
            if v.failed:
                continue
            store = v.containers
            for cid in store.container_ids():
                payload = self._payload_size(store, cid)
                garbage = max(0, payload - live.get(cid, 0))
                o = min(garbage, orphans.get(cid, 0))
                orphan_bytes += o
                dead_bytes += garbage - o
        # mirror segments shadowed by a full local replica: PR-10 upgrade
        # leftovers — reclaim now, census anything still pending
        seg_bytes = 0
        store = dn.mirror._store
        for bid in store.blocks():
            meta = dn.replicas.get_meta(bid)
            if meta is not None and not dn.replicas.is_rbw(bid):
                dn.mirror.on_full_replica(bid)
        try:
            for name in os.listdir(store._root):
                if name.endswith(".seg"):
                    seg_bytes += os.path.getsize(
                        os.path.join(store._root, name))
        except OSError:
            pass
        census = {"dead_chunks": dead_bytes, "orphan_append": orphan_bytes,
                  "tmp": tmp_bytes, "mirror_segments": seg_bytes,
                  "quarantined": quar_bytes}
        for cls, v in census.items():
            _S.gauge(f"garbage_bytes|class={cls}", v)
        _S.gauge("garbage_bytes_total", sum(census.values()))
        return census

    @staticmethod
    def _payload_size(store, cid: int) -> int:
        """Uncompressed payload size of a container: the sealed header's
        fsync'd ``usize`` (container_store.py:51 _SEAL_HDR), or the raw
        file's size minus the placeholder header."""
        from hdrf_tpu.storage.container_store import (_SEAL_HDR,
                                                      _SEAL_MAGIC)

        try:
            with open(store._sealed_path(cid), "rb") as f:
                hdr = f.read(_SEAL_HDR.size)
            if len(hdr) == _SEAL_HDR.size:
                magic, usize, _codec = _SEAL_HDR.unpack(hdr)
                if magic == _SEAL_MAGIC:
                    return int(usize)
        except OSError:
            pass
        try:
            return max(0, os.path.getsize(store._raw_path(cid))
                       - _SEAL_HDR.size)
        except OSError:
            return 0

    # -------------------------------------------------------------- stats

    def report(self) -> dict:
        """Heartbeat + /stats census payload (server/datanode.py _stats)."""
        return {
            "cycles": self._cycles,
            "bytes_verified": _S.counter("scrub_bytes_verified"),
            "corrupt_total": self.corrupt_total(),
            "garbage_bytes": sum(self._last_census.values()),
            "garbage": dict(self._last_census),
            "repairs_triggered": _S.counter("scrub_repairs_triggered"),
            "tmp_reclaimed": _S.counter("scrub_tmp_reclaimed"),
        }

    @staticmethod
    def corrupt_total() -> int:
        """Sum of the labelled scrub_corrupt counters (the /prom family
        renders as ``scrub_corrupt_total|class=...``)."""
        snap = metrics.registry("scrub").snapshot()
        return sum(int(v) for k, v in snap.get("counters", {}).items()
                   if k.split("|")[0] == "scrub_corrupt")
