"""Chunk-granular read-serving plane: range resolution, a DN-wide decoded-
chunk cache, and coalesced container decodes.

Re-expression of the reference read path one layer above the container
store.  DataConstructor.java's hash-list fetch (:222-235) and metadata
batch lookup + group-by-container (quickBuildMT, DataConstructor.java
:360-417) become an explicit :class:`ChunkPlan` — the position→chunk-range
resolver that lets ``read_logical(offset, length)`` touch ONLY the
containers overlapping the requested range (the reference always
materializes the full block, BlockSender.java:612-623).  The decoded-chunk
LRU has no reference counterpart: the reference re-decompresses whole
containers per read (threadedConstructor, DataConstructor.java:430-567)
and caches nothing chunk-shaped, so a hot dedup'd chunk shared by many
files pays a container decode on every file that touches it.  Here the
cache is keyed by FINGERPRINT, so hits serve cross-file exactly as far as
dedup reached, and a hit books zero decode bytes in the read-amplification
ledger (reduction/accounting.py:118 record_container_decode never fires) —
the compounding win ROADMAP item 1 chases.

The :class:`ReadCoalescer` re-applies server/write_pipeline.py's
group-commit discipline (:149-226: bounded admission, drain-up-to-depth,
lead-timeline binding with mirrored spans) to the read side: concurrent
readers' container-decode misses group into ONE
``ops/dispatch.block_decompress_batch`` call per window, so a container
wanted by N readers decodes once and the per-call dispatch overhead
amortizes across the group.  LZ4 decode itself is byte-serial host work by
design (ops/reconstruct.py:1-30) — the batch surface is the grouped
DISPATCH seam a future device decoder slots into, not a pretend TPU
decoder; on this 1-vCPU host the honest wins are decode-once-per-container
and fewer dispatch round trips (PERF_NOTES.md round 4).  At depth 1 / on
the non-TPU backend the coalescer decodes inline on the caller's thread —
bit-identical results, no extra hops.  Reads still attribute ≥95% of wall
through the PR 11 read timelines: the worker binds the lead reader's
timeline for the real ``container_decode`` spans and mirrors the window to
every other member; the reader-side wait is its own ``decode_wait``
transport phase.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field

from hdrf_tpu.ops import dispatch
from hdrf_tpu.utils import metrics, profiler, qos

_M = metrics.registry("read_plane")


def chunk_cache_hit_ratio() -> float:
    """Decoded-chunk cache hit ratio over the process's cumulative
    ``chunk_cache_hit``/``chunk_cache_miss`` counters (0.0 before any
    probe) — the /prom + /health gauge, the chunk-granular sibling of
    storage/container_store.py:38 cache_hit_ratio."""
    hits, misses = _M.counter("chunk_cache_hit"), _M.counter("chunk_cache_miss")
    total = hits + misses
    return hits / total if total else 0.0


def _gauge_hit_ratio() -> None:
    _M.gauge("chunk_cache_hit_ratio", chunk_cache_hit_ratio())


# ------------------------------------------------------- chunk-range plans


@dataclass
class ChunkPlan:
    """A resolved read: which chunks, from which containers, land where.

    ``wanted[i]`` is the (container_id, offset, length) of the i-th needed
    chunk, ``hashes[i]`` its fingerprint (the chunk-cache key), and
    ``spans[i]`` the (out_at, src_lo, n) scatter into the output buffer —
    the same three-list shape DedupScheme.reconstruct built inline before
    this plane existed (reduction/dedup.py:289)."""

    block_id: int
    offset: int
    end: int
    logical_len: int
    wanted: list = field(default_factory=list)   # (cid, off, len) per chunk
    hashes: list = field(default_factory=list)   # fingerprint per chunk
    spans: list = field(default_factory=list)    # (out_at, src_lo, n)

    @property
    def out_len(self) -> int:
        return max(self.end - self.offset, 0)

    def containers(self) -> list:
        """Distinct containers the plan touches, in first-use order."""
        return list(dict.fromkeys(cid for cid, _, _ in self.wanted))


def resolve_chunk_plan(index, block_id: int, offset: int = 0,
                       length: int = -1) -> ChunkPlan:
    """Position→chunk-range resolution over the chunk index: walk the
    block's ordered hash list accumulating logical positions and keep only
    the chunks overlapping [offset, offset+length) (quickBuildMT's
    group-by-container lookup, DataConstructor.java:360-417, with the
    range cut the reference never does).  ``length=-1`` means to EOF;
    a zero-length / past-EOF request resolves to an empty plan.  Raises
    KeyError for an unindexed block and IOError for a chunk missing from
    the index or a length-sum mismatch (index corruption)."""
    entry = index.get_block(block_id)
    if entry is None:
        raise KeyError(f"block {block_id} not in chunk index")
    end = entry.logical_len if length < 0 else min(offset + length,
                                                   entry.logical_len)
    plan = ChunkPlan(block_id=block_id, offset=offset, end=end,
                     logical_len=entry.logical_len)
    if offset >= end:
        return plan
    locmap = index.lookup_chunks(list(set(entry.hashes)))
    pos = 0
    for h in entry.hashes:
        loc = locmap[h]
        if loc is None:
            raise IOError(f"block {block_id}: chunk {h.hex()} missing "
                          f"from index")
        c_start, c_len = pos, loc.length
        pos += c_len
        if c_start >= end or c_start + c_len <= offset:
            continue
        lo = max(offset, c_start) - c_start
        hi = min(end, c_start + c_len) - c_start
        plan.wanted.append((loc.container_id, loc.offset, loc.length))
        plan.hashes.append(h)
        plan.spans.append((max(offset, c_start) - offset, lo, hi - lo))
    if pos != entry.logical_len:
        raise IOError(f"block {block_id}: chunk lengths sum to {pos}, "
                      f"index says {entry.logical_len}")
    return plan


# ------------------------------------------------------ decoded-chunk LRU


class ChunkCache:
    """Byte-budgeted true-LRU of decoded chunks keyed by fingerprint.

    Sits ABOVE the decoded-container LRU (container_store.py:120): a hit
    here never reaches ``read_container``, so no decode bytes book in the
    read-amplification ledger and the hit serves any file that dedup'd the
    chunk.  Each entry remembers the container it was sliced from so a
    quarantine/delete invalidation (scrubber interplay) can drop exactly
    the entries whose backing bytes are gone."""

    def __init__(self, capacity_bytes: int):
        self._cap = max(int(capacity_bytes), 0)
        self._lock = threading.Lock()
        self._data: dict[bytes, bytes] = {}      # fp -> chunk (LRU order)
        self._cid_of: dict[bytes, int] = {}      # fp -> source container
        self._by_cid: dict[int, set] = {}        # cid -> {fp, ...}
        self._bytes = 0

    @property
    def capacity(self) -> int:
        return self._cap

    @property
    def bytes_used(self) -> int:
        return self._bytes

    def get(self, fp: bytes) -> bytes | None:
        with self._lock:
            data = self._data.pop(fp, None)
            if data is None:
                _M.incr("chunk_cache_miss")
            else:
                # true LRU: re-insert on hit (same discipline as the
                # container LRU — FIFO evicts the hottest under cycles)
                self._data[fp] = data
                _M.incr("chunk_cache_hit")
        _gauge_hit_ratio()
        return data

    def put(self, fp: bytes, data: bytes, cid: int) -> None:
        if self._cap <= 0 or len(data) > self._cap:
            return  # disabled, or a chunk that would evict everything
        with self._lock:
            if fp in self._data:
                self._drop_locked(fp)
            self._data[fp] = data
            self._cid_of[fp] = cid
            self._by_cid.setdefault(cid, set()).add(fp)
            self._bytes += len(data)
            while self._bytes > self._cap:
                victim = next(iter(self._data))
                self._drop_locked(victim)
                _M.incr("chunk_cache_evict")
            _M.gauge("chunk_cache_bytes", self._bytes)

    def _drop_locked(self, fp: bytes) -> None:
        data = self._data.pop(fp, None)
        if data is None:
            return
        self._bytes -= len(data)
        cid = self._cid_of.pop(fp)
        peers = self._by_cid.get(cid)
        if peers is not None:
            peers.discard(fp)
            if not peers:
                del self._by_cid[cid]

    def invalidate_container(self, cid: int) -> int:
        """Drop every cached chunk sliced from ``cid`` — wired to the
        store's quarantine/delete retirement hook so a scrub-condemned or
        compacted-away container can never serve another chunk from this
        cache.  Returns entries dropped."""
        with self._lock:
            fps = list(self._by_cid.get(cid, ()))
            for fp in fps:
                self._drop_locked(fp)
            if fps:
                _M.incr("chunk_cache_invalidated", len(fps))
                _M.gauge("chunk_cache_bytes", self._bytes)
        return len(fps)


# ---------------------------------------------------------- read coalescer


class _Req:
    __slots__ = ("cids", "future", "timeline", "tenant")

    def __init__(self, cids: list, future: Future, timeline,
                 tenant: str | None = None) -> None:
        self.cids = cids
        self.future = future
        self.timeline = timeline
        self.tenant = tenant


class ReadCoalescer:
    """Bounded batching of container-decode misses (write_pipeline.py's
    coalescer + group-commit window, applied to reads): concurrent
    readers' misses that land within one ``read_batch_window_ms`` window
    decode through ONE grouped ``block_decompress_batch`` dispatch, each
    distinct container once.  Admission is bounded by the
    ``read_max_inflight`` semaphore (the same bounded-slots discipline as
    pipeline_max_inflight).  ``batched=False`` (depth 1 / non-TPU backend)
    decodes inline on the caller's thread."""

    def __init__(self, containers, window_ms: float = 2.0,
                 max_inflight: int = 16, depth: int = 8,
                 backend: str = "native", batched: bool | None = None,
                 qos_ctrl=None):
        self._containers = containers
        self._window_s = max(window_ms, 0.0) / 1000.0
        self._depth = max(depth, 1)
        self._backend = backend
        self._qos = qos_ctrl
        self._sem = threading.BoundedSemaphore(max(max_inflight, 1))
        # weighted-fair dequeue across tenants (utils/qos.py FairQueue) —
        # a flooding tenant's queued decode groups cannot starve a light
        # tenant's (the coalescer window still batches across lanes)
        self._q = qos.FairQueue()
        self._thread: threading.Thread | None = None
        if batched is None:
            batched = backend == "tpu" and window_ms > 0 and max_inflight > 1
        if batched:
            self._thread = threading.Thread(target=self._loop,
                                            name="read-plane", daemon=True)
            self._thread.start()

    def _decomp(self, codec_names, blobs, usizes):
        return dispatch.block_decompress_batch(codec_names, blobs, usizes,
                                               self._backend)

    def fetch(self, cids: list, timeline=None,
              tenant: str | None = None) -> dict:
        """Decoded payloads for ``cids`` (cid -> bytes).  Blocks at the
        admission bound; in batched mode the call parks on the group's
        future while the worker decodes under the lead member's timeline.
        Sheds (qos.ShedError) BEFORE acquiring a permit when the ambient
        tenant is over rate or the deadline cannot cover the estimate."""
        if tenant is None:
            tenant = qos.current_tenant()
        # unattributed callers (scrub, EC reconstruction, compaction) are
        # internal housekeeping — never shed them, only client traffic
        if self._qos is not None and tenant is not None:
            self._qos.admit(tenant, "read")
        if not self._sem.acquire(timeout=300):
            raise TimeoutError("read plane admission timeout")
        try:
            if self._thread is None:
                _M.incr("inline_decodes")
                with profiler.phase("container_decode"):
                    return self._containers.read_containers(
                        cids, decompress_batch=self._decomp)
            fut: Future = Future()
            self._q.put(_Req(list(cids), fut,
                             timeline or profiler.current_timeline(),
                             tenant))
            with profiler.phase("decode_wait"):
                return fut.result(timeout=300)
        finally:
            self._sem.release()

    def close(self) -> None:
        if self._thread is not None:
            self._q.put(None)
            self._thread.join()
            self._thread = None

    def _loop(self) -> None:
        while True:
            req = self._q.get()
            if req is None:
                return
            group = [req]
            deadline = time.monotonic() + self._window_s
            stopping = False
            while len(group) < self._depth:
                remain = deadline - time.monotonic()
                if remain <= 0:
                    break
                try:
                    nxt = self._q.get(timeout=remain)
                except queue.Empty:
                    break
                if nxt is None:
                    stopping = True
                    break
                group.append(nxt)
            self._serve(group)
            if stopping:
                return

    def _serve(self, group: list) -> None:
        cids = list(dict.fromkeys(c for r in group for c in r.cids))
        lead = group[0].timeline
        t0 = profiler.mark()
        try:
            # the lead reader's timeline is ambient for the real decode
            # spans; the shared window is mirrored to the rest below — the
            # same attribution contract as write_pipeline's device batches
            with profiler.bind_timeline(lead), \
                    profiler.phase("container_decode"):
                datas = self._containers.read_containers(
                    cids, decompress_batch=self._decomp)
        except BaseException as e:  # noqa: BLE001 — readers unwrap
            for r in group:
                if not r.future.done():
                    r.future.set_exception(e)
            return
        t1 = profiler.mark()
        _M.incr("read_batches")
        _M.observe("read_batch_containers", len(cids))
        if len(group) > 1:
            _M.incr("coalesced_reads", len(group))
        for i, r in enumerate(group):
            if r.timeline is not None and i > 0:
                r.timeline.add_span("container_decode", t0, t1, 0)
            r.future.set_result({c: datas[c] for c in r.cids})


# ------------------------------------------------------------- the facade


class ReadPlane:
    """The DN's chunk-granular serving engine: plan → cache → coalescer.

    ``fetch_chunks(plan)`` probes the decoded-chunk cache per fingerprint,
    groups the misses by container, decodes those containers through the
    coalescer (once each, batched across concurrent readers), slices the
    missed chunks out and back-fills the cache.  Per-plan decode fan-out is
    exported as ``containers_decoded_per_read`` — the acceptance gauge that
    a range read touches exactly the containers overlapping its range."""

    def __init__(self, containers, chunk_cache_mb: float = 8,
                 window_ms: float = 2.0, max_inflight: int = 16,
                 backend: str = "native", batched: bool | None = None,
                 qos_ctrl=None):
        self.cache = ChunkCache(int(chunk_cache_mb * (1 << 20)))
        self.coalescer = ReadCoalescer(containers, window_ms=window_ms,
                                       max_inflight=max_inflight,
                                       backend=backend, batched=batched,
                                       qos_ctrl=qos_ctrl)
        self._containers = containers

    def attach_store(self, containers) -> None:
        """Install the cache-invalidation hook on the store (quarantine or
        delete retires a container → its cached chunks drop)."""
        containers._on_retire = self.cache.invalidate_container

    def fetch_chunks(self, plan: ChunkPlan) -> list:
        """Decoded chunk bytes, one per ``plan.wanted`` entry."""
        out: list = [None] * len(plan.wanted)
        misses: list[int] = []
        with profiler.phase("cache_probe"):
            for i, fp in enumerate(plan.hashes):
                data = self.cache.get(fp)
                if data is not None:
                    out[i] = data
                else:
                    misses.append(i)
        decoded = 0
        if misses:
            need: dict[int, list[int]] = {}
            for i in misses:
                need.setdefault(plan.wanted[i][0], []).append(i)
            datas = self.coalescer.fetch(list(need))
            decoded = len(need)
            for cid, idxs in need.items():
                payload = datas[cid]
                for i in idxs:
                    _, off, ln = plan.wanted[i]
                    chunk = payload[off:off + ln]
                    out[i] = chunk
                    self.cache.put(plan.hashes[i], chunk, cid)
        _M.incr("plans_served")
        _M.incr("containers_fetched", decoded)
        _M.observe("containers_decoded_per_read", decoded)
        return out

    def close(self) -> None:
        self.coalescer.close()
