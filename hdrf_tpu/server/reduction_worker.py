"""Co-located TPU reduction worker: a separate process owning the device.

The north-star deployment (BASELINE.json; SURVEY.md §2.4 "bulk transport"):
*"BlockReceiver streams 128 MB block packets over gRPC to a co-located TPU
worker; bytes land in HBM."*  This daemon is that worker — the TPU-side
equivalent of the reference's in-process JNI boundary (DataXceiver ->
libnayuki/codecs), lifted into its own process so the DataNode host stays
device-free:

- **Streaming ingest**: the DataNode forwards block packets AS RECEIVED
  over the owned framed protocol (same packet framing as DN<->DN transfer);
  the worker stages them to HBM in stride-sized device uploads while later
  packets are still arriving, then assembles the resident block
  device-side — bytes land in HBM before the stream even finishes.
- **Compute**: CDC candidate scan + bucketed SHA-256 via
  ops.resident.ResidentReducer on the resident image; LZ4 match discovery
  via ops.lz4_tpu.  Only cuts/digests/compressed bytes return to the DN —
  O(chunks), not O(block).
- **Completion**: the DN's admission slot is held across the round trip
  and released when the response lands (the DDRunner completion-callback
  role, DDRunner.java:37-53, with real backpressure instead of ticket
  arithmetic).

Run standalone: ``python -m hdrf_tpu.server.reduction_worker --port 0``.
"""

from __future__ import annotations

import socket
import socketserver
import threading
import time
from typing import Any

import numpy as np

from hdrf_tpu.config import CdcConfig
from hdrf_tpu.proto import datatransfer as dt
from hdrf_tpu.proto.rpc import recv_frame, send_frame
from hdrf_tpu.reduction import accounting
from hdrf_tpu.utils import metrics, profiler, retry, tracing

_M = metrics.registry("reduction_worker")
_TR = tracing.tracer("reduction_worker")

# Device upload stride for streaming ingest: big enough to amortize the
# per-transfer cost, small enough that HBM staging overlaps the tail of
# the network stream.
_STRIDE = 4 << 20


class ReductionWorker:
    """The worker daemon.  Thread-per-connection like the DN xceiver; the
    device work itself is serialized by JAX's stream, so concurrent jobs
    interleave at dispatch granularity."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 backend: str = "auto"):
        from hdrf_tpu.ops import dispatch as ops_dispatch

        self.backend = ops_dispatch.resolve_backend(backend)
        self._reducers: dict[tuple, Any] = {}
        self._lz4 = None
        self._stats_lock = threading.Lock()
        self._stats = {"blocks_reduced": 0, "bytes_reduced": 0,
                       "compress_jobs": 0}
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:
                sock = self.request
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                try:
                    while True:
                        req = recv_frame(sock)
                        outer._dispatch(sock, req)
                except (ConnectionError, OSError):
                    return

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = Server((host, port), Handler)
        self._thread: threading.Thread | None = None
        from hdrf_tpu.utils.watchdog import StallWatchdog

        self.watchdog = StallWatchdog("reduction_worker", registry=_M)

    @property
    def addr(self) -> tuple[str, int]:
        return self._server.server_address

    def start(self) -> "ReductionWorker":
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="reduction-worker", daemon=True)
        self._thread.start()
        self.watchdog.start()
        return self

    def stop(self) -> None:
        self.watchdog.stop()
        self._server.shutdown()
        self._server.server_close()

    # ------------------------------------------------------------- dispatch

    def _dispatch(self, sock: socket.socket, req: dict) -> None:
        op = req.get("op")
        # Resume the DN-side span carried in the request frame (the op-header
        # continueTraceSpan pattern, Receiver.java:94-98, extended across the
        # DN->worker process boundary) — only around compute ops so ping /
        # stats / trace polls never pollute the span sink.
        trace = req.get("_trace")
        try:
            if op in ("reduce", "compress", "compress_batch"):
                # Rebind the DN's remaining deadline budget (hop-by-hop,
                # same transport slot as _trace) so worker-side sub-calls
                # inherit what's left of the end-to-end budget.
                with retry.bind_remaining(req.get(retry.DEADLINE_KEY)), \
                        self.watchdog.track(f"worker.{op}"), \
                        _TR.span(f"worker.{op}",
                                 parent=tuple(trace) if trace else None) as sp:
                    sp.annotate("backend", self.backend)
                    if op == "reduce":
                        self._op_reduce(sock, req)
                    elif op == "compress":
                        self._op_compress(sock, req)
                    else:
                        self._op_compress_batch(sock, req)
            elif op == "ping":
                send_frame(sock, {"ok": True, "backend": self.backend})
            elif op == "stats":
                with self._stats_lock:
                    send_frame(sock, dict(self._stats))
            elif op == "traces":
                from hdrf_tpu.utils import device_ledger

                send_frame(sock, {
                    "daemon": "reduction_worker",
                    "spans": tracing.all_span_snapshots(),
                    "ledger": device_ledger.events_snapshot(),
                    "counters": profiler.counters_snapshot()})
            else:
                send_frame(sock, {"error": "NoSuchOp", "message": str(op)})
        except (ConnectionError, OSError):
            raise
        except Exception as e:  # noqa: BLE001 — errors cross the wire
            _M.incr("op_errors")
            send_frame(sock, {"error": type(e).__name__, "message": str(e)})

    def _reducer(self, cdc: CdcConfig):
        key = (cdc.mask_bits, cdc.min_chunk, cdc.max_chunk)
        r = self._reducers.get(key)
        if r is None:
            from hdrf_tpu.ops.resident import ResidentReducer

            r = self._reducers[key] = ResidentReducer(cdc)
        return r

    def _op_reduce(self, sock: socket.socket, req: dict) -> None:
        """Packet stream -> (cuts, digests).  TPU backend: packets stage to
        HBM in _STRIDE device uploads DURING the stream; the resident block
        is assembled device-side."""
        cdc = CdcConfig(mask_bits=req["mask_bits"],
                        min_chunk=req["min_chunk"],
                        max_chunk=req["max_chunk"])
        if self.backend == "tpu":
            cuts, digs = self._reduce_streaming_tpu(sock, cdc)
        else:
            from hdrf_tpu.ops import dispatch as ops_dispatch

            data = dt.collect_packets(sock)
            buf = np.frombuffer(data, dtype=np.uint8)
            cuts, digs = ops_dispatch.chunk_and_fingerprint(
                buf, cdc, self.backend)
        nbytes = int(cuts[-1]) if len(cuts) else 0
        with self._stats_lock:
            self._stats["blocks_reduced"] += 1
            self._stats["bytes_reduced"] += nbytes
        send_frame(sock, {"cuts": np.asarray(cuts, np.int64).tobytes(),
                          "digests": np.ascontiguousarray(digs).tobytes()})
        _M.incr("blocks_reduced")
        accounting.record_worker_bytes("reduce", nbytes)

    def _reduce_streaming_tpu(self, sock: socket.socket, cdc: CdcConfig):
        import jax
        import jax.numpy as jnp

        parts: list = []        # resident device strides (uploads in flight)
        pend: list[bytes] = []  # current stride accumulator
        pend_n = 0
        total = 0
        for _seq, data, _last in dt.iter_packets(sock):
            if data:
                pend.append(data)
                pend_n += len(data)
                total += len(data)
                if pend_n >= _STRIDE:
                    blob = np.frombuffer(b"".join(pend), np.uint8)
                    parts.append(jax.device_put(blob))  # async H2D: lands
                    # in HBM while the next packets stream in
                    pend, pend_n = [], 0
        if pend:
            parts.append(jax.device_put(
                np.frombuffer(b"".join(pend), np.uint8)))
        if not parts:
            return np.empty(0, np.int64), np.empty((0, 32), np.uint8)
        from hdrf_tpu.ops.resident import _PAD_GRID

        pad = (-total) % _PAD_GRID
        if pad:
            parts.append(jnp.zeros(pad, jnp.uint8))
        block = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
        r = self._reducer(cdc)
        job = r.submit(block, n=total)
        r.start_sha(job)
        return r.finish(job)

    def _op_compress(self, sock: socket.socket, req: dict) -> None:
        from hdrf_tpu.ops import dispatch as ops_dispatch

        data = dt.collect_packets(sock)
        out = ops_dispatch.block_compress(req.get("codec", "lz4"), data,
                                          self.backend)
        with self._stats_lock:
            self._stats["compress_jobs"] += 1
        send_frame(sock, {"data": bytes(out)})
        _M.incr("compress_jobs")
        accounting.record_worker_bytes("compress", len(data))

    def _op_compress_batch(self, sock: socket.socket, req: dict) -> None:
        """N payloads in one round trip (a DN sealing several container
        lanes at once): req["sizes"] splits the single concatenated packet
        stream.  On the TPU backend equal-size payloads compress as ONE
        device program with one grouped readback (block_compress_batch) —
        without this op each lane pays its own dispatch + readback round
        trip through the transport."""
        from hdrf_tpu.ops import dispatch as ops_dispatch

        sizes = [int(v) for v in req.get("sizes", [])]
        blob = dt.collect_packets(sock)
        if sum(sizes) != len(blob):
            send_frame(sock, {"error": "ValueError",
                              "message": f"sizes sum {sum(sizes)} != "
                                         f"stream length {len(blob)}"})
            return
        datas, off = [], 0
        for n in sizes:
            datas.append(blob[off:off + n])
            off += n
        outs = ops_dispatch.block_compress_batch(
            req.get("codec", "lz4"), datas, self.backend)
        with self._stats_lock:
            self._stats["compress_jobs"] += len(sizes)
        send_frame(sock, {"datas": [bytes(o) for o in outs]})
        _M.incr("compress_jobs", len(sizes))
        accounting.record_worker_bytes("compress", len(blob))


# ------------------------------------------------------------------ client


class WorkerError(IOError):
    """Worker-side failure (connect/protocol/compute).  DISTINCT from the
    caller's own stream errors: a DN forwarding client packets must treat a
    dead worker as 'fall back to in-process compute' but a dead CLIENT as a
    failed write — conflating them would commit truncated blocks."""


class WorkerClient:
    """DN-side handle on the co-located worker.  One pooled connection per
    concurrent job (connections are cheap on loopback; the pool bound comes
    from the DN's admission slots holding across the round trip).

    Resilience contract (utils/retry.py): every data-path op runs under a
    payload-scaled deadline budget — ``deadline_s`` base plus
    ``deadline_s_per_mb`` accrued per streamed MiB, clamped by any ambient
    end-to-end deadline — so a HUNG worker costs at most the remaining
    budget, not the reference's fixed 600 s socket timeout.  When a
    ``breaker`` (retry.CircuitBreaker) is attached, data-path ops check it
    BEFORE connecting: a DEAD worker costs zero connect attempts while the
    breaker is open, and the half-open probe re-admits the edge when the
    worker returns.  Worker-side failures record breaker outcomes; errors
    from the caller's own packet iterator never touch the breaker (they
    are not evidence about the worker).  ping/stats/traces stay outside
    the breaker so observability polls never consume the half-open probe.
    """

    def __init__(self, addr, timeout: float = 600.0,
                 deadline_s: float | None = None,
                 deadline_s_per_mb: float = 0.0,
                 breaker: "retry.CircuitBreaker | None" = None):
        self._addr = (addr[0], int(addr[1]))
        self._timeout = timeout if deadline_s is None else deadline_s
        self._per_mb = float(deadline_s_per_mb)
        self._breaker = breaker
        self._pool: list[socket.socket] = []
        self._lock = threading.Lock()

    def set_addr(self, addr) -> None:
        """Repoint at a respawned worker (it lands on a fresh ephemeral
        port); pooled connections to the old incarnation are dropped."""
        with self._lock:
            self._addr = (addr[0], int(addr[1]))
            for s in self._pool:
                s.close()
            self._pool.clear()

    def _deadline(self, nbytes: int = 0) -> retry.Deadline:
        budget = self._timeout + self._per_mb * (nbytes / float(1 << 20))
        return retry.Deadline(retry.effective_budget(budget))

    def _conn(self, dl: retry.Deadline,
              gated: bool = True) -> socket.socket:
        if gated and self._breaker is not None \
                and not self._breaker.allow():
            e = WorkerError(
                f"worker breaker '{self._breaker.name}' open: "
                "skipping connect")
            e.breaker_open = True  # not evidence of a NEW failure
            raise e
        with self._lock:
            if self._pool:
                s = self._pool.pop()
                s.settimeout(dl.timeout())
                return s
        try:
            _M.incr("connect_attempts")
            s = socket.create_connection(self._addr, timeout=dl.timeout())
        except OSError as e:
            err = WorkerError(f"worker unreachable: {e}")
            if gated:
                # connect refusal is the clearest dead-worker evidence, and
                # it raises BEFORE the callers' try/except-_fail blocks —
                # record it here (ungated observability polls stay outside)
                self._fail(err)
            raise err from e
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return s

    def _release(self, s: socket.socket) -> None:
        with self._lock:
            if len(self._pool) < 8:
                self._pool.append(s)
                return
        s.close()

    def _ok(self) -> None:
        if self._breaker is not None:
            self._breaker.record_success()

    def _fail(self, e: BaseException) -> None:
        if self._breaker is not None \
                and not getattr(e, "breaker_open", False):
            self._breaker.record_failure()

    def _checked(self, resp: dict) -> dict:
        if "error" in resp:
            raise WorkerError(
                f"worker: {resp['error']}: {resp['message']}")
        return resp

    def _stamped(self, req: dict,
                 dl: "retry.Deadline | None" = None) -> dict:
        """Stamp the caller's span context (and remaining deadline budget)
        into the request frame (same contract as dt.send_op headers /
        RpcClient.call), so the worker's span nests under the DN pipeline
        span that drove it and its sub-calls inherit the budget."""
        tr = tracing.current_context()
        if tr is not None:
            req["_trace"] = list(tr)
        hdr = dl.header() if dl is not None else retry.remaining_header()
        if hdr is not None:
            req[retry.DEADLINE_KEY] = hdr
        return req

    def reduce_stream(self, packets, cdc: CdcConfig):
        """Forward an iterator of byte packets; returns (cuts, digests).
        This is the true streaming path: the DN calls it from inside its
        packet-receive loop, so client->DN->worker->HBM is one pipeline.
        The deadline budget accrues ``deadline_s_per_mb`` per streamed MiB
        (payload size is only known as it arrives).

        Exception taxonomy: worker-side failures raise :class:`WorkerError`;
        anything the ``packets`` iterator itself raises (the caller's OWN
        stream — e.g. the DN's client connection dying) propagates
        unchanged, so the caller can tell the two apart."""
        dl = self._deadline()
        s = self._conn(dl)
        try:
            try:
                send_frame(s, self._stamped(
                    {"op": "reduce", "mask_bits": cdc.mask_bits,
                     "min_chunk": cdc.min_chunk,
                     "max_chunk": cdc.max_chunk}, dl))
            except OSError as e:
                raise WorkerError(f"worker send failed: {e}") from e
            seq = 0
            it = iter(packets)
            while True:
                try:
                    data = next(it)  # caller errors propagate UNWRAPPED
                except StopIteration:
                    break
                if not data:
                    continue
                try:
                    dl.extend(self._per_mb * len(data) / float(1 << 20))
                    dl.check("worker reduce stream")
                    s.settimeout(dl.timeout())
                    dt.write_packet(s, seq, data)
                except OSError as e:
                    raise WorkerError(f"worker send failed: {e}") from e
                seq += 1
            try:
                dl.check("worker reduce")
                s.settimeout(dl.timeout())
                # the final drain IS the wait on device compute: the worker
                # answers only after its TPU reduce completes, so the DN-side
                # timeline books it as device_wait (its own ledger records
                # nothing — the dispatches live in the worker process)
                with profiler.phase("device_wait"):
                    dt.write_packet(s, seq, b"", last=True)
                    resp = self._checked(recv_frame(s))
            except (OSError, ConnectionError) as e:
                raise WorkerError(f"worker failed: {e}") from e
            cuts = np.frombuffer(resp["cuts"], np.int64)
            digs = np.frombuffer(resp["digests"],
                                 np.uint8).reshape(-1, 32)
            self._release(s)
            self._ok()
            return cuts, digs
        except BaseException as e:
            s.close()
            if isinstance(e, (WorkerError, retry.DeadlineExceeded)):
                self._fail(e)
            raise

    def reduce(self, data: bytes, cdc: CdcConfig):
        return self.reduce_stream([data], cdc)

    def compress(self, codec: str, data: bytes) -> bytes:
        dl = self._deadline(len(data))
        s = self._conn(dl)
        try:
            try:
                send_frame(s, self._stamped({"op": "compress",
                                             "codec": codec}, dl))
                dt.stream_bytes(s, data, 1 << 20)
                dl.check("worker compress")
                s.settimeout(dl.timeout())
                out = bytes(self._checked(recv_frame(s))["data"])
            except (OSError, ConnectionError) as e:
                raise WorkerError(f"worker failed: {e}") from e
            self._release(s)
            self._ok()
            return out
        except BaseException as e:
            s.close()
            if isinstance(e, (WorkerError, retry.DeadlineExceeded)):
                self._fail(e)
            raise

    def compress_batch(self, codec: str, datas: list) -> list:
        """Batched compress: one round trip, one worker-side device program
        for the group (see ReductionWorker._op_compress_batch)."""
        dl = self._deadline(sum(len(d) for d in datas))
        s = self._conn(dl)
        try:
            try:
                send_frame(s, self._stamped(
                    {"op": "compress_batch", "codec": codec,
                     "sizes": [len(d) for d in datas]}, dl))
                seq = 0
                for d in datas:
                    if d:
                        dt.write_packet(s, seq, d)
                        seq += 1
                dt.write_packet(s, seq, b"", last=True)
                dl.check("worker compress_batch")
                s.settimeout(dl.timeout())
                outs = [bytes(v)
                        for v in self._checked(recv_frame(s))["datas"]]
            except (OSError, ConnectionError) as e:
                raise WorkerError(f"worker failed: {e}") from e
            self._release(s)
            self._ok()
            return outs
        except BaseException as e:
            s.close()
            if isinstance(e, (WorkerError, retry.DeadlineExceeded)):
                self._fail(e)
            raise

    def ping(self) -> dict:
        s = self._conn(self._deadline(), gated=False)
        try:
            send_frame(s, {"op": "ping"})
            out = self._checked(recv_frame(s))
            self._release(s)
            return out
        except BaseException:
            s.close()
            raise

    def stats(self) -> dict:
        s = self._conn(self._deadline(), gated=False)
        try:
            send_frame(s, {"op": "stats"})
            out = self._checked(recv_frame(s))
            self._release(s)
            return out
        except BaseException:
            s.close()
            raise

    def traces(self) -> dict:
        """Worker-process spans + device-ledger events (the DN proxies this
        through its own trace_spans op for the gateway merge)."""
        s = self._conn(self._deadline(), gated=False)
        try:
            send_frame(s, {"op": "traces"})
            out = self._checked(recv_frame(s))
            self._release(s)
            return out
        except BaseException:
            s.close()
            raise

    def close(self) -> None:
        with self._lock:
            for s in self._pool:
                s.close()
            self._pool.clear()


def spawn_local_worker(backend: str = "auto"):
    """Launch a worker as a real SEPARATE PROCESS (the co-located
    deployment shape); returns (Popen, (host, port)).  The caller owns the
    process (terminate() when done)."""
    import re
    import subprocess
    import sys

    proc = subprocess.Popen(
        [sys.executable, "-m", "hdrf_tpu.server.reduction_worker",
         "--port", "0", "--backend", backend],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
    line = proc.stdout.readline()
    m = re.search(r"listening on ([\d.]+):(\d+)", line)
    if not m:
        proc.terminate()
        raise RuntimeError(f"worker failed to start: {line!r}")
    return proc, (m.group(1), int(m.group(2)))


class WorkerSupervisor:
    """Supervised co-located worker: owns the process, detects death, and
    respawns with capped full-jitter backoff (the NodeManager service-
    restart role the reference delegates to init systems; DataNode.java has
    no analog for its in-process codecs — they die with the daemon).

    ``on_respawn(addr)`` fires after each successful respawn so the owner
    repoints its :class:`WorkerClient` (`set_addr`) — respawned workers
    land on a fresh ephemeral port.  Clock/sleep/spawn are injectable so
    tests drive the respawn schedule without wall-clock waits.  A process
    that stayed up longer than ``healthy_s`` resets the backoff streak.
    """

    def __init__(self, backend: str = "auto", base_s: float = 0.5,
                 cap_s: float = 15.0, healthy_s: float = 30.0,
                 on_respawn=None, clock=time.monotonic,
                 sleep=time.sleep, spawn=spawn_local_worker,
                 poll_s: float = 0.2):
        self._backend = backend
        self._base_s = float(base_s)
        self._cap_s = float(cap_s)
        self._healthy_s = float(healthy_s)
        self._on_respawn = on_respawn
        self._clock = clock
        self._sleep = sleep
        self._spawn = spawn
        self._poll_s = float(poll_s)
        self._proc = None
        self.addr: tuple[str, int] | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._spawned_at = 0.0
        self._streak = 0  # consecutive quick deaths
        self.respawns = 0

    def start(self) -> tuple[str, int]:
        """Spawn the first incarnation and the monitor thread; returns the
        worker address (startup failures propagate to the caller — only
        RE-spawns are retried with backoff)."""
        self._proc, self.addr = self._spawn(self._backend)
        self._spawned_at = self._clock()
        self._thread = threading.Thread(target=self._monitor,
                                        name="worker-supervisor",
                                        daemon=True)
        self._thread.start()
        return self.addr

    def _monitor(self) -> None:
        import random as _random

        while not self._stop.is_set():
            if self._proc.poll() is None:
                self._sleep(self._poll_s)
                continue
            if self._stop.is_set():
                return
            if self._clock() - self._spawned_at >= self._healthy_s:
                self._streak = 0
            delay = _random.uniform(0.0, min(
                self._cap_s, self._base_s * (2.0 ** self._streak)))
            self._streak += 1
            _M.incr("worker_deaths")
            if delay > 0:
                self._sleep(delay)
            if self._stop.is_set():
                return
            try:
                self._proc, self.addr = self._spawn(self._backend)
            except Exception:
                _M.incr("worker_respawn_failures")
                continue  # next lap backs off further
            self._spawned_at = self._clock()
            self.respawns += 1
            _M.incr("worker_respawns")
            if self._on_respawn is not None:
                try:
                    self._on_respawn(self.addr)
                except Exception:
                    _M.incr("worker_respawn_callback_errors")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self._proc is not None and self._proc.poll() is None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout=5)
            except Exception:
                self._proc.kill()


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="hdrf-reduction-worker")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--backend", default="auto")
    args = p.parse_args(argv)
    w = ReductionWorker(args.host, args.port, backend=args.backend).start()
    # Startup banner goes to STDOUT (spawn_local_worker regex-parses the
    # "listening on host:port" substring off the first line — present in
    # both the text and JSON log formats).
    import sys

    from hdrf_tpu.utils import log

    log.get_logger("reduction_worker", stream=sys.stdout).info(
        f"reduction worker ({w.backend}) listening on "
        f"{w.addr[0]}:{w.addr[1]}", backend=w.backend)
    try:
        while True:
            import time

            time.sleep(3600)
    except KeyboardInterrupt:
        w.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
