"""Per-daemon status HTTP server: /prom, /metrics, /traces, /stacks.

Equivalent of the reference's per-daemon HttpServer2 servlet set (every
Hadoop daemon serves /jmx, /metrics, /stacks and /conf on its info port;
DataNode.java:499 wires it at startup): a tiny threaded HTTP server each daemon
opts into via ``status_port`` config, serving

- ``/prom``    — Prometheus text exposition over this process's registries
  (utils/prom.py; the PrometheusMetricsSink analog),
- ``/metrics`` — raw JSON registry snapshots (the /jmx analog),
- ``/traces``  — this process's finished spans + device-ledger events +
  profiler counter-track samples (raw JSON; ``?format=chrome`` renders
  Perfetto JSON with counter tracks; the gateway's /traces merges these
  across daemons),
- ``/stacks``  — live thread stacks plus the watchdog's recent stall
  captures (the HttpServer2 StackServlet analog),
- ``/timeseries`` — the daemon's flight-recorder ring (bounded over-time
  gauge samples, utils/flight_recorder.py; nothing in the reference
  serves a curve — MutableRollingAverages keeps a few windowed means and
  discards the series),
- ``/contention`` — the daemon's lock/RPC contention table (per-method
  calls/p99/lock-share + the instrumented namesystem lock's books,
  utils/lockprof.py; the FSNamesystemLock.java:60 metrics plus the RPC
  decomposition RpcMetrics.java:118 never had, served nowhere in the
  reference).

The server threads are daemonic and shut down with the owning daemon.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from hdrf_tpu.utils import device_ledger, metrics, profiler, prom, tracing
from hdrf_tpu.utils.watchdog import StallWatchdog, thread_stacks


class StatusHttpServer:
    def __init__(self, name: str, host: str = "127.0.0.1", port: int = 0,
                 watchdog: StallWatchdog | None = None,
                 recorder=None, contention=None):
        """``recorder``: optional utils.flight_recorder.FlightRecorder —
        when set, ``/timeseries`` serves its bounded gauge ring.
        ``contention``: optional zero-arg callable returning the daemon's
        contention table (the NN passes rpc_contention, ISSUE 18) —
        when set, ``/contention`` serves it."""
        self.name = name
        self._watchdog = watchdog
        self._recorder = recorder
        self._contention = contention
        status = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                u = urlparse(self.path)
                q = {k: v[0] for k, v in parse_qs(u.query).items()}
                if u.path == "/prom":
                    body = prom.render(metrics.all_snapshots()).encode()
                    return self._send(200, body,
                                      "text/plain; version=0.0.4")
                if u.path == "/metrics":
                    return self._send(
                        200, json.dumps(metrics.all_snapshots()).encode(),
                        "application/json")
                if u.path == "/traces":
                    out = status.traces(trace_id=q.get("trace_id"))
                    if q.get("format") == "chrome":
                        out = tracing.chrome_trace(
                            out["spans"], out["ledger"],
                            trace_id=q.get("trace_id"),
                            counters=out.get("counters", []))
                    return self._send(200, json.dumps(out).encode(),
                                      "application/json")
                if u.path == "/stacks":
                    return self._send(200,
                                      json.dumps(status.stacks()).encode(),
                                      "application/json")
                if u.path == "/timeseries":
                    out = status.timeseries(metric=q.get("metric"),
                                            since=q.get("since"))
                    return self._send(200, json.dumps(out).encode(),
                                      "application/json")
                if u.path == "/contention":
                    return self._send(
                        200, json.dumps(status.contention()).encode(),
                        "application/json")
                self._send(404, b'{"error": "not found"}',
                           "application/json")

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name=f"status-http-{name}", daemon=True)

    @property
    def addr(self) -> tuple[str, int]:
        return self._server.server_address

    def start(self) -> "StatusHttpServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    def traces(self, trace_id: str | None = None) -> dict:
        spans = tracing.all_span_snapshots()
        ledger = device_ledger.events_snapshot()
        counters = profiler.counters_snapshot()
        if trace_id is not None:
            spans = [s for s in spans if s["trace_id"] == trace_id]
            ledger = [e for e in ledger if e.get("trace_id") == trace_id]
            counters = []  # counter samples have no trace affinity
        return {"daemon": self.name, "spans": spans, "ledger": ledger,
                "counters": counters}

    def timeseries(self, metric: str | None = None,
                   since=None) -> dict:
        """The flight recorder's ring (utils/flight_recorder.py), or an
        empty shell when the daemon runs without a recorder — the endpoint
        shape stays stable either way.  ``?metric=`` (comma-separated
        gauge names) and ``?since=`` (wall seconds) project the ring down
        (utils/flight_archive.py filter_series) so pollers stop paying
        for the full dump."""
        if self._recorder is None:
            return {"daemon": self.name, "interval_s": 0.0, "capacity": 0,
                    "samples": []}
        out = self._recorder.snapshot()
        if metric or since is not None:
            from hdrf_tpu.utils import flight_archive

            out["samples"] = flight_archive.filter_series(
                out["samples"], metric=metric,
                since=float(since) if since is not None else None)
        return out

    def contention(self) -> dict:
        """The daemon's lock/RPC contention table (utils/lockprof.py +
        proto/rpc.py contention_summary), or an empty shell for daemons
        that run without one — the endpoint shape stays stable."""
        if self._contention is None:
            return {"daemon": self.name, "methods": {}, "lock": None}
        out = dict(self._contention())
        out["daemon"] = self.name
        return out

    def stacks(self) -> dict:
        out = {"daemon": self.name, "threads": thread_stacks()}
        if self._watchdog is not None:
            out["stalls"] = self._watchdog.stalls()
            out["inflight"] = self._watchdog.inflight()
        return out
