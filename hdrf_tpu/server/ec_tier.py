"""DataNode half of the EC(6,3) cold tier: demotion, serving, repair.

Re-expresses the reference's DN-side erasure-coding worker stack —
ErasureCodingWorker.java:55 (reconstruction executor wired to NN
commands), StripedBlockReconstructor.java:41 (fan-in k shards, decode,
write back), StripedBlockReader.java:40 (per-shard fetch legs),
BlockECReconstructionCommand (DNA_ERASURE_CODING_RECONSTRUCTION) — on
top of the container abstraction: the striping unit is a **sealed
container file** (storage/stripe_store.py), not a raw block group, so
demotion multiplies the EC saving with the reduction ratio.  Three
roles live here:

- **Demote** (NN ``stripe_demote`` command): RS-encode every sealed
  container backing a cold block, push the k+m stripes to NN-chosen
  holders (peer ``stripe_write`` ops under the retry/deadline spine,
  utils/retry.py), WAL the manifest in the chunk index
  (index/chunk_index.py record_stripe — the commit point), then delete
  the local sealed file and report ``stripe_complete`` to the NN.
- **Degraded read** (ContainerStore ``_stripe_fallback`` hook): when a
  chunk gather misses the sealed file, gather any k surviving stripes —
  local disk first, then peers, skipping breaker-open edges (PR-5
  evidence) — and reassemble the exact sealed bytes, decoding through
  ops/rs.py only when a data stripe is lost.  With
  ``ec_read_hedge_delta`` > 0 the gather launches k primary legs PLUS
  δ hedged legs through utils/retry.py:1 ``hedged_quorum`` once the
  rolling per-holder p95 leg latency elapses, decoding from the first k
  to land — the k+δ speculative-fetch result of the straggler-coding
  line (arXiv 1802.03049; StripedBlockReader.java:40's serial legs are
  the tail it removes), with the old serial loop kept as the fallback
  when fewer than k legs can launch.  The reconstructed payload feeds
  the unchanged decompress + device chunk-gather path
  (ops/reconstruct.py), so reads stay bit-identical to the replicated
  tier.
- **Repair** (NN ``stripe_repair`` command): re-decode exactly the lost
  stripe indices from k survivors and push them to replacement holders,
  keeping the manifest's holder map current.  With ``ec_coded_repair``
  the gather runs as a partial-sum coded exchange
  (server/coded_exchange.py; ops/rs.py ``partial_sums``): one
  ``stripe_coded_read`` chained through the remote holders, each
  bit-matmuling its LOCAL stripes into a (|missing|, stripe_len)
  contribution and XOR-folding it into the response riding back — the
  repairing owner ingests ~|missing| stripes of bytes instead of k,
  CRC-verifies every rebuilt stripe against the manifest, and falls back
  to the classic full gather (which CRC-filters corrupt stripes as
  erasures) on any mismatch, old peer, or chain failure.  Repair and
  demote both run on the QoS control lane (utils/qos.py ``background()``)
  so background reconstruction can never shed a foreground tenant.
"""

from __future__ import annotations

import os
import time

import numpy as np

from hdrf_tpu import native
from hdrf_tpu.ops import rs
from hdrf_tpu.proto import datatransfer as dt
from hdrf_tpu.reduction import accounting
from hdrf_tpu.server import coded_exchange
from hdrf_tpu.storage import stripe_store
from hdrf_tpu.storage.container_store import _SEAL_HDR, _SEAL_MAGIC
from hdrf_tpu.utils import (fault_injection, metrics, profiler, qos, retry,
                            rollwin)

_M = metrics.registry("ec")

# budget for one whole demote/repair command (all stripe legs); each peer
# leg retries under it via the ambient-deadline discipline of utils/retry
_CMD_BUDGET_S = 60.0


class EcTier:
    """Owns the DN's stripe store + the three cold-tier roles above."""

    def __init__(self, dn) -> None:
        self._dn = dn
        self.store = stripe_store.StripeStore(
            os.path.join(dn.config.data_dir, "stripes"))
        # degraded-read hooks: a missing sealed file falls through to
        # reconstruction; has_container consults the manifest's payload size
        dn.containers._stripe_fallback = self.reconstruct_sealed
        dn.containers._stripe_probe = self.stripe_usize
        # chain the delete observer: a deleted striped container must drop
        # its local stripes + manifest too (remote stripes are reclaimed by
        # the NN's repair loop noticing the group vanished)
        prev_on_delete = dn.containers._on_delete

        def _on_delete(cid: int) -> None:
            if prev_on_delete is not None:
                prev_on_delete(cid)
            if dn.index.stripe_manifest(cid) is not None:
                self.store.delete_stripes(dn.dn_id, cid)
                dn.index.drop_stripe(cid)

        dn.containers._on_delete = _on_delete
        # rolling per-holder stripe-leg latency (seconds), the hedge
        # trigger's p95 input (the gather-side sibling of the DN's
        # _peer_win slow-peer windows)
        self._leg_win = rollwin.WindowMap(window_s=300.0, maxlen=64)

    # ------------------------------------------------------------ hooks

    def stripe_usize(self, cid: int) -> int | None:
        """Uncompressed payload size of a striped container (has_container's
        extent check), or None when the container is not striped."""
        m = self._dn.index.stripe_manifest(cid)
        return int(m["usize"]) if m is not None else None

    def reconstruct_sealed(self, cid: int) -> bytes | None:
        """ContainerStore fallback: reassemble the sealed FILE bytes of a
        demoted container from any k surviving stripes.  None = not striped
        or unrecoverable (the store then raises its original error)."""
        manifest = self._dn.index.stripe_manifest(cid)
        if manifest is None:
            return None
        _M.incr("stripe_gathers")
        got = self._gather(cid, manifest)
        k = int(manifest["k"])
        bad = {i for i, v in got.items()
               if int(native.crc32c(v)) != int(manifest["crcs"][i])}
        if bad:
            # a corrupt survivor is an erasure, not an input: re-gather
            # around it so the decode still sees k intact stripes
            _M.incr("repair_corrupt_survivors", len(bad))
            more = self._gather(cid, manifest, exclude=set(got))
            got = {i: v for i, v in got.items() if i not in bad}
            got.update(more)
        if len(got) < k:
            _M.incr("degraded_read_failures")
            return None
        # (a gather missing a data stripe decodes through parity — the
        # store's reconstruct_container counts that as a degraded read)
        try:
            blob = stripe_store.reconstruct_container(got, manifest)
        except (stripe_store.StripeCorrupt, ValueError):
            _M.incr("degraded_read_failures")
            return None
        assert isinstance(blob, bytes)
        return blob

    # ---------------------------------------------------------- serving

    def serve_read(self, sock, fields: dict) -> None:
        """Peer ``stripe_read``: hand one local stripe to a gatherer.
        A gatherer that sent ``accept_enc=1`` may get an LZ4 payload back
        (``enc=1`` + ``usize``) under coded_exchange's smaller-of
        negotiation; callers that never ask — old peers — always get raw
        bytes, so mixed versions stay byte-identical."""
        from hdrf_tpu.proto.rpc import send_frame

        fault_injection.point("stripe.read", dn_id=self._dn.dn_id)
        owner = fields["owner"]
        cid, idx = int(fields["cid"]), int(fields["idx"])
        try:
            data = self.store.read_stripe(owner, cid, idx)
        except FileNotFoundError:
            send_frame(sock, {"ok": False,
                              "error": f"no stripe {owner}/{cid}/{idx}"})
            return
        usize = len(data)
        enc = 0
        if int(fields.get("accept_enc", 0)) and self._dn.coded.compress_on:
            data, enc = coded_exchange.pack(data, self._dn.coded.backend)
        send_frame(sock, {"ok": True, "data": data, "enc": enc,
                          "usize": usize})

    def serve_write(self, sock, fields: dict) -> None:
        """Peer ``stripe_write``: durably store a stripe pushed by the
        demoting/repairing owner (CRC-checked before the ack).  ``enc=1``
        payloads are LZ4'd by the pusher's coded-exchange negotiation and
        decode to ``usize`` raw bytes BEFORE the CRC check, so the stored
        file and its CRC are identical to the raw path's."""
        from hdrf_tpu.proto.rpc import send_frame

        try:
            data = fields["data"]
            if int(fields.get("enc", 0)):
                data = coded_exchange.unpack(data, 1, int(fields["usize"]))
            self.store.put_stripe(fields["owner"], int(fields["cid"]),
                                  int(fields["idx"]), data,
                                  crc=fields.get("crc"))
        except (stripe_store.StripeCorrupt, ValueError, KeyError,
                RuntimeError, OSError) as e:
            send_frame(sock, {"ok": False,
                              "error": f"{type(e).__name__}: {e}"})
            return
        send_frame(sock, {"ok": True})

    def serve_coded_read(self, sock, fields: dict) -> None:
        """Peer ``stripe_coded_read``: one hop of a partial-sum repair
        chain.  Compute this DN's GF-combined contribution over its LOCAL
        survivor stripes (ops/rs.py ``partial_sums`` — one Cauchy
        bit-matmul, the coefficients ride in the plan), relay the rest of
        the plan to the next holder, XOR its returned partial sums into
        ours, and answer the fold — so the response traveling back to the
        repairing owner always carries exactly (|missing|, stripe_len)
        bytes no matter how many holders contributed.  Any hop failure
        answers ok=False and the owner falls back to the full gather."""
        from hdrf_tpu.proto.rpc import send_frame

        dn = self._dn
        fault_injection.point("stripe.coded_read", dn_id=dn.dn_id)
        try:
            owner = fields["owner"]
            cid = int(fields["cid"])
            stripe_len = int(fields["stripe_len"])
            nwant = int(fields["nwant"])
            accept_enc = int(fields.get("accept_enc", 0))
            plan = [list(e) for e in fields["plan"]]
            mine = next((e for e in plan if e[0] == dn.dn_id), None)
            rest = [e for e in plan if e[0] != dn.dn_id]
            with qos.background():
                parts = np.zeros((nwant, stripe_len), dtype=np.uint8)
                if mine is not None:
                    coeff_map = mine[3]
                    idxs = sorted(int(s) for s in coeff_map)
                    stripes = np.stack([np.frombuffer(
                        self.store.read_stripe(owner, cid, s),
                        dtype=np.uint8) for s in idxs])
                    coeffs = np.stack(
                        [np.asarray(coeff_map[str(s)], dtype=np.uint8)
                         for s in idxs], axis=1)
                    parts ^= rs.partial_sums(stripes, coeffs)
                if rest:
                    nxt = rest[0]
                    br = retry.breaker(f"{dn.dn_id}->{nxt[0]}")
                    try:
                        resp = dn.coded.send(
                            (nxt[1], int(nxt[2])), dt.STRIPE_CODED_READ,
                            nwant * stripe_len, owner=owner, cid=cid,
                            stripe_len=stripe_len, nwant=nwant, plan=rest,
                            accept_enc=accept_enc)
                        if not resp.get("ok"):
                            raise IOError(resp.get("error", "coded relay"))
                    except (OSError, ConnectionError, IOError, KeyError):
                        br.record_failure()
                        raise
                    br.record_success()
                    encs = resp.get("enc") or [0] * len(resp["parts"])
                    _M.incr("coded_relay_bytes",
                            sum(len(p) for p in resp["parts"]))
                    for i, (p, e) in enumerate(zip(resp["parts"], encs)):
                        parts[i] ^= np.frombuffer(
                            coded_exchange.unpack(p, e, stripe_len),
                            dtype=np.uint8)
                blobs = [parts[i].tobytes() for i in range(nwant)]
                if accept_enc and dn.coded.compress_on:
                    packed = coded_exchange.pack_many(blobs,
                                                      dn.coded.backend)
                else:
                    packed = [(b, 0) for b in blobs]
            send_frame(sock, {"ok": True,
                              "parts": [p for p, _ in packed],
                              "enc": [e for _, e in packed]})
        except (OSError, ConnectionError, IOError, KeyError, ValueError,
                RuntimeError, qos.ShedError) as e:
            send_frame(sock, {"ok": False,
                              "error": f"{type(e).__name__}: {e}"})

    # --------------------------------------------------------- demotion

    def demote(self, cmd: dict) -> None:
        """NN ``stripe_demote``: stripe every sealed, not-yet-striped
        container backing ``block_id`` onto ``targets``, then report.
        Ordering per container: stripes durable on holders -> manifest
        WAL'd -> sealed file deleted — a crash at any point leaves the
        container readable (sealed file until the WAL commit, stripes
        after)."""
        dn = self._dn
        bid = cmd["block_id"]
        k, m = int(cmd["k"]), int(cmd["m"])
        targets = [list(t) for t in cmd["targets"]]
        if len(targets) != k + m:
            _M.incr("demote_failures")
            return
        entry = dn.index.get_block(bid)
        if entry is None:
            return
        cids: list[int] = []
        for h in entry.hashes:
            loc = dn.index.chunk_location(h)
            if loc is not None and loc.container_id not in cids:
                cids.append(loc.container_id)
        done: list[dict] = []
        with retry.bind(retry.Deadline(_CMD_BUDGET_S)), qos.background():
            for cid in cids:
                if dn.index.stripe_manifest(cid) is not None:
                    continue  # already striped (shared container)
                blob = dn.containers.sealed_file_bytes(cid)
                if blob is None:
                    continue  # open/raw container: stays hot
                magic, usize, _codec = _SEAL_HDR.unpack(
                    blob[:_SEAL_HDR.size])
                if magic != _SEAL_MAGIC:
                    continue
                stripes, manifest = stripe_store.encode_container(blob, k, m)
                manifest.update(owner=dn.dn_id, usize=usize,
                                holders=targets)
                try:
                    for idx, data in enumerate(stripes):
                        self._place(targets[idx], cid, idx, data,
                                    manifest["crcs"][idx])
                except (OSError, ConnectionError, IOError,
                        retry.DeadlineExceeded):
                    _M.incr("demote_failures")
                    continue  # no manifest committed: sealed file stays
                dn.index.record_stripe(cid, manifest)
                freed = dn.containers.drop_sealed_file(cid)
                _M.incr("containers_demoted")
                _M.incr("demote_bytes_freed", freed)
                # the full manifest rides the report so the NN can journal
                # it (editlog/fsimage durable): owner-loss repair needs a
                # copy that survives this DN's WAL dying with this DN
                done.append({"cid": cid, "holders": targets,
                             "logical": manifest["length"],
                             "physical": (k + m) * manifest["stripe_len"],
                             "manifest": manifest})
        if done:
            self._notify_nn(bid, done)

    def repair(self, cmd: dict) -> None:
        """NN ``stripe_repair``: re-decode the lost stripe indices from k
        survivors and push them to replacement holders.  The manifest comes
        from this DN's WAL when it is the group's owner; after OWNER loss
        the NN deputizes a surviving holder and hands down its journaled
        manifest copy (``cmd["manifest"]``) — repaired stripes keep the
        original owner's name so every holder's files stay findable."""
        dn = self._dn
        fault_injection.point("stripe.repair", dn_id=dn.dn_id)
        cid = int(cmd["cid"])
        # an NN-supplied manifest (owner-loss deputization) wins over the
        # local WAL: cids are per-DN counters, so the deputy's OWN container
        # of the same cid would shadow the dead owner's group otherwise
        manifest = cmd.get("manifest") or dn.index.stripe_manifest(cid)
        if manifest is None:
            return
        owner = manifest.get("owner", dn.dn_id)
        missing = [int(i) for i in cmd["missing"]]
        targets = [list(t) for t in cmd["targets"]]
        red = dn.reduction_ctx.config
        with retry.bind(retry.Deadline(_CMD_BUDGET_S)), qos.background():
            decoded = None
            if getattr(red, "ec_coded_repair", True):
                decoded = self._gather_coded(cid, manifest, missing)
            if decoded is None:
                # classic full gather: k whole stripes to the owner, CRC-
                # filtered per stripe (corrupt survivors become erasures)
                got = self._gather(cid, manifest, exclude=set(missing))
                bad = {i for i, v in got.items()
                       if int(native.crc32c(v)) != int(manifest["crcs"][i])}
                if bad:
                    # a corrupt survivor is an erasure: re-gather around
                    # it so the decode still sees k intact stripes
                    _M.incr("repair_corrupt_survivors", len(bad))
                    more = self._gather(
                        cid, manifest, exclude=set(missing) | set(got))
                    got = {i: v for i, v in got.items() if i not in bad}
                    got.update(more)
                try:
                    decoded = stripe_store.reconstruct_container(
                        got, manifest, want=missing)
                except (stripe_store.StripeCorrupt, ValueError):
                    _M.incr("repair_failures")
                    return
                coded_exchange.book_repair_wire(
                    sum(len(v) for v in got.values()),
                    sum(len(v) for v in decoded.values()))
            holders = [list(t) for t in manifest["holders"]]
            try:
                for idx, tgt in zip(missing, targets):
                    self._place(tgt, cid, idx, decoded[idx],
                                manifest["crcs"][idx], owner=owner)
                    holders[idx] = list(tgt)
                    _M.incr("repair_bytes", len(decoded[idx]))
            except (OSError, ConnectionError, IOError,
                    retry.DeadlineExceeded):
                _M.incr("repair_failures")
                return
        manifest["holders"] = holders
        if owner == dn.dn_id:
            # agents repairing a dead owner's group must NOT WAL the
            # foreign manifest: cids are per-DN counters, so a local
            # record would shadow this DN's own container of the same id
            # — the NN's editlog copy stays the orphan group's home
            dn.index.record_stripe(cid, manifest)
        _M.incr("stripes_repaired", len(missing))
        self._notify_nn(cmd.get("block_id"),
                        [{"cid": cid, "holders": holders,
                          "logical": manifest["length"],
                          "physical": (int(manifest["k"])
                                       + int(manifest["m"]))
                          * manifest["stripe_len"],
                          "manifest": manifest}],
                        owner=owner)

    # ---------------------------------------------------------- plumbing

    def _place(self, target: list, cid: int, idx: int, data: bytes,
               crc: int, owner: str | None = None) -> None:
        """Durably land one stripe on ``target`` (local fast path; peers
        via stripe_write with capped retries under the ambient deadline and
        the background-transfer throttle).  ``owner`` names the group the
        stripe files belong to — repairs of a dead owner's group pass the
        ORIGINAL owner so surviving holders' (owner, cid, idx) paths stay
        coherent; demotion defaults to this DN."""
        dn = self._dn
        owner = owner or dn.dn_id
        tgt_id, host, port = target[0], target[1], int(target[2])
        if tgt_id == dn.dn_id:
            self.store.put_stripe(owner, cid, idx, data, crc=crc)
            return
        # coded-exchange push: smaller-of LZ4 negotiation (sealed-container
        # stripes are usually incompressible and ship raw; raw-codec and
        # parity-of-raw stripes compress), paced + admitted inside
        # dn.coded.send on the background lane
        wire, enc = data, 0
        if dn.coded.compress_on:
            wire, enc = coded_exchange.pack(data, dn.coded.backend)
        _M.incr("stripe_push_raw_bytes", len(data))
        _M.incr("stripe_push_wire_bytes", len(wire))
        state = {"wire": wire, "enc": enc}

        def _push() -> None:
            resp = dn.coded.send((host, port), dt.STRIPE_WRITE,
                                 len(state["wire"]), owner=owner, cid=cid,
                                 idx=idx, data=state["wire"],
                                 enc=state["enc"], usize=len(data), crc=crc)
            if not resp.get("ok"):
                if state["enc"]:
                    # peer refused the encoded payload (old version or
                    # decode failure): re-negotiate to raw for the retries
                    _M.incr("stripe_push_enc_fallbacks")
                    state["wire"], state["enc"] = data, 0
                raise IOError(f"stripe_write {cid}/{idx} to {tgt_id}: "
                              f"{resp.get('error')}")
        retry.call_with_retries(
            _push, attempts=3,
            retry_on=(ConnectionError, OSError, IOError))

    def _gather(self, cid: int, manifest: dict,
                exclude: set[int] | None = None) -> dict[int, bytes]:
        """k+δ straggler-proof stripe gather (utils/retry.py:194
        ``hedged_quorum``; arXiv 1802.03049's speculative k+δ fetch):
        launch k primary legs — data indices first, so no decode is
        needed when all k land — plus up to ``ec_read_hedge_delta``
        hedged legs once the rolling per-holder p95 leg latency elapses,
        and decode from the FIRST k to land instead of waiting out a
        stalled holder.  Falls back to the serial loop when δ = 0, when
        fewer than k breaker-closed legs can launch, or when the hedged
        fan-out itself misses quorum (mid-gather holder deaths beyond
        what δ covered)."""
        dn = self._dn
        red = dn.reduction_ctx.config
        k, m = int(manifest["k"]), int(manifest["m"])
        owner = manifest.get("owner", dn.dn_id)
        holders = manifest["holders"]
        delta = int(getattr(red, "ec_read_hedge_delta", 0))
        if delta <= 0:
            return self._gather_serial(cid, manifest, exclude)

        # Candidate legs in data-first order, minus excluded stripes and
        # breaker-OPEN edges.  The .state peek is probe-free: half-open
        # edges stay IN the candidate set and spend their single probe
        # inside the leg via br.allow() at call time.
        usable: list[int] = []
        for idx in range(k + m):
            if exclude and idx in exclude:
                continue
            tgt_id = holders[idx][0]
            if (tgt_id != dn.dn_id
                    and retry.breaker(f"{dn.dn_id}->{tgt_id}").state
                    == "open"):
                _M.incr("breaker_skips")
                continue
            usable.append(idx)
        if len(usable) < k:
            # Not enough live legs for a quorum launch; the serial loop
            # still gathers whatever exists (caller handles < k).
            return self._gather_serial(cid, manifest, exclude)
        primaries = usable[:k]
        hedge_idxs = usable[k:k + delta]

        accept_enc = 1 if dn.coded.compress_on else 0

        def leg(idx: int):
            tgt_id, host, port = (holders[idx][0], holders[idx][1],
                                  int(holders[idx][2]))

            def run():
                fault_injection.point("ec.stripe_hedge", dn_id=dn.dn_id,
                                      holder=tgt_id, idx=idx)
                t0 = time.monotonic()
                if tgt_id == dn.dn_id:
                    data = self.store.read_stripe(owner, cid, idx)
                else:
                    br = retry.breaker(f"{dn.dn_id}->{tgt_id}")
                    if not br.allow():
                        raise retry.BreakerOpen(f"{dn.dn_id}->{tgt_id}")
                    try:
                        resp = dn._peer_call((host, port), dt.STRIPE_READ,
                                             owner=owner, cid=cid, idx=idx,
                                             accept_enc=accept_enc)
                        if not resp.get("ok"):
                            raise IOError(
                                resp.get("error", "stripe_read failed"))
                        data = coded_exchange.unpack(
                            resp["data"], int(resp.get("enc", 0)),
                            int(resp.get("usize", 0)))
                    except (OSError, ConnectionError, IOError, KeyError,
                            ValueError):
                        br.record_failure()
                        raise
                    br.record_success()
                self._leg_win.note(tgt_id, time.monotonic() - t0)
                return idx, data

            return run

        sums = self._leg_win.summaries()
        p95s = [sums[holders[i][0]]["p95"] for i in primaries
                if holders[i][0] in sums]
        hedge_after = max((max(p95s) if p95s else 0.0)
                          * red.mirror_hedge_p95_mult,
                          red.mirror_hedge_floor_s)
        try:
            with profiler.phase("ec_gather"):
                wins, _errors, _hedged = retry.hedged_quorum(
                    [leg(i) for i in primaries],
                    [leg(i) for i in hedge_idxs],
                    k=k, hedge_after_s=hedge_after,
                    timeout_s=_CMD_BUDGET_S,
                    on_hedge=lambda: _M.incr("ec_hedges_fired"))
        except retry.QuorumFailed as e:
            _M.incr("ec_hedge_fallbacks")
            # hand the serial fallback the holders that JUST failed so it
            # does not burn its budget re-contacting them (their breakers
            # may need more consecutive failures to open)
            legs_by_pos = primaries + hedge_idxs
            failed = {holders[legs_by_pos[j]][0] for j, _err in e.errors
                      if j < len(legs_by_pos)
                      and holders[legs_by_pos[j]][0] != dn.dn_id}
            return self._gather_serial(cid, manifest, exclude,
                                       failed=failed)
        got: dict[int, bytes] = {}
        for leg_i, (sidx, data) in wins:
            got[sidx] = data
            if leg_i >= len(primaries):
                _M.incr("ec_hedge_wins")
        accounting.record_stripe_gather(sum(len(v) for v in got.values()))
        return got

    def _gather_serial(self, cid: int, manifest: dict,
                       exclude: set[int] | None = None,
                       failed: set[str] | None = None) -> dict[int, bytes]:
        """Serial fallback gather: fetch up to k stripes one holder at a
        time, data indices first, skipping ``exclude``, holders that just
        failed the hedged attempt (``failed``), and breaker-open peers —
        the same probe-free ``.state`` peek the k+δ path uses, so a
        half-open edge's single probe is spent at CALL time (br.allow()),
        never on the skip decision.  Leg latencies feed the same
        ``_leg_win`` windows as the hedged legs, so serial rounds keep the
        hedge-trigger p95s warm instead of letting them age out."""
        dn = self._dn
        k, m = int(manifest["k"]), int(manifest["m"])
        owner = manifest.get("owner", dn.dn_id)
        holders = manifest["holders"]
        accept_enc = 1 if dn.coded.compress_on else 0
        got: dict[int, bytes] = {}
        with profiler.phase("ec_gather"):
            for idx in range(k + m):
                if len(got) >= k:
                    break
                if exclude and idx in exclude:
                    continue
                tgt_id, host, port = (holders[idx][0], holders[idx][1],
                                      int(holders[idx][2]))
                if tgt_id == dn.dn_id:
                    try:
                        got[idx] = self.store.read_stripe(owner, cid, idx)
                    except OSError:
                        continue
                    continue
                if failed and tgt_id in failed:
                    _M.incr("serial_failed_skips")
                    continue
                br = retry.breaker(f"{dn.dn_id}->{tgt_id}")
                if br.state == "open" or not br.allow():
                    _M.incr("breaker_skips")
                    continue
                t0 = time.monotonic()
                try:
                    resp = dn._peer_call((host, port), dt.STRIPE_READ,
                                         owner=owner, cid=cid, idx=idx,
                                         accept_enc=accept_enc)
                    if not resp.get("ok"):
                        raise IOError(resp.get("error", "stripe_read failed"))
                    got[idx] = coded_exchange.unpack(
                        resp["data"], int(resp.get("enc", 0)),
                        int(resp.get("usize", 0)))
                    br.record_success()
                    self._leg_win.note(tgt_id, time.monotonic() - t0)
                except (OSError, ConnectionError, IOError, KeyError,
                        ValueError):
                    br.record_failure()
                    continue
        accounting.record_stripe_gather(sum(len(v) for v in got.values()))
        return got

    def _gather_coded(self, cid: int, manifest: dict,
                      missing: list[int]) -> dict[int, bytes] | None:
        """Partial-sum repair gather (ops/rs.py ``repair_rows`` /
        ``partial_sums``; the repair-pipelining shape of arXiv
        1802.03049): pick k breaker-closed survivors, split the repair
        matrix's columns by holding DN, fold this DN's local
        contribution for free, and chain ONE ``stripe_coded_read``
        through the remote holders — each XORs its contribution into the
        (|missing|, stripe_len) response riding back, so owner ingress is
        ~|missing| stripes instead of k.  Every rebuilt stripe is
        CRC-verified against the manifest: a corrupt contribution
        anywhere in the fold surfaces there (the sum hides WHICH survivor
        was bad), and ``None`` sends the caller to the classic gather,
        which CRC-filters per stripe and treats the corrupt one as an
        erasure.  ``None`` likewise on any chain/peer/old-version
        failure — the fallback is byte-identical."""
        dn = self._dn
        if not missing:
            return {}
        k, m = int(manifest["k"]), int(manifest["m"])
        owner = manifest.get("owner", dn.dn_id)
        holders = manifest["holders"]
        stripe_len = int(manifest["stripe_len"])
        exclude = set(missing)
        usable: list[int] = []
        for idx in range(k + m):
            if idx in exclude:
                continue
            tgt_id = holders[idx][0]
            if (tgt_id != dn.dn_id
                    and retry.breaker(f"{dn.dn_id}->{tgt_id}").state
                    == "open"):
                _M.incr("breaker_skips")
                continue
            usable.append(idx)
        if len(usable) < k:
            return None
        have = usable[:k]
        rows = rs.repair_rows(k, m, tuple(have), tuple(missing))
        col_of = {s: j for j, s in enumerate(have)}
        local: list[int] = []
        groups: dict[str, tuple[tuple, list[int]]] = {}
        for s in have:
            tgt_id, host, port = (holders[s][0], holders[s][1],
                                  int(holders[s][2]))
            if tgt_id == dn.dn_id:
                local.append(s)
            else:
                groups.setdefault(tgt_id, ((host, port), []))[1].append(s)
        parts = np.zeros((len(missing), stripe_len), dtype=np.uint8)
        if local:
            try:
                stripes = np.stack([np.frombuffer(
                    self.store.read_stripe(owner, cid, s), dtype=np.uint8)
                    for s in local])
            except OSError:
                return None
            coeffs = rows[:, [col_of[s] for s in local]]
            parts ^= rs.partial_sums(stripes, coeffs)
        wire = 0
        if groups:
            # one chain through the remote holders; per-survivor coeff
            # columns ride as str-keyed lists (msgpack-stable)
            plan = [[tgt_id, addr[0], addr[1],
                     {str(s): [int(c) for c in rows[:, col_of[s]]]
                      for s in idxs}]
                    for tgt_id, (addr, idxs) in groups.items()]
            head = plan[0]
            br = retry.breaker(f"{dn.dn_id}->{head[0]}")
            try:
                with profiler.phase("ec_gather"):
                    resp = dn.coded.send(
                        (head[1], int(head[2])), dt.STRIPE_CODED_READ,
                        len(missing) * stripe_len, owner=owner, cid=cid,
                        stripe_len=stripe_len, nwant=len(missing),
                        plan=plan, accept_enc=1 if dn.coded.compress_on
                        else 0)
                if not resp.get("ok"):
                    raise IOError(resp.get("error", "coded read failed"))
                encs = resp.get("enc") or [0] * len(resp["parts"])
                wire = sum(len(p) for p in resp["parts"])
                for i, (p, e) in enumerate(zip(resp["parts"], encs)):
                    parts[i] ^= np.frombuffer(
                        coded_exchange.unpack(p, e, stripe_len),
                        dtype=np.uint8)
            except (OSError, ConnectionError, IOError, KeyError,
                    ValueError, RuntimeError):
                # unknown op on an old peer lands here too (no response
                # frame -> recv error): classic gather takes over
                br.record_failure()
                _M.incr("coded_repair_fallbacks")
                return None
            br.record_success()
        decoded: dict[int, bytes] = {}
        for i, w in enumerate(missing):
            blob = parts[i].tobytes()
            if int(native.crc32c(blob)) != int(manifest["crcs"][w]):
                _M.incr("coded_contrib_corrupt")
                _M.incr("coded_repair_fallbacks")
                return None
            decoded[w] = blob
        accounting.record_stripe_gather(wire)
        coded_exchange.book_repair_wire(wire,
                                        len(missing) * stripe_len)
        _M.incr("coded_repairs")
        return decoded

    def _notify_nn(self, block_id, containers: list[dict],
                   owner: str | None = None) -> None:
        """Report new/updated stripe groups (and the demoted block) to the
        NameNodes; first accepting NN wins — the active applies it, a
        standby refuses (same pattern as commit_block_sync).  ``owner``
        keys the groups when a deputized agent reports a dead owner's
        repair (defaults to the reporting DN)."""
        from hdrf_tpu.proto.rpc import RpcError

        for nn in self._dn._nns:
            try:
                nn.call("stripe_complete", dn_id=self._dn.dn_id,
                        block_id=block_id, containers=containers,
                        owner=owner)
                return
            except (OSError, ConnectionError, RpcError):
                continue
        _M.incr("stripe_complete_failures")

    # ------------------------------------------------------------- stats

    def report(self) -> dict:
        """Heartbeat payload: tier sizes + the holder map the NN's repair
        scheduler rebuilds its soft state from (stripe groups are WAL-
        durable HERE, in the owner DN's chunk index — the NN only caches)."""
        from hdrf_tpu.reduction import accounting

        manifests = self._dn.index.stripe_manifests()
        logical = sum(int(m["length"]) for m in manifests.values())
        physical = self.store.physical_bytes()
        accounting.record_stripe_tier(logical, physical)
        return {
            "striped_containers": len(manifests),
            "stripe_logical_bytes": logical,
            "stripe_physical_bytes": physical,
            "manifests": {str(cid): {"holders": m["holders"],
                                     "length": int(m["length"])}
                          for cid, m in manifests.items()},
        }
