"""DataNode half of the EC(6,3) cold tier: demotion, serving, repair.

Re-expresses the reference's DN-side erasure-coding worker stack —
ErasureCodingWorker.java:55 (reconstruction executor wired to NN
commands), StripedBlockReconstructor.java:41 (fan-in k shards, decode,
write back), StripedBlockReader.java:40 (per-shard fetch legs),
BlockECReconstructionCommand (DNA_ERASURE_CODING_RECONSTRUCTION) — on
top of the container abstraction: the striping unit is a **sealed
container file** (storage/stripe_store.py), not a raw block group, so
demotion multiplies the EC saving with the reduction ratio.  Three
roles live here:

- **Demote** (NN ``stripe_demote`` command): RS-encode every sealed
  container backing a cold block, push the k+m stripes to NN-chosen
  holders (peer ``stripe_write`` ops under the retry/deadline spine,
  utils/retry.py), WAL the manifest in the chunk index
  (index/chunk_index.py record_stripe — the commit point), then delete
  the local sealed file and report ``stripe_complete`` to the NN.
- **Degraded read** (ContainerStore ``_stripe_fallback`` hook): when a
  chunk gather misses the sealed file, gather any k surviving stripes —
  local disk first, then peers, skipping breaker-open edges (PR-5
  evidence) — and reassemble the exact sealed bytes, decoding through
  ops/rs.py only when a data stripe is lost.  With
  ``ec_read_hedge_delta`` > 0 the gather launches k primary legs PLUS
  δ hedged legs through utils/retry.py:1 ``hedged_quorum`` once the
  rolling per-holder p95 leg latency elapses, decoding from the first k
  to land — the k+δ speculative-fetch result of the straggler-coding
  line (arXiv 1802.03049; StripedBlockReader.java:40's serial legs are
  the tail it removes), with the old serial loop kept as the fallback
  when fewer than k legs can launch.  The reconstructed payload feeds
  the unchanged decompress + device chunk-gather path
  (ops/reconstruct.py), so reads stay bit-identical to the replicated
  tier.
- **Repair** (NN ``stripe_repair`` command): re-decode exactly the lost
  stripe indices from k survivors and push them to replacement holders,
  keeping the manifest's holder map current.
"""

from __future__ import annotations

import os
import time

from hdrf_tpu.reduction import accounting
from hdrf_tpu.storage import stripe_store
from hdrf_tpu.storage.container_store import _SEAL_HDR, _SEAL_MAGIC
from hdrf_tpu.utils import fault_injection, metrics, profiler, retry, rollwin

_M = metrics.registry("ec")

# budget for one whole demote/repair command (all stripe legs); each peer
# leg retries under it via the ambient-deadline discipline of utils/retry
_CMD_BUDGET_S = 60.0


class EcTier:
    """Owns the DN's stripe store + the three cold-tier roles above."""

    def __init__(self, dn) -> None:
        self._dn = dn
        self.store = stripe_store.StripeStore(
            os.path.join(dn.config.data_dir, "stripes"))
        # degraded-read hooks: a missing sealed file falls through to
        # reconstruction; has_container consults the manifest's payload size
        dn.containers._stripe_fallback = self.reconstruct_sealed
        dn.containers._stripe_probe = self.stripe_usize
        # chain the delete observer: a deleted striped container must drop
        # its local stripes + manifest too (remote stripes are reclaimed by
        # the NN's repair loop noticing the group vanished)
        prev_on_delete = dn.containers._on_delete

        def _on_delete(cid: int) -> None:
            if prev_on_delete is not None:
                prev_on_delete(cid)
            if dn.index.stripe_manifest(cid) is not None:
                self.store.delete_stripes(dn.dn_id, cid)
                dn.index.drop_stripe(cid)

        dn.containers._on_delete = _on_delete
        # rolling per-holder stripe-leg latency (seconds), the hedge
        # trigger's p95 input (the gather-side sibling of the DN's
        # _peer_win slow-peer windows)
        self._leg_win = rollwin.WindowMap(window_s=300.0, maxlen=64)

    # ------------------------------------------------------------ hooks

    def stripe_usize(self, cid: int) -> int | None:
        """Uncompressed payload size of a striped container (has_container's
        extent check), or None when the container is not striped."""
        m = self._dn.index.stripe_manifest(cid)
        return int(m["usize"]) if m is not None else None

    def reconstruct_sealed(self, cid: int) -> bytes | None:
        """ContainerStore fallback: reassemble the sealed FILE bytes of a
        demoted container from any k surviving stripes.  None = not striped
        or unrecoverable (the store then raises its original error)."""
        manifest = self._dn.index.stripe_manifest(cid)
        if manifest is None:
            return None
        _M.incr("stripe_gathers")
        got = self._gather(cid, manifest)
        k = int(manifest["k"])
        if len(got) < k:
            _M.incr("degraded_read_failures")
            return None
        # (a gather missing a data stripe decodes through parity — the
        # store's reconstruct_container counts that as a degraded read)
        try:
            blob = stripe_store.reconstruct_container(got, manifest)
        except (stripe_store.StripeCorrupt, ValueError):
            _M.incr("degraded_read_failures")
            return None
        assert isinstance(blob, bytes)
        return blob

    # ---------------------------------------------------------- serving

    def serve_read(self, sock, fields: dict) -> None:
        """Peer ``stripe_read``: hand one local stripe to a gatherer."""
        from hdrf_tpu.proto.rpc import send_frame

        fault_injection.point("stripe.read", dn_id=self._dn.dn_id)
        owner = fields["owner"]
        cid, idx = int(fields["cid"]), int(fields["idx"])
        try:
            data = self.store.read_stripe(owner, cid, idx)
        except FileNotFoundError:
            send_frame(sock, {"ok": False,
                              "error": f"no stripe {owner}/{cid}/{idx}"})
            return
        send_frame(sock, {"ok": True, "data": data})

    def serve_write(self, sock, fields: dict) -> None:
        """Peer ``stripe_write``: durably store a stripe pushed by the
        demoting/repairing owner (CRC-checked before the ack)."""
        from hdrf_tpu.proto.rpc import send_frame

        try:
            self.store.put_stripe(fields["owner"], int(fields["cid"]),
                                  int(fields["idx"]), fields["data"],
                                  crc=fields.get("crc"))
        except stripe_store.StripeCorrupt as e:
            send_frame(sock, {"ok": False, "error": str(e)})
            return
        send_frame(sock, {"ok": True})

    # --------------------------------------------------------- demotion

    def demote(self, cmd: dict) -> None:
        """NN ``stripe_demote``: stripe every sealed, not-yet-striped
        container backing ``block_id`` onto ``targets``, then report.
        Ordering per container: stripes durable on holders -> manifest
        WAL'd -> sealed file deleted — a crash at any point leaves the
        container readable (sealed file until the WAL commit, stripes
        after)."""
        dn = self._dn
        bid = cmd["block_id"]
        k, m = int(cmd["k"]), int(cmd["m"])
        targets = [list(t) for t in cmd["targets"]]
        if len(targets) != k + m:
            _M.incr("demote_failures")
            return
        entry = dn.index.get_block(bid)
        if entry is None:
            return
        cids: list[int] = []
        for h in entry.hashes:
            loc = dn.index.chunk_location(h)
            if loc is not None and loc.container_id not in cids:
                cids.append(loc.container_id)
        done: list[dict] = []
        with retry.bind(retry.Deadline(_CMD_BUDGET_S)):
            for cid in cids:
                if dn.index.stripe_manifest(cid) is not None:
                    continue  # already striped (shared container)
                blob = dn.containers.sealed_file_bytes(cid)
                if blob is None:
                    continue  # open/raw container: stays hot
                magic, usize, _codec = _SEAL_HDR.unpack(
                    blob[:_SEAL_HDR.size])
                if magic != _SEAL_MAGIC:
                    continue
                stripes, manifest = stripe_store.encode_container(blob, k, m)
                manifest.update(owner=dn.dn_id, usize=usize,
                                holders=targets)
                try:
                    for idx, data in enumerate(stripes):
                        self._place(targets[idx], cid, idx, data,
                                    manifest["crcs"][idx])
                except (OSError, ConnectionError, IOError,
                        retry.DeadlineExceeded):
                    _M.incr("demote_failures")
                    continue  # no manifest committed: sealed file stays
                dn.index.record_stripe(cid, manifest)
                freed = dn.containers.drop_sealed_file(cid)
                _M.incr("containers_demoted")
                _M.incr("demote_bytes_freed", freed)
                # the full manifest rides the report so the NN can journal
                # it (editlog/fsimage durable): owner-loss repair needs a
                # copy that survives this DN's WAL dying with this DN
                done.append({"cid": cid, "holders": targets,
                             "logical": manifest["length"],
                             "physical": (k + m) * manifest["stripe_len"],
                             "manifest": manifest})
        if done:
            self._notify_nn(bid, done)

    def repair(self, cmd: dict) -> None:
        """NN ``stripe_repair``: re-decode the lost stripe indices from k
        survivors and push them to replacement holders.  The manifest comes
        from this DN's WAL when it is the group's owner; after OWNER loss
        the NN deputizes a surviving holder and hands down its journaled
        manifest copy (``cmd["manifest"]``) — repaired stripes keep the
        original owner's name so every holder's files stay findable."""
        dn = self._dn
        fault_injection.point("stripe.repair", dn_id=dn.dn_id)
        cid = int(cmd["cid"])
        # an NN-supplied manifest (owner-loss deputization) wins over the
        # local WAL: cids are per-DN counters, so the deputy's OWN container
        # of the same cid would shadow the dead owner's group otherwise
        manifest = cmd.get("manifest") or dn.index.stripe_manifest(cid)
        if manifest is None:
            return
        owner = manifest.get("owner", dn.dn_id)
        missing = [int(i) for i in cmd["missing"]]
        targets = [list(t) for t in cmd["targets"]]
        with retry.bind(retry.Deadline(_CMD_BUDGET_S)):
            got = self._gather(cid, manifest, exclude=set(missing))
            try:
                decoded = stripe_store.reconstruct_container(
                    got, manifest, want=missing)
            except (stripe_store.StripeCorrupt, ValueError):
                _M.incr("repair_failures")
                return
            holders = [list(t) for t in manifest["holders"]]
            try:
                for idx, tgt in zip(missing, targets):
                    self._place(tgt, cid, idx, decoded[idx],
                                manifest["crcs"][idx], owner=owner)
                    holders[idx] = list(tgt)
                    _M.incr("repair_bytes", len(decoded[idx]))
            except (OSError, ConnectionError, IOError,
                    retry.DeadlineExceeded):
                _M.incr("repair_failures")
                return
        manifest["holders"] = holders
        if owner == dn.dn_id:
            # agents repairing a dead owner's group must NOT WAL the
            # foreign manifest: cids are per-DN counters, so a local
            # record would shadow this DN's own container of the same id
            # — the NN's editlog copy stays the orphan group's home
            dn.index.record_stripe(cid, manifest)
        _M.incr("stripes_repaired", len(missing))
        self._notify_nn(cmd.get("block_id"),
                        [{"cid": cid, "holders": holders,
                          "logical": manifest["length"],
                          "physical": (int(manifest["k"])
                                       + int(manifest["m"]))
                          * manifest["stripe_len"],
                          "manifest": manifest}],
                        owner=owner)

    # ---------------------------------------------------------- plumbing

    def _place(self, target: list, cid: int, idx: int, data: bytes,
               crc: int, owner: str | None = None) -> None:
        """Durably land one stripe on ``target`` (local fast path; peers
        via stripe_write with capped retries under the ambient deadline and
        the background-transfer throttle).  ``owner`` names the group the
        stripe files belong to — repairs of a dead owner's group pass the
        ORIGINAL owner so surviving holders' (owner, cid, idx) paths stay
        coherent; demotion defaults to this DN."""
        dn = self._dn
        owner = owner or dn.dn_id
        tgt_id, host, port = target[0], target[1], int(target[2])
        if tgt_id == dn.dn_id:
            self.store.put_stripe(owner, cid, idx, data, crc=crc)
            return
        dn.balance_throttler.throttle(len(data))

        def _push() -> None:
            resp = dn._peer_call((host, port), "stripe_write",
                                 owner=owner, cid=cid, idx=idx,
                                 data=data, crc=crc)
            if not resp.get("ok"):
                raise IOError(f"stripe_write {cid}/{idx} to {tgt_id}: "
                              f"{resp.get('error')}")
        retry.call_with_retries(
            _push, attempts=3,
            retry_on=(ConnectionError, OSError, IOError))

    def _gather(self, cid: int, manifest: dict,
                exclude: set[int] | None = None) -> dict[int, bytes]:
        """k+δ straggler-proof stripe gather (utils/retry.py:194
        ``hedged_quorum``; arXiv 1802.03049's speculative k+δ fetch):
        launch k primary legs — data indices first, so no decode is
        needed when all k land — plus up to ``ec_read_hedge_delta``
        hedged legs once the rolling per-holder p95 leg latency elapses,
        and decode from the FIRST k to land instead of waiting out a
        stalled holder.  Falls back to the serial loop when δ = 0, when
        fewer than k breaker-closed legs can launch, or when the hedged
        fan-out itself misses quorum (mid-gather holder deaths beyond
        what δ covered)."""
        dn = self._dn
        red = dn.reduction_ctx.config
        k, m = int(manifest["k"]), int(manifest["m"])
        owner = manifest.get("owner", dn.dn_id)
        holders = manifest["holders"]
        delta = int(getattr(red, "ec_read_hedge_delta", 0))
        if delta <= 0:
            return self._gather_serial(cid, manifest, exclude)

        # Candidate legs in data-first order, minus excluded stripes and
        # breaker-OPEN edges.  The .state peek is probe-free: half-open
        # edges stay IN the candidate set and spend their single probe
        # inside the leg via br.allow() at call time.
        usable: list[int] = []
        for idx in range(k + m):
            if exclude and idx in exclude:
                continue
            tgt_id = holders[idx][0]
            if (tgt_id != dn.dn_id
                    and retry.breaker(f"{dn.dn_id}->{tgt_id}").state
                    == "open"):
                _M.incr("breaker_skips")
                continue
            usable.append(idx)
        if len(usable) < k:
            # Not enough live legs for a quorum launch; the serial loop
            # still gathers whatever exists (caller handles < k).
            return self._gather_serial(cid, manifest, exclude)
        primaries = usable[:k]
        hedge_idxs = usable[k:k + delta]

        def leg(idx: int):
            tgt_id, host, port = (holders[idx][0], holders[idx][1],
                                  int(holders[idx][2]))

            def run():
                fault_injection.point("ec.stripe_hedge", dn_id=dn.dn_id,
                                      holder=tgt_id, idx=idx)
                t0 = time.monotonic()
                if tgt_id == dn.dn_id:
                    data = self.store.read_stripe(owner, cid, idx)
                else:
                    br = retry.breaker(f"{dn.dn_id}->{tgt_id}")
                    if not br.allow():
                        raise retry.BreakerOpen(f"{dn.dn_id}->{tgt_id}")
                    try:
                        resp = dn._peer_call((host, port), "stripe_read",
                                             owner=owner, cid=cid, idx=idx)
                        if not resp.get("ok"):
                            raise IOError(
                                resp.get("error", "stripe_read failed"))
                        data = resp["data"]
                    except (OSError, ConnectionError, IOError, KeyError):
                        br.record_failure()
                        raise
                    br.record_success()
                self._leg_win.note(tgt_id, time.monotonic() - t0)
                return idx, data

            return run

        sums = self._leg_win.summaries()
        p95s = [sums[holders[i][0]]["p95"] for i in primaries
                if holders[i][0] in sums]
        hedge_after = max((max(p95s) if p95s else 0.0)
                          * red.mirror_hedge_p95_mult,
                          red.mirror_hedge_floor_s)
        try:
            with profiler.phase("ec_gather"):
                wins, _errors, _hedged = retry.hedged_quorum(
                    [leg(i) for i in primaries],
                    [leg(i) for i in hedge_idxs],
                    k=k, hedge_after_s=hedge_after,
                    timeout_s=_CMD_BUDGET_S,
                    on_hedge=lambda: _M.incr("ec_hedges_fired"))
        except retry.QuorumFailed:
            _M.incr("ec_hedge_fallbacks")
            return self._gather_serial(cid, manifest, exclude)
        got: dict[int, bytes] = {}
        for leg_i, (sidx, data) in wins:
            got[sidx] = data
            if leg_i >= len(primaries):
                _M.incr("ec_hedge_wins")
        accounting.record_stripe_gather(sum(len(v) for v in got.values()))
        return got

    def _gather_serial(self, cid: int, manifest: dict,
                       exclude: set[int] | None = None) -> dict[int, bytes]:
        """Serial fallback gather: fetch up to k stripes one holder at a
        time, data indices first, skipping ``exclude`` and breaker-open
        peers (the pre-hedging PR-10 path, kept for δ = 0 and for
        quorum-miss recovery)."""
        dn = self._dn
        k, m = int(manifest["k"]), int(manifest["m"])
        owner = manifest.get("owner", dn.dn_id)
        holders = manifest["holders"]
        got: dict[int, bytes] = {}
        with profiler.phase("ec_gather"):
            for idx in range(k + m):
                if len(got) >= k:
                    break
                if exclude and idx in exclude:
                    continue
                tgt_id, host, port = (holders[idx][0], holders[idx][1],
                                      int(holders[idx][2]))
                if tgt_id == dn.dn_id:
                    try:
                        got[idx] = self.store.read_stripe(owner, cid, idx)
                    except OSError:
                        continue
                    continue
                br = retry.breaker(f"{dn.dn_id}->{tgt_id}")
                if not br.allow():
                    _M.incr("breaker_skips")
                    continue
                try:
                    resp = dn._peer_call((host, port), "stripe_read",
                                         owner=owner, cid=cid, idx=idx)
                    if not resp.get("ok"):
                        raise IOError(resp.get("error", "stripe_read failed"))
                    got[idx] = resp["data"]
                    br.record_success()
                except (OSError, ConnectionError, IOError, KeyError):
                    br.record_failure()
                    continue
        accounting.record_stripe_gather(sum(len(v) for v in got.values()))
        return got

    def _notify_nn(self, block_id, containers: list[dict],
                   owner: str | None = None) -> None:
        """Report new/updated stripe groups (and the demoted block) to the
        NameNodes; first accepting NN wins — the active applies it, a
        standby refuses (same pattern as commit_block_sync).  ``owner``
        keys the groups when a deputized agent reports a dead owner's
        repair (defaults to the reporting DN)."""
        from hdrf_tpu.proto.rpc import RpcError

        for nn in self._dn._nns:
            try:
                nn.call("stripe_complete", dn_id=self._dn.dn_id,
                        block_id=block_id, containers=containers,
                        owner=owner)
                return
            except (OSError, ConnectionError, RpcError):
                continue
        _M.incr("stripe_complete_failures")

    # ------------------------------------------------------------- stats

    def report(self) -> dict:
        """Heartbeat payload: tier sizes + the holder map the NN's repair
        scheduler rebuilds its soft state from (stripe groups are WAL-
        durable HERE, in the owner DN's chunk index — the NN only caches)."""
        from hdrf_tpu.reduction import accounting

        manifests = self._dn.index.stripe_manifests()
        logical = sum(int(m["length"]) for m in manifests.values())
        physical = self.store.physical_bytes()
        accounting.record_stripe_tier(logical, physical)
        return {
            "striped_containers": len(manifests),
            "stripe_logical_bytes": logical,
            "stripe_physical_bytes": physical,
            "manifests": {str(cid): {"holders": m["holders"],
                                     "length": int(m["length"])}
                          for cid, m in manifests.items()},
        }
