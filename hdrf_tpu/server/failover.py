"""Automatic failover controller (the ZKFC analog, minus ZooKeeper).

The reference's DFSZKFailoverController watches NN health via RPC and uses a
ZooKeeper leader lock to coordinate who promotes whom
(DFSZKFailoverController.java:63; HAZKInfo.proto).  Here
the shared journal's epoch IS the lock (editlog.claim_epoch fences the old
writer), so the controller only needs health checking + a promote call:
poll every NN's ha_state; if no active answers for ``grace`` consecutive
probes, transition the first healthy standby.  Safe under split brain by
construction — two controllers racing both call transition_to_active, the
second claim_epoch wins, the first active gets fenced on its next append.
"""

from __future__ import annotations

import threading

from hdrf_tpu.proto.rpc import RpcClient
from hdrf_tpu.utils import metrics

_M = metrics.registry("failover")


class FailoverController:
    def __init__(self, nn_addrs: list[tuple[str, int]],
                 probe_interval_s: float = 1.0, grace: int = 3):
        self._addrs = [tuple(a) for a in nn_addrs]
        self._interval = probe_interval_s
        self._grace = grace
        self._misses = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, name="zkfc",
                                        daemon=True)

    def start(self) -> "FailoverController":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)

    def probe(self) -> tuple[bool, list[tuple[tuple, str]]]:
        """(active_alive, [(addr, role) for each reachable NN])."""
        states = []
        active_alive = False
        for addr in self._addrs:
            try:
                with RpcClient(addr, timeout=2.0) as c:
                    st = c.call("ha_state")
                states.append((addr, st["role"]))
                if st["role"] == "active":
                    active_alive = True
            except (OSError, ConnectionError):
                continue
        return active_alive, states

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                active_alive, states = self.probe()
                if active_alive:
                    self._misses = 0
                    continue
                self._misses += 1
                _M.incr("active_misses")
                if self._misses < self._grace:
                    continue
                for addr, role in states:
                    if role == "standby":
                        with RpcClient(addr, timeout=5.0) as c:
                            c.call("transition_to_active")
                        _M.incr("failovers_triggered")
                        self._misses = 0
                        break
            except Exception:  # noqa: BLE001 — controller must survive
                _M.incr("controller_errors")
