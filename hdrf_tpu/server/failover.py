"""Automatic failover controller (the ZKFC analog, minus ZooKeeper).

The reference's DFSZKFailoverController watches NN health via RPC and uses a
ZooKeeper leader lock to coordinate who promotes whom
(DFSZKFailoverController.java:63; HAZKInfo.proto).  Here
the shared journal's epoch IS the lock (editlog.claim_epoch fences the old
writer), so the controller only needs health checking + a promote call:
poll every NN's ha_state; once the active is settled-dead, transition the
reachable STANDBY with the highest applied txid (the most-caught-up
replica — promoting a lagged one forfeits quorum-committed edits until its
catch-up tail runs, and in shared-dir mode forfeits them for good).
Observers are never candidates: they are read replicas by contract
(ObserverReadProxyProvider semantics) and keep serving staleness-bounded
reads THROUGH the failover window.  Safe under split brain by
construction — two controllers racing both call transition_to_active, the
second claim_epoch wins, the first active gets fenced on its next append.

Miss tracking is per NN endpoint, not global: a flaky probe target that
happens to be polled alongside a healthy active must not age the global
counter toward a spurious failover, and — the inverse failure the global
counter had — one reachable-but-slow endpoint resetting a shared counter
must not mask an active that is actually down.
"""

from __future__ import annotations

import threading

from hdrf_tpu.proto.rpc import RpcClient
from hdrf_tpu.utils import metrics

_M = metrics.registry("failover")


class FailoverController:
    def __init__(self, nn_addrs: list[tuple[str, int]],
                 probe_interval_s: float = 1.0, grace: int = 3):
        self._addrs = [tuple(a) for a in nn_addrs]
        self._interval = probe_interval_s
        self._grace = grace
        # per-endpoint consecutive probe misses + the last addr seen in the
        # active role: "the active is dead" requires ITS endpoint to have
        # missed `grace` straight probes (or to answer in a demoted role),
        # not merely `grace` rounds with no active in sight.
        self._misses: dict[tuple, int] = {a: 0 for a in self._addrs}
        self._active_addr: tuple | None = None
        self._rounds_without_active = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, name="zkfc",
                                        daemon=True)

    def start(self) -> "FailoverController":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)

    def probe(self) -> tuple[bool, list[tuple[tuple, str, int]]]:
        """(active_alive, [(addr, role, applied_txid) per reachable NN])."""
        states = []
        active_alive = False
        for addr in self._addrs:
            try:
                with RpcClient(addr, timeout=2.0) as c:
                    st = c.call("ha_state")
            except (OSError, ConnectionError):
                self._misses[addr] = self._misses.get(addr, 0) + 1
                continue
            self._misses[addr] = 0
            txid = int(st.get("applied_txid", st.get("seq", 0)) or 0)
            states.append((addr, st["role"], txid))
            if st["role"] == "active":
                active_alive = True
                self._active_addr = addr
        return active_alive, states

    @staticmethod
    def _choose_candidate(states: list[tuple[tuple, str, int]]
                          ) -> tuple | None:
        """The reachable standby with the highest applied txid; observers
        are read replicas, never failover candidates."""
        best: tuple | None = None
        best_txid = -1
        for addr, role, txid in states:
            if role != "standby":
                continue
            if txid > best_txid:
                best, best_txid = addr, txid
        return best

    def _active_settled_dead(self, states) -> bool:
        """True once the evidence points at the ACTIVE being down, not at a
        flaky probe path: its endpoint missed `grace` straight probes, or
        it answered in a non-active role (demoted/fenced — no grace
        needed), or no active was ever seen for `grace` rounds."""
        known = self._active_addr
        if known is None:
            return self._rounds_without_active >= self._grace
        if any(addr == known for addr, _role, _txid in states):
            return True  # reachable but no longer active: already fenced
        return self._misses.get(known, 0) >= self._grace

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                active_alive, states = self.probe()
                if active_alive:
                    self._rounds_without_active = 0
                    continue
                self._rounds_without_active += 1
                _M.incr("active_misses")
                if not self._active_settled_dead(states):
                    continue
                cand = self._choose_candidate(states)
                if cand is None:
                    continue  # only observers/nothing reachable: keep probing
                with RpcClient(cand, timeout=5.0) as c:
                    c.call("transition_to_active")
                _M.incr("failovers_triggered")
                self._rounds_without_active = 0
                self._active_addr = cand
            except Exception:  # noqa: BLE001 — controller must survive
                _M.incr("controller_errors")
