"""Time-series flight recorder: fixed-cadence gauge snapshots per daemon.

Every surface the daemons already expose is a point-in-time read — /prom and
/metrics (server/status_http.py:52-77) answer "what is the value NOW", and
the reference is no better: Hadoop's MutableRollingAverages keeps a few
windowed means but nothing per-daemon you can plot.  ROADMAP item 3's honest
production number is a *curve* ("tracks storage_ratio and read latency over
time, not just at first write"), so each daemon runs one of these: a sampler
thread that, every ``interval_s``, calls the daemon-supplied ``sample_fn()``
(a dozen key gauges — storage ratio, dedup ratio, cache hit rate, read/write
p95, inflight, breaker states from utils/retry.py:393-395's
``all_breakers``) and appends the dict into a bounded ring.

The ring serves as ``/timeseries`` JSON on status_http + the gateway and is
rendered by tools/slo_report.py (the over-time sibling of
tools/gap_report.py:60-99's one-shot aggregation).  Deterministic for tests:
clocks are injectable and ``sample_once()`` drives the sampler inline — the
thread is just a cadence, never the semantics.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable

from . import metrics

_M = metrics.registry("flight_recorder")


class FlightRecorder:
    """Bounded time-series ring of gauge snapshots, sampled on a cadence.

    ``sample_fn() -> dict[str, float]`` is the daemon's gauge set; each
    sample lands as ``{"t": <wall>, "mono": <monotonic>, **gauges}``.
    Oldest samples fall off once ``capacity`` is reached, bounding memory
    to ``capacity`` dicts regardless of uptime."""

    def __init__(self, name: str, sample_fn: Callable[[], dict],
                 interval_s: float = 1.0, capacity: int = 512,
                 clock=time.monotonic, wall=time.time, archive=None):
        self.name = name
        self.interval_s = float(interval_s)
        self.capacity = int(capacity)
        self._sample_fn = sample_fn
        self._clock = clock
        self._wall = wall
        self._ring: deque[dict[str, Any]] = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.archive = archive
        if archive is not None:
            # Restart survival: re-seed the ring from the archive's tail
            # (utils/flight_archive.py replay — torn tails already
            # dropped), so /timeseries shows pre-crash history at once.
            for s in archive.replay(limit=self.capacity):
                self._ring.append(s)

    def sample_once(self) -> dict[str, Any]:
        """Take one sample inline (the thread's body; tests call it
        directly for determinism).  A sample_fn error is counted, not
        raised — the recorder must never take its daemon down."""
        try:
            gauges = self._sample_fn() or {}
        except Exception:  # noqa: BLE001 — recorder outlives gauge bugs
            _M.incr("sample_errors")
            gauges = {}
        sample = {"t": self._wall(), "mono": self._clock(), **gauges}
        with self._lock:
            self._ring.append(sample)
        if self.archive is not None:
            try:
                self.archive.append(sample)
            except (OSError, ValueError):  # ValueError: closed archive
                _M.incr("archive_errors")
        _M.incr("samples_total")
        return sample

    def snapshot(self) -> dict[str, Any]:
        """The ``/timeseries`` JSON body: ring contents oldest-first plus
        the cadence metadata a renderer needs to put time on an axis."""
        with self._lock:
            samples = list(self._ring)
        return {"daemon": self.name, "interval_s": self.interval_s,
                "capacity": self.capacity, "samples": samples}

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "FlightRecorder":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name=f"flight-recorder-{self.name}",
            daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.sample_once()
