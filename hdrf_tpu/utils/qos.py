"""Per-tenant QoS admission: token buckets, weighted-fair dequeue, and
deadline-aware load shedding.

Generalizes utils/throttler.py:20 (DataTransferThrottler.java:28's blocking
token bucket) into the NON-blocking admission discipline the overload plane
needs: a flooding tenant must be REFUSED with a structured retryable error,
not parked on a lock it will monopolize.  Re-expression of the reference's
FairCallQueue line — fair scheduling (FairCallQueue.java:46's per-priority
sub-queues drained weighted round-robin, here per-TENANT), backoff-instead-
of-queueing (CallQueueManager.java:92 ``shouldBackOff`` →
RetriableException with a retry hint), and the cost-based user accounting
of DecayRpcScheduler.java:57 — folded onto this repo's existing planes:
tenancy attribution rides utils/tenants.py:1's ``_client`` channel,
deadline budgets ride utils/retry.py:64's ambient :class:`Deadline`, and
service-time estimates come from utils/rollwin.py:117's ``WindowMap``.

Three cooperating pieces:

- :class:`TenantBucket` / :class:`AdmissionController` — per-tenant deficit
  token buckets (``admit`` charges nothing; ``charge`` debits ACTUAL bytes
  after the op, possibly driving the bucket negative — byte counts are
  unknown at admission for streamed writes).  ``admit`` also sheds when the
  ambient ``_deadline`` budget cannot cover the rolling-p95 service
  estimate times ``shed_p95_mult`` — rejecting at admission instead of
  burning a slot to time out mid-pipeline (CallQueueManager.java:92's
  backoff-when-overloaded, with the deadline spine as the signal).
- :class:`ShedError` — the structured retryable refusal.  ``retry_after_s``
  is the hint a client should wait before retrying (RetriableException +
  RetryPolicies.java:178's exponential-backoff contract, made explicit).
- :class:`FairQueue` — a queue.Queue-compatible weighted-fair dequeue
  (put / get / get_nowait, queue.Empty, ``None`` close sentinel) whose
  per-tenant lanes drain round-robin (FairCallQueue.java:214
  ``MultiplexedProcessor``), so the coalescer queues in
  server/write_pipeline.py and server/read_plane.py serve a light tenant's
  items interleaved with — not behind — a flood.

The ambient-tenant contextvar (``bind_tenant`` / ``current_tenant``)
threads attribution through call stacks that cannot carry a parameter
(scheme.reconstruct → ReadCoalescer.fetch), mirroring how retry.py binds
deadlines.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
import time
from collections import deque
from queue import Empty  # the contract exception FairQueue.get raises

from hdrf_tpu.utils import fault_injection, metrics, retry, rollwin, tenants

_M = metrics.registry("qos")

# Sentinel distinct from None: the close protocol of the pipeline queues
# uses None as a real item (the stop sentinel), so "no item available"
# needs its own marker inside FairQueue.
_MISSING = object()


class ShedError(IOError):
    """Structured retryable admission refusal.

    ``retry_after_s`` is the server's hint for when a retry is likely to
    be admitted (bucket refill time or the service-estimate budget a
    deadline-shed retry would need).  Subclasses IOError so transports
    that fold server errors into IOError stay compatible; clients that
    recognize the type can honor the hint instead of blind backoff."""

    def __init__(self, msg: str, retry_after_s: float = 0.0,
                 tenant: str | None = None, op: str | None = None):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)
        self.tenant = tenant
        self.op = op


# ------------------------------------------------------- tenant attribution

_tenant_var: contextvars.ContextVar = contextvars.ContextVar(
    "hdrf_qos_tenant", default=None)


@contextlib.contextmanager
def bind_tenant(tenant: str | None):
    """Make ``tenant`` ambient for the with-block (reset on exit)."""
    token = _tenant_var.set(tenant)
    try:
        yield
    finally:
        _tenant_var.reset(token)


def current_tenant() -> str | None:
    return _tenant_var.get()


# The control-lane tenant: background bulk work (stripe repair gathers,
# demote pushes, scrubber decode checks — server/coded_exchange.py) binds
# this sentinel so the admission gate recognizes it structurally: never
# shed, never debited against a foreground tenant's bucket, never fed to
# the deadline estimator.  The queue-side face of the same idea is
# FairQueue's control lane (FairCallQueue.java:214's control-priority
# analog); pacing comes from the balance throttle instead of admission.
BACKGROUND_TENANT = "__background__"


@contextlib.contextmanager
def background():
    """Bind the background control lane for the with-block: every op
    inside admits as :data:`BACKGROUND_TENANT` (auditable via the
    ``qos.admit`` fault point) and can never shed foreground traffic."""
    with bind_tenant(BACKGROUND_TENANT):
        yield


def is_background(tenant: str | None = None) -> bool:
    """Is ``tenant`` (default: the ambient one) the control lane?"""
    t = tenant if tenant is not None else current_tenant()
    return t == BACKGROUND_TENANT


# --------------------------------------------------- deficit token buckets


class TenantBucket:
    """Non-blocking deficit token bucket for one tenant.

    Unlike throttler.Throttler (which parks the caller), ``try_admit``
    answers immediately: 0.0 when the bucket is positive, else the seconds
    until it refills past zero — the shed's retry-after hint.  ``charge``
    debits actual bytes AFTER the op and may drive the level negative
    (deficit), so a tenant that burst past its budget pays the overdraft
    before its next admit."""

    def __init__(self, rate_bytes_s: float, burst_bytes: float,
                 clock=time.monotonic):
        self.rate = float(rate_bytes_s)
        self.burst = float(burst_bytes)
        self._clock = clock
        self._level = self.burst
        self._last = clock()

    def _refill(self) -> None:
        now = self._clock()
        self._level = min(self._level + (now - self._last) * self.rate,
                          self.burst)
        self._last = now

    def try_admit(self) -> float:
        """0.0 = admitted; else seconds until the level turns positive."""
        self._refill()
        if self._level > 0:
            return 0.0
        return (-self._level) / self.rate if self.rate > 0 else 1.0

    def charge(self, nbytes: int) -> None:
        self._refill()
        self._level -= float(nbytes)

    @property
    def level(self) -> float:
        self._refill()
        return self._level


class AdmissionController:
    """The DN-wide admission gate shared by the write and read planes.

    ``admit(tenant, op)`` raises :class:`ShedError` when either
    (a) the tenant's token bucket is in deficit (``rate_mb_s`` > 0), or
    (b) an ambient deadline's remaining budget cannot cover the rolling-p95
    service estimate for ``op`` times ``shed_p95_mult`` — the op would
    time out mid-pipeline anyway, so refuse it before it holds a slot.
    ``charge`` books the op's actual bytes and service latency afterward.

    The service estimator requires ``_MIN_SAMPLES`` observations per op
    before deadline-shedding trusts it (a cold window must not shed)."""

    _MIN_SAMPLES = 5

    def __init__(self, rate_mb_s: float = 0.0, burst_mb: float = 8.0,
                 shed_p95_mult: float = 3.0, clock=time.monotonic):
        self.rate_bytes_s = float(rate_mb_s) * (1 << 20)
        self.burst_bytes = float(burst_mb) * (1 << 20)
        self.shed_p95_mult = float(shed_p95_mult)
        self._clock = clock
        self._lock = threading.Lock()
        self._buckets: dict[str, TenantBucket] = {}
        # rolling per-op service times (seconds), 5-minute window — the
        # deadline-shed estimator (rollwin.py:117 WindowMap)
        self._svc = rollwin.WindowMap(window_s=300.0, maxlen=128)
        self._sheds: dict[str, int] = {}

    # -- internals ---------------------------------------------------------

    def _bucket(self, tenant: str) -> TenantBucket:
        b = self._buckets.get(tenant)
        if b is None:
            b = self._buckets[tenant] = TenantBucket(
                self.rate_bytes_s, self.burst_bytes, clock=self._clock)
        return b

    def _svc_p95_s(self, op: str) -> float | None:
        s = self._svc.summaries(now=self._clock()).get(op)
        if s is None or s["count"] < self._MIN_SAMPLES:
            return None
        return s["p95"]

    def _shed(self, tenant: str, op: str, why: str,
              retry_after_s: float) -> ShedError:
        fault_injection.point("qos.shed", tenant=tenant, op=op, why=why)
        _M.incr("sheds_total")
        _M.incr(f"tenant_sheds|tenant={tenant},op={op}")
        _M.observe("shed_retry_after_ms", retry_after_s * 1e3)
        with self._lock:
            self._sheds[tenant] = self._sheds.get(tenant, 0) + 1
        return ShedError(
            f"admission shed ({why}): tenant={tenant} op={op} "
            f"retry_after={retry_after_s:.3f}s",
            retry_after_s=retry_after_s, tenant=tenant, op=op)

    # -- the gate ----------------------------------------------------------

    def admit(self, tenant: str | None, op: str,
              deadline: retry.Deadline | None = None) -> None:
        """Admission check: raises ShedError, never blocks, charges
        nothing (see ``charge``)."""
        if tenant == BACKGROUND_TENANT:
            # control lane: background exchanges are paced by the balance
            # throttle, never shed, and never touch tenant buckets — but
            # they still pass the gate so the audit trail (fault point +
            # counter) proves what lane every op ran under
            fault_injection.point("qos.admit", tenant=tenant, op=op)
            _M.incr("background_admits")
            return
        tenant = tenant or tenants.DEFAULT_TENANT
        fault_injection.point("qos.admit", tenant=tenant, op=op)
        # (a) token bucket: only with a configured rate
        if self.rate_bytes_s > 0:
            with self._lock:
                wait = self._bucket(tenant).try_admit()
            if wait > 0:
                raise self._shed(tenant, op, "rate", wait)
        # (b) deadline-aware shed: budget cannot cover the p95 estimate
        d = deadline if deadline is not None else retry.current()
        if d is not None and self.shed_p95_mult > 0:
            p95 = self._svc_p95_s(op)
            if p95 is not None:
                need = p95 * self.shed_p95_mult
                if d.remaining() < need:
                    raise self._shed(tenant, op, "deadline", need)
        _M.incr("admits_total")

    def charge(self, tenant: str | None, op: str, nbytes: int = 0,
               latency_s: float | None = None) -> None:
        """Book the op's actual cost: bucket debit + service estimator."""
        if tenant == BACKGROUND_TENANT:
            return  # control lane: no bucket debit, no estimator samples
        tenant = tenant or tenants.DEFAULT_TENANT
        if self.rate_bytes_s > 0 and nbytes > 0:
            with self._lock:
                self._bucket(tenant).charge(nbytes)
        if latency_s is not None:
            self._svc.note(op, latency_s, now=self._clock())

    def note_latency(self, op: str, latency_s: float) -> None:
        """Feed the service estimator without a bucket debit."""
        self._svc.note(op, latency_s, now=self._clock())

    # -- observability -----------------------------------------------------

    def sheds_total(self) -> int:
        with self._lock:
            return sum(self._sheds.values())

    def shed_retry_after_p50_ms(self) -> float:
        with _M._lock:
            h = _M._histograms.get("shed_retry_after_ms")
            return h.quantile(0.5) if h is not None else 0.0

    def report(self) -> dict:
        """Heartbeat / read-plane-report face: shed totals per tenant."""
        with self._lock:
            per_tenant = dict(self._sheds)
        return {"sheds_total": sum(per_tenant.values()),
                "tenant_sheds": per_tenant,
                "rate_mb_s": self.rate_bytes_s / (1 << 20),
                "shed_p95_mult": self.shed_p95_mult}


# ------------------------------------------------------ weighted-fair queue


class FairQueue:
    """queue.Queue-compatible weighted-fair dequeue over per-tenant lanes.

    ``put(item)`` routes by ``item.tenant`` (``None``/missing → the
    default tenant lane); ``get`` drains lanes round-robin so each tenant
    with queued work gets one item per cycle regardless of lane depth
    (FairCallQueue.java:214).  A ``None`` item is the pipelines' close
    sentinel: it parks in a control lane served only once every data lane
    is empty, preserving the FIFO close contract (queued work drains
    before the stop)."""

    def __init__(self):
        self._cv = threading.Condition()
        self._lanes: dict[str, deque] = {}
        self._rr: deque[str] = deque()       # lane service order
        self._control: deque = deque()       # close sentinels

    def put(self, item) -> None:
        with self._cv:
            if item is None:
                self._control.append(item)
            else:
                t = getattr(item, "tenant", None) or tenants.DEFAULT_TENANT
                lane = self._lanes.get(t)
                if lane is None:
                    lane = self._lanes[t] = deque()
                    self._rr.append(t)
                lane.append(item)
            self._cv.notify()

    def _next_locked(self):
        for _ in range(len(self._rr)):
            t = self._rr[0]
            self._rr.rotate(-1)
            lane = self._lanes[t]
            if lane:
                return lane.popleft()
        if self._control:
            return self._control.popleft()
        return _MISSING

    def get(self, block: bool = True, timeout: float | None = None):
        with self._cv:
            end = (None if timeout is None
                   else time.monotonic() + max(timeout, 0.0))
            while True:
                item = self._next_locked()
                if item is not _MISSING:
                    return item
                if not block:
                    raise Empty
                if end is None:
                    self._cv.wait()
                else:
                    remain = end - time.monotonic()
                    if remain <= 0:
                        raise Empty
                    self._cv.wait(remain)

    def get_nowait(self):
        return self.get(block=False)

    def qsize(self) -> int:
        with self._cv:
            return (sum(len(v) for v in self._lanes.values())
                    + len(self._control))

    def depth_by_tenant(self) -> dict[str, int]:
        with self._cv:
            return {t: len(v) for t, v in self._lanes.items() if v}
