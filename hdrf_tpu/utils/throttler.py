"""Bandwidth throttling for background transfers
(util/DataTransferThrottler.java:28 analog, used by BlockSender's balancer
and re-replication legs in the reference).

Token bucket: ``throttle(n)`` blocks until ``n`` bytes of budget exist.
Budget accrues at ``bytes_per_s`` and is capped at one period's worth
(burst = period * rate, period 500 ms like the reference), so an idle
throttler doesn't bank unlimited credit.  Rate 0 disables (no locking on
the fast path).  ``set_rate`` applies live — the
``dfsadmin -setBalancerBandwidth`` path."""

from __future__ import annotations

import threading
import time

PERIOD_S = 0.5


class Throttler:
    def __init__(self, bytes_per_s: float = 0):
        self._rate = float(bytes_per_s)
        self._lock = threading.Lock()
        self._budget = 0.0
        self._last = time.monotonic()
        self.throttled_bytes = 0   # observability: bytes gated so far

    @property
    def rate(self) -> float:
        return self._rate

    def set_rate(self, bytes_per_s: float) -> None:
        self._rate = float(bytes_per_s)

    def throttle(self, nbytes: int) -> None:
        rate = self._rate
        if rate <= 0 or nbytes <= 0:
            return
        with self._lock:
            self.throttled_bytes += nbytes
            while True:
                now = time.monotonic()
                self._budget = min(self._budget + (now - self._last) * rate,
                                   rate * PERIOD_S)
                self._last = now
                if self._budget >= nbytes or self._budget >= rate * PERIOD_S:
                    # a request larger than the whole burst window passes
                    # once the bucket is full (it still paid the wait) —
                    # the reference caps the same way
                    self._budget -= nbytes
                    return
                need = (nbytes - self._budget) / rate
                time.sleep(min(need, PERIOD_S))
