"""Decayed rolling windows for health telemetry.

Re-expresses the windowing half of the reference daemons' health trackers:
HDFS's SlowPeerTracker.java:56 keeps per-peer latency reports in rolling
report windows that age out stale observations, and SlowDiskTracker rides
the same shape over per-volume IO latencies (DataNodeVolumeMetrics).  Here
one structure serves both: a bounded sample window whose entries expire
after ``window_s`` seconds, summarized as median/mean/max/count, with a
nearest-rank ``quantiles()`` surface (p50/p95/p99 for the per-tenant SLO
gauges) and a five-marker P² streaming estimator (:class:`P2Quantile`)
for cumulative series where even ``maxlen`` samples is too much state.

Deterministic by construction — the clock is injectable (tests drive
``now=``), expiry happens on access (no background thread), and the
summary is a pure function of the surviving samples.  The DataNode keeps
one ``WindowMap`` per telemetry family (peers, volumes) and ships the
summaries in its heartbeat payload (server/datanode.py) — the compact
SlowPeerReports analog the NameNode's outlier detector consumes
(utils/outlier.py).
"""

from __future__ import annotations

import statistics
import threading
import time
from collections import deque


class RollingWindow:
    """Bounded, time-decayed sample window.

    Samples older than ``window_s`` are pruned on access; at most
    ``maxlen`` samples are retained (oldest dropped first) so a hot
    observation point cannot grow the window without bound between
    heartbeats."""

    __slots__ = ("window_s", "maxlen", "_clock", "_samples")

    def __init__(self, window_s: float = 300.0, maxlen: int = 64,
                 clock=time.monotonic):
        self.window_s = window_s
        self.maxlen = maxlen
        self._clock = clock
        self._samples: deque[tuple[float, float]] = deque(maxlen=maxlen)

    def add(self, value: float, now: float | None = None) -> None:
        t = self._clock() if now is None else now
        self._samples.append((t, float(value)))

    def _prune(self, now: float) -> None:
        cutoff = now - self.window_s
        while self._samples and self._samples[0][0] < cutoff:
            self._samples.popleft()

    def values(self, now: float | None = None) -> list[float]:
        t = self._clock() if now is None else now
        self._prune(t)
        return [v for _, v in self._samples]

    def summary(self, now: float | None = None) -> dict | None:
        """{"median","mean","max","p95","count"} over live samples, or None
        when every sample has decayed out.  p95 is the nearest-rank upper
        quantile — the hedged mirror legs' trigger statistic (a pure
        function of the surviving samples, so deterministic under an
        injected clock like the rest of the summary)."""
        vs = self.values(now)
        if not vs:
            return None
        ranked = sorted(vs)
        # nearest-rank: ceil(0.95 * n) - 1, clamped to the last sample
        p95 = ranked[min(len(ranked) - 1, max(0, -(-len(ranked) * 95 // 100) - 1))]
        return {"median": statistics.median(vs),
                "mean": sum(vs) / len(vs),
                "max": max(vs),
                "p95": p95,
                "count": len(vs)}

    def quantiles(self, pcts: tuple[int, ...] = (50, 95, 99),
                  now: float | None = None) -> dict | None:
        """{"p50","p95","p99",...} over live samples by the same
        nearest-rank rule ``summary()`` uses for p95 (so ``quantiles((95,))
        == {"p95": summary()["p95"]}`` by construction), or None when the
        window is empty.  Memory stays bounded by ``maxlen`` — this is the
        rolling per-tenant p50/p95/p99 surface; for unbounded cumulative
        streams use :class:`P2Quantile` instead."""
        vs = self.values(now)
        if not vs:
            return None
        ranked = sorted(vs)
        n = len(ranked)
        return {f"p{p}": ranked[min(n - 1, max(0, -(-n * p // 100) - 1))]
                for p in pcts}


class P2Quantile:
    """Bounded-memory streaming quantile estimator (the P² algorithm,
    Jain & Chlamtac 1985): five markers tracked in O(1) memory regardless
    of stream length — the cumulative-series complement to the decayed
    window's exact nearest-rank.  Exact (nearest-rank) below five samples;
    marker-interpolated above.  Deterministic: a pure function of the
    observation sequence, no clock involved."""

    __slots__ = ("q", "_h", "_n", "_ns", "_dns", "count")

    def __init__(self, q: float):
        if not 0.0 < q < 1.0:
            raise ValueError(f"quantile must be in (0, 1): {q}")
        self.q = q
        self.count = 0
        self._h: list[float] = []       # marker heights (first 5: raw samples)
        self._n = [0.0, 1.0, 2.0, 3.0, 4.0]            # actual positions
        self._ns = [0.0, 2 * q, 4 * q, 2 + 2 * q, 4.0]  # desired positions
        self._dns = [0.0, q / 2, q, (1 + q) / 2, 1.0]   # desired increments

    def add(self, x: float) -> None:
        self.count += 1
        h = self._h
        if len(h) < 5:
            h.append(float(x))
            h.sort()
            return
        n, ns, dns = self._n, self._ns, self._dns
        if x < h[0]:
            h[0] = float(x)
            k = 0
        elif x >= h[4]:
            h[4] = float(x)
            k = 3
        else:
            k = 3
            for i in range(1, 5):
                if x < h[i]:
                    k = i - 1
                    break
        for i in range(k + 1, 5):
            n[i] += 1.0
        for i in range(5):
            ns[i] += dns[i]
        for i in range(1, 4):
            d = ns[i] - n[i]
            if ((d >= 1.0 and n[i + 1] - n[i] > 1.0)
                    or (d <= -1.0 and n[i - 1] - n[i] < -1.0)):
                s = 1.0 if d > 0 else -1.0
                hp = self._parabolic(i, s)
                if not h[i - 1] < hp < h[i + 1]:
                    # parabolic prediction left the bracket: linear fallback
                    j = i + int(s)
                    hp = h[i] + s * (h[j] - h[i]) / (n[j] - n[i])
                h[i] = hp
                n[i] += s

    def _parabolic(self, i: int, s: float) -> float:
        h, n = self._h, self._n
        return h[i] + s / (n[i + 1] - n[i - 1]) * (
            (n[i] - n[i - 1] + s) * (h[i + 1] - h[i]) / (n[i + 1] - n[i])
            + (n[i + 1] - n[i] - s) * (h[i] - h[i - 1]) / (n[i] - n[i - 1]))

    def value(self) -> float:
        """Current estimate; nearest-rank over the raw samples while fewer
        than five have arrived, 0.0 for an empty stream."""
        h = self._h
        if not h:
            return 0.0
        if len(h) < 5:
            k = len(h)
            return h[min(k - 1, max(0, -(-k * int(self.q * 100) // 100) - 1))]
        return self._h[2]


class WindowMap:
    """Keyed RollingWindows sharing one parameter set — the per-peer /
    per-volume maps the DataNode aggregates heartbeat summaries from.
    Thread-safe: observation points (xceiver threads, the volume checker)
    and the heartbeat loop touch it concurrently."""

    def __init__(self, window_s: float = 300.0, maxlen: int = 64,
                 clock=time.monotonic):
        self.window_s = window_s
        self.maxlen = maxlen
        self._clock = clock
        self._lock = threading.Lock()
        self._wins: dict = {}

    def note(self, key, value: float, now: float | None = None) -> None:
        with self._lock:
            w = self._wins.get(key)
            if w is None:
                w = self._wins[key] = RollingWindow(
                    self.window_s, self.maxlen, self._clock)
            w.add(value, now=now)

    def summaries(self, now: float | None = None) -> dict:
        """key -> summary dict for every key with live samples; fully
        decayed keys are dropped from the map (a peer that stopped being
        written to ages out of the reports, SlowPeerTracker semantics)."""
        out = {}
        with self._lock:
            for key in list(self._wins):
                s = self._wins[key].summary(now)
                if s is None:
                    del self._wins[key]
                else:
                    out[key] = s
        return out
