"""Decayed rolling windows for health telemetry.

Re-expresses the windowing half of the reference daemons' health trackers:
HDFS's SlowPeerTracker.java:56 keeps per-peer latency reports in rolling
report windows that age out stale observations, and SlowDiskTracker rides
the same shape over per-volume IO latencies (DataNodeVolumeMetrics).  Here
one structure serves both: a bounded sample window whose entries expire
after ``window_s`` seconds, summarized as median/mean/max/count.

Deterministic by construction — the clock is injectable (tests drive
``now=``), expiry happens on access (no background thread), and the
summary is a pure function of the surviving samples.  The DataNode keeps
one ``WindowMap`` per telemetry family (peers, volumes) and ships the
summaries in its heartbeat payload (server/datanode.py) — the compact
SlowPeerReports analog the NameNode's outlier detector consumes
(utils/outlier.py).
"""

from __future__ import annotations

import statistics
import threading
import time
from collections import deque


class RollingWindow:
    """Bounded, time-decayed sample window.

    Samples older than ``window_s`` are pruned on access; at most
    ``maxlen`` samples are retained (oldest dropped first) so a hot
    observation point cannot grow the window without bound between
    heartbeats."""

    __slots__ = ("window_s", "maxlen", "_clock", "_samples")

    def __init__(self, window_s: float = 300.0, maxlen: int = 64,
                 clock=time.monotonic):
        self.window_s = window_s
        self.maxlen = maxlen
        self._clock = clock
        self._samples: deque[tuple[float, float]] = deque(maxlen=maxlen)

    def add(self, value: float, now: float | None = None) -> None:
        t = self._clock() if now is None else now
        self._samples.append((t, float(value)))

    def _prune(self, now: float) -> None:
        cutoff = now - self.window_s
        while self._samples and self._samples[0][0] < cutoff:
            self._samples.popleft()

    def values(self, now: float | None = None) -> list[float]:
        t = self._clock() if now is None else now
        self._prune(t)
        return [v for _, v in self._samples]

    def summary(self, now: float | None = None) -> dict | None:
        """{"median","mean","max","p95","count"} over live samples, or None
        when every sample has decayed out.  p95 is the nearest-rank upper
        quantile — the hedged mirror legs' trigger statistic (a pure
        function of the surviving samples, so deterministic under an
        injected clock like the rest of the summary)."""
        vs = self.values(now)
        if not vs:
            return None
        ranked = sorted(vs)
        # nearest-rank: ceil(0.95 * n) - 1, clamped to the last sample
        p95 = ranked[min(len(ranked) - 1, max(0, -(-len(ranked) * 95 // 100) - 1))]
        return {"median": statistics.median(vs),
                "mean": sum(vs) / len(vs),
                "max": max(vs),
                "p95": p95,
                "count": len(vs)}


class WindowMap:
    """Keyed RollingWindows sharing one parameter set — the per-peer /
    per-volume maps the DataNode aggregates heartbeat summaries from.
    Thread-safe: observation points (xceiver threads, the volume checker)
    and the heartbeat loop touch it concurrently."""

    def __init__(self, window_s: float = 300.0, maxlen: int = 64,
                 clock=time.monotonic):
        self.window_s = window_s
        self.maxlen = maxlen
        self._clock = clock
        self._lock = threading.Lock()
        self._wins: dict = {}

    def note(self, key, value: float, now: float | None = None) -> None:
        with self._lock:
            w = self._wins.get(key)
            if w is None:
                w = self._wins[key] = RollingWindow(
                    self.window_s, self.maxlen, self._clock)
            w.add(value, now=now)

    def summaries(self, now: float | None = None) -> dict:
        """key -> summary dict for every key with live samples; fully
        decayed keys are dropped from the map (a peer that stopped being
        written to ages out of the reports, SlowPeerTracker semantics)."""
        out = {}
        with self._lock:
            for key in list(self._wins):
                s = self._wins[key].summary(now)
                if s is None:
                    del self._wins[key]
                else:
                    out[key] = s
        return out
