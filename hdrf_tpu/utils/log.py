"""Structured logging for the daemons.

Equivalent of the reference's log4j usage (``LOG.info`` in DataNode.java:499,
DataDeduplicator.java and every daemon main): leveled, named loggers with a
machine-parseable option.  Two output formats, selected by env:

- ``HDRF_LOG_FORMAT=text`` (default): ``ts LEVEL name: event k=v ...``
- ``HDRF_LOG_FORMAT=json``: one JSON object per line (log-shipper friendly)

``HDRF_LOG_LEVEL`` picks the threshold (debug|info|warning|error, default
info).  Loggers default to stderr so daemon stdout stays a clean
operator/handshake channel — startup banners that tooling greps (the
``listening on host:port`` contract ``spawn_local_worker`` parses) pass
``stream=sys.stdout`` explicitly and keep that substring in BOTH formats.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Any, TextIO

_LEVELS = {"debug": 10, "info": 20, "warning": 30, "error": 40}
_lock = threading.Lock()


def _threshold() -> int:
    return _LEVELS.get(os.environ.get("HDRF_LOG_LEVEL", "info").lower(), 20)


class Logger:
    __slots__ = ("name", "_stream")

    def __init__(self, name: str, stream: TextIO | None = None) -> None:
        self.name = name
        self._stream = stream

    def _emit(self, level: str, event: str, fields: dict[str, Any]) -> None:
        if _LEVELS[level] < _threshold():
            return
        stream = self._stream if self._stream is not None else sys.stderr
        if os.environ.get("HDRF_LOG_FORMAT", "text").lower() == "json":
            line = json.dumps({"ts": round(time.time(), 3), "level": level,
                               "name": self.name, "event": event, **fields})
        else:
            kv = "".join(f" {k}={v}" for k, v in fields.items())
            line = (f"{time.strftime('%Y-%m-%dT%H:%M:%S')} {level.upper()} "
                    f"{self.name}: {event}{kv}")
        with _lock:
            print(line, file=stream, flush=True)

    def debug(self, event: str, **fields: Any) -> None:
        self._emit("debug", event, fields)

    def info(self, event: str, **fields: Any) -> None:
        self._emit("info", event, fields)

    def warning(self, event: str, **fields: Any) -> None:
        self._emit("warning", event, fields)

    def error(self, event: str, **fields: Any) -> None:
        self._emit("error", event, fields)


def get_logger(name: str, stream: TextIO | None = None) -> Logger:
    return Logger(name, stream)
