"""Resilience primitives: deadline budgets, capped backoff, circuit breakers.

The reference stack has these scattered across Hadoop IPC — RetryPolicies
(RetryPolicies.java:153 ``exponentialBackoffRetry``), the failover proxy's
retry loop (RetryInvocationHandler.java:88), and per-protocol socket
timeouts (DataNode.java:436 ``socketTimeout``) — and the fork's reduction
path has NONE (SURVEY.md §5: a hung codec stalls writes forever).  This
module is the one place hdrf_tpu's cross-daemon edges get their failure
policy from:

- :class:`Deadline` — an absolute time budget.  It propagates hop-by-hop
  as a REMAINING-SECONDS header (``_deadline`` on RPC kwargs / DT op
  fields, riding beside ``_trace``): wall clocks are not synchronized
  across hosts, so each hop rebinds the remaining budget against its own
  monotonic clock — the hrpc/gRPC deadline-propagation shape.  The active
  deadline is ambient (contextvar, like tracing's current span): servers
  bind the inbound header around the handler, clients stamp the remaining
  budget on outbound calls, so a client's 30 s budget bounds the whole
  client->NN->DN->worker chain.
- :func:`backoff_delays` — capped exponential backoff with FULL jitter
  (delay ~ U(0, min(cap, base*2^i)); the AWS-architecture-blog rule the
  reference approximates at RetryPolicies.java:153).
- :class:`CircuitBreaker` — consecutive-failure breaker:
  closed -> open after N failures, half-open single probe after the reset
  timeout, re-close on probe success (the Polly/Hystrix state machine the
  reference lacks entirely).  Clocks are injectable so tests drive state
  transitions without wall-clock sleeps (the utils/outlier.py convention).

Per-edge breakers live in a process-wide registry; their state/transition
counters are mirrored into the ``resilience`` metrics registry, so
utils/prom.py exposition (and bench.py's JSON line) export them with zero
extra wiring: ``hdrf_breaker_open_total``, ``hdrf_breaker_state{...}``.
"""

from __future__ import annotations

import contextlib
import contextvars
import queue
import random
import threading
import time
from typing import Any, Callable, Iterator

from hdrf_tpu.utils import metrics

_M = metrics.registry("resilience")

#: reserved header key — rides RPC kwargs and DT op fields beside ``_trace``
DEADLINE_KEY = "_deadline"


class DeadlineExceeded(TimeoutError):
    """The operation's time budget is exhausted (raised BEFORE issuing
    further network work, so a spent budget costs zero connect attempts)."""


class Deadline:
    """Absolute time budget against an injectable monotonic clock."""

    __slots__ = ("_expires", "_clock")

    def __init__(self, budget_s: float,
                 clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._expires = clock() + float(budget_s)

    def remaining(self) -> float:
        """Seconds left (never negative)."""
        return max(0.0, self._expires - self._clock())

    @property
    def expired(self) -> bool:
        return self._clock() >= self._expires

    def check(self, what: str = "operation") -> None:
        if self.expired:
            _M.incr("deadline_exceeded_total")
            raise DeadlineExceeded(f"{what}: deadline budget exhausted")

    def extend(self, extra_s: float) -> None:
        """Grow the budget (payload-scaled deadlines accrue per streamed
        MiB because stream sizes are only known as bytes arrive)."""
        self._expires += float(extra_s)

    def timeout(self, cap_s: float | None = None) -> float:
        """A socket/step timeout honoring both the budget and ``cap_s``."""
        rem = self.remaining()
        return rem if cap_s is None else min(rem, cap_s)

    def header(self) -> float:
        """The hop-by-hop wire form: remaining seconds (receivers rebind
        against their own clock, which is the decrement)."""
        return self.remaining()


_current_deadline: contextvars.ContextVar[Deadline | None] = \
    contextvars.ContextVar("hdrf_deadline", default=None)


def current() -> Deadline | None:
    """The ambient deadline, if any (the tracing.current_context analog)."""
    return _current_deadline.get()


def remaining_header() -> float | None:
    """Remaining-seconds header for outbound calls; None = no deadline."""
    d = _current_deadline.get()
    return None if d is None else d.header()


@contextlib.contextmanager
def bind(deadline: Deadline | None) -> Iterator[Deadline | None]:
    """Make ``deadline`` ambient for the body (None = explicitly unbound)."""
    tok = _current_deadline.set(deadline)
    try:
        yield deadline
    finally:
        _current_deadline.reset(tok)


@contextlib.contextmanager
def bind_remaining(remaining_s: float | None,
                   clock: Callable[[], float] = time.monotonic,
                   ) -> Iterator[Deadline | None]:
    """Rebind an inbound ``_deadline`` header (remaining seconds) against
    the local clock; no header = no ambient deadline for this handler."""
    if remaining_s is None:
        yield None
        return
    with bind(Deadline(float(remaining_s), clock=clock)) as d:
        yield d


def effective_budget(budget_s: float) -> float:
    """Clamp a local per-op budget by the ambient deadline (a hop may
    never outlive the end-to-end budget it inherited)."""
    d = _current_deadline.get()
    return budget_s if d is None else min(budget_s, d.remaining())


def backoff_delays(attempts: int, base_s: float = 0.05, cap_s: float = 2.0,
                   rng: random.Random | None = None) -> Iterator[float]:
    """Capped exponential backoff with full jitter: attempt i sleeps
    U(0, min(cap_s, base_s * 2**i)).  Yields ``attempts`` delays."""
    rng = rng or random
    for i in range(attempts):
        yield rng.uniform(0.0, min(cap_s, base_s * (2.0 ** i)))


def call_with_retries(fn: Callable[[], Any], attempts: int = 3,
                      retry_on: tuple = (ConnectionError, OSError),
                      base_s: float = 0.05, cap_s: float = 2.0,
                      sleep: Callable[[float], None] = time.sleep,
                      rng: random.Random | None = None,
                      on_retry: Callable[[Exception], None] | None = None,
                      ) -> Any:
    """Run ``fn`` with capped-exponential-full-jitter retries, honoring the
    ambient deadline: a spent budget raises :class:`DeadlineExceeded`
    instead of sleeping into it."""
    last: Exception | None = None
    delays = backoff_delays(max(0, attempts - 1), base_s, cap_s, rng)
    for attempt in range(attempts):
        d = _current_deadline.get()
        if d is not None:
            d.check("retry loop")
        try:
            return fn()
        except retry_on as e:  # type: ignore[misc]
            last = e
            _M.incr("retries_total")
            if on_retry is not None:
                on_retry(e)
        if attempt < attempts - 1:
            delay = next(delays)
            if d is not None:
                delay = min(delay, d.remaining())
            if delay > 0:
                sleep(delay)
    raise last  # type: ignore[misc]


class QuorumFailed(IOError):
    """A hedged fan-out could not land its quorum: fewer than k legs
    succeeded after every launched leg (primaries + hedges) resolved or
    the overall budget expired.  ``errors`` holds (leg_index, exception)
    pairs for per-leg attribution."""

    def __init__(self, msg: str, errors: list | None = None):
        super().__init__(msg)
        self.errors = errors or []


def hedged_quorum(primaries: list, hedges: list, k: int,
                  hedge_after_s: float, timeout_s: float | None = None,
                  on_hedge: Callable[[], None] | None = None,
                  clock: Callable[[], float] = time.monotonic):
    """Hedged-call fan-out with an any-k quorum ack (the coded mirror
    plane's scheduling core; the "defer hedge until p95" discipline of
    the tied-requests design the reference's pipeline lacks entirely —
    SURVEY.md §0 fact 3, DataStreamer.java:765 forwards serially).

    Launches every ``primaries`` thunk concurrently.  The ``hedges``
    thunks launch when EITHER (a) any primary leg fails (fail-fast: a
    dead peer or open breaker should not burn the hedge timer) or (b)
    ``hedge_after_s`` elapses with fewer than k successes (straggler).
    Returns ``(wins, errors, hedged)`` as soon as k legs succeed —
    stragglers keep running on their daemon threads and resolve off the
    caller's critical path.  ``wins``/``errors`` are (leg_index, payload)
    pairs; hedge legs are indexed after the primaries.  Raises
    :class:`QuorumFailed` when k successes become impossible, and honors
    the ambient deadline through ``effective_budget``.
    """
    results: queue.Queue = queue.Queue()

    def _run(idx: int, fn: Callable[[], Any]) -> None:
        try:
            results.put((idx, True, fn()))
        except Exception as e:  # noqa: BLE001 — resolved at the quorum
            results.put((idx, False, e))

    for i, fn in enumerate(primaries):
        threading.Thread(target=_run, args=(i, fn), daemon=True,
                         name=f"hedge-leg-{i}").start()
    total = len(primaries)
    hedged = False

    def _launch_hedges() -> None:
        nonlocal total, hedged
        if hedged or not hedges:
            return
        hedged = True
        _M.incr("hedges_fired_total")
        if on_hedge is not None:
            on_hedge()
        for j, fn in enumerate(hedges):
            threading.Thread(target=_run, args=(len(primaries) + j, fn),
                             daemon=True,
                             name=f"hedge-leg-h{j}").start()
        total += len(hedges)

    overall = Deadline(effective_budget(
        timeout_s if timeout_s is not None else 60.0), clock=clock)
    hedge_at = clock() + max(0.0, float(hedge_after_s))
    wins: list = []
    errors: list = []
    while len(wins) < k:
        if len(wins) + len(errors) >= total:
            if hedged or not hedges:
                break  # every launched leg resolved; quorum unreachable
            _launch_hedges()
            continue
        wait = overall.remaining()
        if not hedged and hedges:
            wait = min(wait, max(0.0, hedge_at - clock()))
        try:
            idx, ok, payload = results.get(timeout=max(wait, 0.001))
        except queue.Empty:
            if not hedged and hedges and clock() >= hedge_at:
                _launch_hedges()
                continue
            if overall.expired:
                break
            continue
        if ok:
            wins.append((idx, payload))
        else:
            errors.append((idx, payload))
            _launch_hedges()  # fail-fast: don't wait out the timer
    if len(wins) < k:
        _M.incr("quorum_failures_total")
        raise QuorumFailed(
            f"hedged quorum missed: {len(wins)}/{k} legs landed "
            f"({len(errors)} failed)", errors)
    return wins, errors, hedged


class BreakerOpen(IOError):
    """Fail-fast refusal: the edge's breaker is open (no connect attempt
    was made — callers fall straight into their degraded path)."""


class CircuitBreaker:
    """Consecutive-failure circuit breaker with a single half-open probe.

    closed --N consecutive failures--> open --reset_s elapsed--> half-open
    (exactly one caller admitted as the probe) --success--> closed /
    --failure--> open again.  ``clock`` is injectable so tests drive every
    transition deterministically.
    """

    _STATE_NUM = {"closed": 0, "half_open": 1, "open": 2}

    def __init__(self, name: str, failure_threshold: int = 3,
                 reset_s: float = 5.0,
                 clock: Callable[[], float] = time.monotonic):
        self.name = name
        self.failure_threshold = max(1, int(failure_threshold))
        self.reset_s = float(reset_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = "closed"
        self._failures = 0          # consecutive
        self._opened_at = 0.0
        self._probe_inflight = False
        self._export()

    # ------------------------------------------------------------- queries

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open()
            return self._state

    def allow(self) -> bool:
        """May a call proceed?  open = no; half-open admits ONE probe."""
        with self._lock:
            self._maybe_half_open()
            if self._state == "closed":
                return True
            if self._state == "half_open" and not self._probe_inflight:
                self._probe_inflight = True
                _M.incr("breaker_probes_total")
                return True
            _M.incr("breaker_rejections_total")
            return False

    def check(self) -> None:
        """``allow`` that raises :class:`BreakerOpen` instead."""
        if not self.allow():
            raise BreakerOpen(f"circuit breaker '{self.name}' is open")

    # ----------------------------------------------------------- outcomes

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probe_inflight = False
            if self._state != "closed":
                self._state = "closed"
                _M.incr("breaker_close_total")
            self._export()

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            was = self._state
            if was == "half_open" or (was == "closed" and
                                      self._failures
                                      >= self.failure_threshold):
                self._state = "open"
                self._opened_at = self._clock()
                self._probe_inflight = False
                if was != "open":
                    _M.incr("breaker_open_total")
            self._export()

    # ----------------------------------------------------------- internals

    def _maybe_half_open(self) -> None:
        """Caller holds the lock."""
        if self._state == "open" \
                and self._clock() - self._opened_at >= self.reset_s:
            self._state = "half_open"
            self._probe_inflight = False
            self._export()

    def _export(self) -> None:
        """Caller holds the lock.  Gauges keep per-edge state visible in
        /prom; the transition counters above are family-wide."""
        _M.gauge(f"breaker_state.{self.name}",
                 self._STATE_NUM[self._state])


_breakers: dict[str, CircuitBreaker] = {}
_breakers_lock = threading.Lock()


def breaker(name: str, failure_threshold: int = 3, reset_s: float = 5.0,
            clock: Callable[[], float] = time.monotonic) -> CircuitBreaker:
    """Process-wide per-edge breaker registry (one breaker per edge name,
    e.g. ``dn-0->worker``); parameters apply only on first creation."""
    with _breakers_lock:
        b = _breakers.get(name)
        if b is None:
            b = _breakers[name] = CircuitBreaker(
                name, failure_threshold=failure_threshold,
                reset_s=reset_s, clock=clock)
        return b


def all_breakers() -> dict[str, CircuitBreaker]:
    with _breakers_lock:
        return dict(_breakers)


def reset_breakers() -> None:
    """Drop every registered breaker (test isolation)."""
    with _breakers_lock:
        _breakers.clear()
