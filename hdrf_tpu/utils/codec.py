"""Shared block-codec helpers (one place for codec name -> implementation).

Used by both the container store's seal stage (the reference's LZ4-on-rollover,
DataDeduplicator.java:770-781) and the compress-only reduction schemes (the
reference's stream-codec modes, BlockReceiver.java:822-866).
"""

from __future__ import annotations

import zlib

CODEC_IDS = {"none": 0, "lz4": 1, "zstd": 2, "gzip": 3, "snappy": 4}
CODEC_NAMES = {v: k for k, v in CODEC_IDS.items()}


def compress(codec: str, data: bytes) -> bytes:
    if codec == "lz4":
        from hdrf_tpu import native

        return native.lz4_compress(data)
    if codec == "zstd":
        import zstandard

        return zstandard.ZstdCompressor(level=1).compress(data)
    if codec == "gzip":
        return zlib.compress(data, 1)
    if codec == "snappy":
        import snappy  # optional dep (the reference's mode 0; absent -> gated)

        return snappy.compress(data)
    if codec == "none":
        return data
    raise KeyError(f"unknown codec {codec!r}")


def decompress(codec: str, data: bytes, usize: int) -> bytes:
    if codec == "lz4":
        from hdrf_tpu import native

        return native.lz4_decompress(data, usize)
    if codec == "zstd":
        import zstandard

        return zstandard.ZstdDecompressor().decompress(data, max_output_size=usize)
    if codec == "gzip":
        return zlib.decompress(data)
    if codec == "snappy":
        import snappy

        return snappy.decompress(data)
    if codec == "none":
        return data
    raise KeyError(f"unknown codec {codec!r}")


def available(codec: str) -> bool:
    try:
        compress(codec, b"x")
        return True
    except (ImportError, KeyError):
        return False
