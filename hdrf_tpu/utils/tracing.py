"""Lightweight distributed tracing.

Equivalent of the reference's HTrace integration: each daemon owns a Tracer
(DataNode.java:402-407), spans ride data-transfer op headers and are resumed
server-side (Receiver.java:94-98 ``continueTraceSpan``). Here a span is
``(trace_id, span_id, parent_id, name, t0, t1)``; the wire carries
``(trace_id, span_id)`` in op headers, and finished spans accumulate in a
bounded in-memory sink queryable from the HTTP status endpoint.

``chrome_trace`` assembles span snapshots (plus device-ledger events,
utils/device_ledger.py) into Chrome/Perfetto ``trace_event`` JSON — the
export format the gateway's ``/traces?format=chrome`` serves, playing the
role of the reference's HTrace span-receiver/Zipkin pipeline.
"""

from __future__ import annotations

import contextvars
import os
import struct
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Iterator
import contextlib


def _rand_id() -> int:
    return struct.unpack("<Q", os.urandom(8))[0] | 1


@dataclass
class Span:
    trace_id: int
    span_id: int
    parent_id: int
    name: str
    tracer: "Tracer | None" = None
    t0: float = field(default_factory=time.time)
    t1: float | None = None
    annotations: dict[str, Any] = field(default_factory=dict)

    def context(self) -> tuple[int, int]:
        """The bits that ride the wire (op header), cf. continueTraceSpan."""
        return (self.trace_id, self.span_id)

    def annotate(self, key: str, value: Any) -> None:
        self.annotations[key] = value

    def finish(self) -> None:
        self.t1 = time.time()
        if self.tracer is not None:
            self.tracer._record(self)


_current_span: contextvars.ContextVar[Span | None] = contextvars.ContextVar(
    "hdrf_current_span", default=None)


class Tracer:
    def __init__(self, name: str, max_spans: int = 4096) -> None:
        self.name = name
        self._sink: deque[Span] = deque(maxlen=max_spans)
        self._lock = threading.Lock()

    @contextlib.contextmanager
    def span(self, name: str, parent: tuple[int, int] | None = None) -> Iterator[Span]:
        """Open a span; ``parent`` is a wire context from an op header, if any."""
        cur = _current_span.get()
        if parent is not None:
            trace_id, parent_id = parent
        elif cur is not None:
            trace_id, parent_id = cur.trace_id, cur.span_id
        else:
            trace_id, parent_id = _rand_id(), 0
        sp = Span(trace_id, _rand_id(), parent_id, name, tracer=self)
        token = _current_span.set(sp)
        try:
            yield sp
        finally:
            _current_span.reset(token)
            sp.finish()

    def _record(self, span: Span) -> None:
        with self._lock:
            self._sink.append(span)

    def spans(self) -> list[Span]:
        with self._lock:
            return list(self._sink)

    def snapshot(self) -> list[dict[str, Any]]:
        return [
            {
                "trace_id": f"{s.trace_id:016x}", "span_id": f"{s.span_id:016x}",
                "parent_id": f"{s.parent_id:016x}", "name": s.name,
                "tracer": self.name,
                "start": s.t0, "duration_ms": None if s.t1 is None else (s.t1 - s.t0) * 1e3,
                "annotations": s.annotations,
            }
            for s in self.spans()
        ]


def current_context() -> tuple[int, int] | None:
    """Wire context of the active span, to stamp into outgoing op headers."""
    sp = _current_span.get()
    return None if sp is None else sp.context()


_tracers: dict[str, Tracer] = {}
_tracers_lock = threading.Lock()


def tracer(name: str) -> Tracer:
    """Process-wide named tracer (one per daemon/subsystem, like the per-daemon
    Tracer builds at DataNode.java:402-407)."""
    with _tracers_lock:
        t = _tracers.get(name)
        if t is None:
            t = _tracers[name] = Tracer(name)
        return t


def all_span_snapshots() -> list[dict[str, Any]]:
    """Finished spans from every tracer in this process (the per-process
    contribution to the gateway's cross-daemon /traces merge)."""
    with _tracers_lock:
        ts = list(_tracers.values())
    out: list[dict[str, Any]] = []
    for t in ts:
        out.extend(t.snapshot())
    return out


def chrome_trace(spans: list[dict[str, Any]],
                 ledger: list[dict[str, Any]] = (),
                 trace_id: str | None = None,
                 counters: list[dict[str, Any]] = ()) -> dict[str, Any]:
    """Assemble span snapshots + device-ledger events + profiler counter
    samples into Chrome ``trace_event`` format (the ``chrome://tracing`` /
    Perfetto JSON schema: ``M`` process-name metadata rows plus ``X``
    complete events with microsecond ``ts``/``dur``, plus ``C`` counter
    events rendered as Perfetto counter tracks — in-flight blocks,
    outstanding dispatches, WAL queue depth from utils/profiler.py).
    ``pid`` groups rows by tracer (spans) or originating process (ledger
    events / counter tracks); ``tid`` groups by trace so one write's causal
    chain reads as one row block.  ``args`` keeps the raw trace/span/parent
    ids, so parent-chain assembly survives the export.  Counter samples have
    no trace affinity, so a ``trace_id`` filter drops them."""
    if trace_id is not None:
        spans = [s for s in spans if s["trace_id"] == trace_id]
        ledger = [e for e in ledger if e.get("trace_id") == trace_id]
        counters = []
    events: list[dict[str, Any]] = []
    pids: dict[str, int] = {}

    def pid_of(group: str) -> int:
        if group not in pids:
            pids[group] = len(pids) + 1
            events.append({"ph": "M", "name": "process_name",
                           "pid": pids[group], "tid": 0,
                           "args": {"name": group}})
        return pids[group]

    for s in spans:
        if s.get("duration_ms") is None:
            continue
        events.append({
            "ph": "X", "name": s["name"], "cat": "span",
            "pid": pid_of(s.get("tracer", "?")),
            "tid": int(s["trace_id"][-8:], 16),
            "ts": s["start"] * 1e6, "dur": s["duration_ms"] * 1e3,
            "args": {"trace_id": s["trace_id"], "span_id": s["span_id"],
                     "parent_id": s["parent_id"],
                     **s.get("annotations", {})},
        })
    for e in ledger:
        tid = int(e["trace_id"][-8:], 16) if e.get("trace_id") else 0
        events.append({
            "ph": "X", "name": f"{e['kind']}:{e['op']}",
            "cat": "device_ledger",
            "pid": pid_of(f"device:{e.get('proc', '?')}"),
            "tid": tid, "ts": e["t0"] * 1e6,
            "dur": max(e.get("dur_us", 0.0), 1.0),
            "args": {"trace_id": e.get("trace_id"),
                     "span_id": e.get("span_id"), "batch": e.get("batch"),
                     "bytes": e.get("bytes"), "kind": e["kind"]},
        })
    for c in counters:
        events.append({
            "ph": "C", "name": c["name"], "cat": "profiler",
            "pid": pid_of(f"profiler:{c.get('proc', '?')}"), "tid": 0,
            "ts": c["t"] * 1e6,
            "args": {"value": c["value"]},
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}
