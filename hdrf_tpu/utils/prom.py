"""Prometheus text exposition for the metrics registries.

Equivalent of the reference's metrics2 sink layer (Hadoop's
``PrometheusMetricsSink`` rendering DataNodeMetrics.java:53 /
NameNodeMetrics.java:42 records as text exposition format): every
MetricsRegistry snapshot becomes ``# TYPE``-annotated families with the
registry name as a label.  Conventions:

- counters  -> ``hdrf_<key>_total{registry="r"} v``   (``_total`` appended
  once — keys already ending in ``_total`` are not doubled)
- gauges    -> ``hdrf_<key>{registry="r"} v``
- histograms-> ``hdrf_<key>_bucket{registry="r",le="<bound>"}`` CUMULATIVE
  counts (utils/metrics.py Histogram.snapshot), ``le="+Inf"`` == ``_count``,
  plus ``_sum`` and ``_count`` series.
- a metric key may carry a ``|k=v,k2=v2`` label suffix (e.g. the device
  ledger's per-op ``wait_us|op=sha256`` or the profiler's
  ``phase_us|phase=wal_commit``): the part before ``|`` names the family,
  the pairs become extra labels after ``registry`` — so labeled series
  share one family with their unlabeled aggregate.

One ``# TYPE`` line per family name across ALL registries (the format forbids
repeats), so same-named metrics from different registries share a family and
differ only in the ``registry`` label.
"""

from __future__ import annotations

import re
from typing import Any

_SAN = re.compile(r"[^a-zA-Z0-9_:]")


def _name(key: str) -> str:
    n = _SAN.sub("_", key)
    if n and n[0].isdigit():
        n = "_" + n
    return "hdrf_" + n


def _fmt(v: float) -> str:
    f = float(v)
    return repr(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def _split_key(key: str) -> tuple[str, str]:
    """Split ``base|k=v,k2=v2`` into (base, rendered extra labels)."""
    if "|" not in key:
        return key, ""
    base, _, rest = key.partition("|")
    parts = []
    for pair in rest.split(","):
        k, _, v = pair.partition("=")
        v = v.replace("\\", "\\\\").replace('"', '\\"')
        parts.append(f'{_SAN.sub("_", k)}="{v}"')
    return base, "," + ",".join(parts)


def render(snapshots: dict[str, Any]) -> str:
    """Render ``metrics.all_snapshots()``-shaped dicts as exposition text."""
    families: dict[str, tuple[str, list[str]]] = {}

    def fam(name: str, ptype: str) -> list[str]:
        got = families.get(name)
        if got is None:
            got = families[name] = (ptype, [])
        return got[1]

    for reg_name, snap in sorted(snapshots.items()):
        lbl = f'registry="{_SAN.sub("_", reg_name)}"'
        for key, v in sorted(snap.get("counters", {}).items()):
            raw, extra = _split_key(key)
            base = _name(raw)
            if not base.endswith("_total"):
                base += "_total"
            fam(base, "counter").append(f"{base}{{{lbl}{extra}}} {_fmt(v)}")
        for key, v in sorted(snap.get("gauges", {}).items()):
            raw, extra = _split_key(key)
            base = _name(raw)
            fam(base, "gauge").append(f"{base}{{{lbl}{extra}}} {_fmt(v)}")
        for key, h in sorted(snap.get("histograms", {}).items()):
            raw, extra = _split_key(key)
            base = _name(raw)
            rows = fam(base, "histogram")
            for bound, cum in h.get("buckets", []):
                rows.append(
                    f'{base}_bucket{{{lbl}{extra},le="{_fmt(bound)}"}} '
                    f"{_fmt(cum)}")
            rows.append(f'{base}_bucket{{{lbl}{extra},le="+Inf"}} '
                        f"{_fmt(h['count'])}")
            rows.append(f"{base}_sum{{{lbl}{extra}}} {_fmt(h.get('sum', 0.0))}")
            rows.append(f"{base}_count{{{lbl}{extra}}} {_fmt(h['count'])}")

    out: list[str] = []
    for name, (ptype, rows) in sorted(families.items()):
        out.append(f"# TYPE {name} {ptype}")
        out.extend(rows)
    return "\n".join(out) + "\n" if out else "\n"
