"""Device dispatch ledger: per-dispatch accounting for the TPU hot path.

Equivalent of the reference's per-stage GPU accounting (the utilization
counters DataDeduplicator.java:264-307 keeps around its chunk-scan calls and
the JNI timing in utilities.java:98-137) re-designed for the async XLA
dispatch model: through the dev tunnel ``block_until_ready`` acks at ENQUEUE
(PERF_NOTES.md), so completion can only be observed at the readback that
forces the result.  The ledger therefore records two moments the hot path
already has — dispatch (enqueue) and readback (the ``np.asarray`` /
``copy_to_host_async`` drain the caller performs anyway) — and never adds a
sync of its own.

Three kinds of records land in the ``device_ledger`` metrics registry and a
bounded event ring:

- ``dispatch(op, ...) -> token``: an enqueued device computation (counters
  ``dispatch_total``/``h2d_bytes_total``; first sighting of an ``(op, key)``
  shape key also counts ``compiles_total`` — the jit-cache-miss approximation).
- ``readback(token, ...)``: the forced completion of a prior dispatch
  (``readback_total``/``d2h_bytes_total``; histogram ``wait_us`` measures
  enqueue->forced-completion wall time, and the per-op family
  ``wait_us|op=<op>`` splits it by dispatch label on /prom; waits beyond
  the stall budget bump
  ``stall_total`` — the ~100 ms/dispatch norm vs the ~35 s VM stalls).
- ``transfer(kind, op, nbytes)``: a bare H2D/D2H copy with no compute
  (``h2d_bytes_total``/``d2h_bytes_total`` and a per-kind event).

Events carry the active trace context (utils/tracing.py) so span trees and
device work join into one timeline (the /traces chrome export); all event
fields are msgpack/JSON-safe scalars so they cross RPC unmodified.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from collections import deque
from typing import Any

from . import metrics, profiler, tracing

_M = metrics.registry("device_ledger")

# A readback wait past this is a stall (PERF_NOTES: awaited dispatches cost
# ~100 ms through the tunnel; the VM's write-burst throttling stalls ~35 s).
STALL_BUDGET_S = float(os.environ.get("HDRF_DISPATCH_BUDGET_S", "5.0"))

_RING_MAX = 4096
_ring: deque[dict[str, Any]] = deque(maxlen=_RING_MAX)
_lock = threading.Lock()
_seen_keys: set[tuple] = set()
_next_id = [0]
_PROC = f"{os.path.basename(sys.argv[0] or 'py')}:{os.getpid()}"


class _Pending:
    """Timing token returned by dispatch(); closed by readback()."""

    __slots__ = ("op", "t0_wall", "t0", "batch", "h2d", "counted")

    def __init__(self, op: str, batch: int, h2d: int,
                 counted: bool = True) -> None:
        self.op = op
        self.t0_wall = time.time()
        self.t0 = time.perf_counter()
        self.batch = batch
        self.h2d = h2d
        # Only counted tokens moved the outstanding-dispatches counter track
        # up at dispatch(); pending() tokens must not move it down.
        self.counted = counted


def _event(op: str, kind: str, *, t0: float, dur_us: float, batch: int,
           nbytes: int) -> int:
    ctx = tracing.current_context()
    ev = {
        "proc": _PROC, "op": op, "kind": kind, "t0": t0,
        "dur_us": round(dur_us, 1), "batch": batch, "bytes": nbytes,
        "trace_id": None if ctx is None else f"{ctx[0]:016x}",
        "span_id": None if ctx is None else f"{ctx[1]:016x}",
    }
    with _lock:
        _next_id[0] += 1
        ev["id"] = _next_id[0]
        _ring.append(ev)
    return ev["id"]


def dispatch(op: str, *, batch: int = 1, h2d_bytes: int = 0,
             key: tuple | None = None) -> _Pending:
    """Record an enqueued device computation; returns the timing token the
    matching ``readback`` closes.  ``key`` is a hashable shape signature —
    its first sighting counts as a compile event (jit cache miss)."""
    _M.incr("dispatch_total")
    _M.incr("dispatch_batch_total", batch)
    if h2d_bytes:
        _M.incr("h2d_bytes_total", h2d_bytes)
    if key is not None:
        k = (op, key)
        with _lock:
            fresh = k not in _seen_keys
            if fresh:
                _seen_keys.add(k)
        if fresh:
            _M.incr("compiles_total")
            _event(op, "compile", t0=time.time(), dur_us=0.0, batch=batch,
                   nbytes=0)
    # Enqueue marker: ring position (id) establishes dispatch ORDER, letting
    # tests pin pipeline structure — e.g. that the fused CDC path enqueues
    # its SHA dispatches BEFORE the cut-table readback completes (one fewer
    # awaited boundary than the XLA prep -> host-select -> SHA shape).
    _event(op, "enqueue", t0=time.time(), dur_us=0.0, batch=batch,
           nbytes=h2d_bytes)
    profiler.note_device_dispatch()
    return _Pending(op, batch, h2d_bytes)


def pending(op: str, *, batch: int = 1) -> _Pending:
    """Timing token WITHOUT counting a dispatch — for aggregate readbacks
    whose constituent dispatches were already recorded individually."""
    return _Pending(op, batch, 0, counted=False)


def readback(tok: _Pending | None, *, d2h_bytes: int = 0) -> None:
    """Record the forced completion of ``tok``'s dispatch.  Call AFTER the
    caller's own forcing readback (np.asarray / block_until_ready on a
    host-bound value) — the ledger never forces device work itself."""
    if tok is None:
        return
    dur = time.perf_counter() - tok.t0
    _M.incr("readback_total")
    if d2h_bytes:
        _M.incr("d2h_bytes_total", d2h_bytes)
    _M.observe("wait_us", dur * 1e6)
    _M.observe(f"wait_us|op={tok.op}", dur * 1e6)
    if dur > STALL_BUDGET_S:
        _M.incr("stall_total")
        _event(tok.op, "stall", t0=tok.t0_wall, dur_us=dur * 1e6,
               batch=tok.batch, nbytes=d2h_bytes)
    ev_id = _event(tok.op, "dispatch", t0=tok.t0_wall, dur_us=dur * 1e6,
                   batch=tok.batch, nbytes=tok.h2d + d2h_bytes)
    profiler.note_device_wait(tok.op, tok.t0_wall, tok.t0_wall + dur,
                              event_id=ev_id, counted=tok.counted)


def transfer(kind: str, op: str, nbytes: int) -> None:
    """Record a bare transfer (kind ``h2d`` or ``d2h``) with no compute."""
    _M.incr(f"{kind}_bytes_total", nbytes)
    _M.incr(f"{kind}_transfer_total")
    _event(op, kind, t0=time.time(), dur_us=0.0, batch=1, nbytes=nbytes)


def events_snapshot(limit: int = _RING_MAX) -> list[dict[str, Any]]:
    """Newest-last copy of the event ring (msgpack/JSON-safe dicts)."""
    with _lock:
        evs = list(_ring)
    return evs[-limit:]


def stamp() -> dict[str, int]:
    """Cheap counter stamp for delta accounting across a bench round."""
    snap = _M.snapshot()["counters"]
    return {k: snap.get(k, 0) for k in
            ("dispatch_total", "readback_total", "compiles_total",
             "stall_total", "h2d_bytes_total", "d2h_bytes_total")}


def delta(before: dict[str, int]) -> dict[str, int]:
    """Counter movement since ``before`` (a ``stamp()`` result)."""
    now = stamp()
    return {k: now[k] - before.get(k, 0) for k in now}
