"""Stall watchdog: flags in-flight operations that exceed their budget.

Equivalent of the reference's slow-node/slow-disk detection
(DataNodeMetrics.java:557's SlowPeer reports and the ``/stacks`` servlet Hadoop's
HttpServer2 exposes for hung-daemon triage): a per-daemon background thread
scans the in-flight table every ``tick_s`` and, when an op has been running
past its budget, bumps ``stall_total`` on the daemon's registry, captures a
full thread-stack snapshot into a bounded ring (served by the ``/stacks``
endpoint), emits a structured log line, and fires the
``watchdog.stall`` fault-injection point so tests can observe the flag.

Budgets target the environment's two known pathologies (PERF_NOTES.md): the
~35 s VM write-burst stalls and device dispatches far over the ~100 ms norm.
Each stalled op is flagged ONCE (re-flagged only if still running after
another full budget), so a 35 s stall counts as one stall, not 35/tick.

Stall records carry the tracked op's active trace id (captured at track()
entry) and the phase the op's thread is in at flag time (utils/profiler.py
per-thread phase stacks), and a synthetic ``stall`` span lands in the
``watchdog`` tracer — so a VM stall is attributable to a specific
block/phase in /stacks AND visible on the chrome export timeline.
"""

from __future__ import annotations

import contextlib
import os
import sys
import threading
import time
import traceback
from collections import deque
from typing import Any, Iterator

from . import fault_injection, log, metrics, profiler, tracing

DEFAULT_BUDGET_S = float(os.environ.get("HDRF_STALL_BUDGET_S", "30.0"))


def thread_stacks() -> dict[str, list[str]]:
    """Formatted stacks of every live thread (the /stacks servlet body)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out: dict[str, list[str]] = {}
    for ident, frame in sys._current_frames().items():
        key = f"{names.get(ident, '?')}:{ident}"
        out[key] = traceback.format_stack(frame)
    return out


class StallWatchdog:
    """Tracks in-flight ops and flags the ones that exceed their budget."""

    def __init__(self, name: str, budget_s: float = DEFAULT_BUDGET_S,
                 tick_s: float | None = None,
                 registry: metrics.MetricsRegistry | None = None,
                 lock: Any | None = None) -> None:
        """``lock``: optional utils.lockprof.InstrumentedRLock — when set,
        each stall record also captures the CURRENT lock holder's identity,
        held-for duration and stack, so a lock convoy (N threads parked
        behind one slow holder) is diagnosable from /stacks instead of
        showing N identical waiter stacks and no culprit."""
        self.name = name
        self._profiled_lock = lock
        self.budget_s = budget_s
        self.tick_s = tick_s if tick_s is not None else min(
            max(budget_s / 4.0, 0.01), 2.0)
        self._reg = registry if registry is not None else metrics.registry(
            name)
        self._log = log.get_logger(f"watchdog.{name}")
        self._lock = threading.Lock()
        self._inflight: dict[int, dict[str, Any]] = {}
        self._next = 0
        self._stalls: deque[dict[str, Any]] = deque(maxlen=64)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "StallWatchdog":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name=f"watchdog-{self.name}", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    # ------------------------------------------------------------- tracking

    @contextlib.contextmanager
    def track(self, op: str, budget_s: float | None = None) -> Iterator[None]:
        """Wrap an operation; the scan thread flags it if it outlives its
        budget.  Zero-cost beyond one dict insert/remove."""
        ctx = tracing.current_context()
        ent = {"op": op, "t0": time.monotonic(),
               "budget": budget_s if budget_s is not None else self.budget_s,
               "flagged": 0.0, "thread": threading.get_ident(),
               "trace_id": None if ctx is None else f"{ctx[0]:016x}"}
        with self._lock:
            self._next += 1
            key = self._next
            self._inflight[key] = ent
        try:
            yield
        finally:
            with self._lock:
                self._inflight.pop(key, None)

    def _run(self) -> None:
        while not self._stop.wait(self.tick_s):
            self.scan()

    def scan(self, now: float | None = None) -> int:
        """One watchdog pass; returns how many ops were newly flagged.
        Public so tests can drive the check deterministically."""
        if now is None:
            now = time.monotonic()
        stalled: list[dict[str, Any]] = []
        with self._lock:
            for ent in self._inflight.values():
                ref = ent["flagged"] or ent["t0"]
                if now - ref > ent["budget"]:
                    ent["flagged"] = now
                    stalled.append(dict(ent))
        holder = self._lock_holder() if stalled else None
        for ent in stalled:
            elapsed = now - ent["t0"]
            # phase the stalled op's thread is in RIGHT NOW (cross-thread
            # probe — the scan thread is not the stalled thread)
            phase = profiler.thread_phase(ent.get("thread"))
            self._reg.incr("stall_total")
            rec = {"ts": time.time(), "daemon": self.name, "op": ent["op"],
                   "elapsed_s": round(elapsed, 3),
                   "budget_s": ent["budget"],
                   "trace_id": ent.get("trace_id"), "phase": phase,
                   "stacks": thread_stacks()}
            if holder is not None:
                rec["lock_holder"] = holder
            with self._lock:
                self._stalls.append(rec)
            self._log.warning("stall", op=ent["op"],
                              elapsed_s=round(elapsed, 3),
                              budget_s=ent["budget"],
                              trace_id=ent.get("trace_id"), phase=phase)
            fault_injection.point("watchdog.stall", daemon=self.name,
                                  op=ent["op"], elapsed_s=elapsed,
                                  trace_id=ent.get("trace_id"), phase=phase)
            self._stall_span(ent, elapsed, phase)
        return len(stalled)

    def _lock_holder(self) -> dict[str, Any] | None:
        """The profiled lock's current holder with its live stack — the
        convoy culprit a stall record would otherwise omit (the waiters'
        stacks all show the same acquire site)."""
        if self._profiled_lock is None:
            return None
        try:
            h = self._profiled_lock.holder()
        except Exception:  # noqa: BLE001 — diagnostics must never raise
            return None
        if h is None:
            return None
        h = dict(h)
        h["held_for_s"] = round(h.get("held_for_s", 0.0), 3)
        frame = sys._current_frames().get(h.get("thread"))
        if frame is not None:
            h["stack"] = traceback.format_stack(frame)
        return h

    def _stall_span(self, ent: dict[str, Any], elapsed: float,
                    phase: str | None) -> None:
        """Synthetic span covering the stalled window so the stall shows up
        on the chrome-export timeline next to the block's other spans
        (same trace id when the op carried one)."""
        try:
            tid = (int(ent["trace_id"], 16) if ent.get("trace_id")
                   else tracing._rand_id())
            sp = tracing.Span(tid, tracing._rand_id(), 0,
                              f"stall:{ent['op']}",
                              tracer=tracing.tracer("watchdog"),
                              t0=time.time() - elapsed)
            sp.annotate("daemon", self.name)
            sp.annotate("elapsed_s", round(elapsed, 3))
            if phase is not None:
                sp.annotate("phase", phase)
            sp.finish()
        except (ValueError, TypeError):
            pass  # malformed trace id: the rec/log/fault still carry it

    # ------------------------------------------------------------ introspect

    def inflight(self) -> list[dict[str, Any]]:
        now = time.monotonic()
        with self._lock:
            return [{"op": e["op"], "elapsed_s": round(now - e["t0"], 3),
                     "budget_s": e["budget"], "flagged": bool(e["flagged"])}
                    for e in self._inflight.values()]

    def stalls(self) -> list[dict[str, Any]]:
        """Recent stall records, stacks included (newest last)."""
        with self._lock:
            return list(self._stalls)

    def stall_count(self) -> int:
        return self._reg.counter("stall_total")
