"""Write-path critical-path profiler: phase-attributed block timelines.

The reference measures its write path with coarse per-op rate counters
(DataNodeMetrics.java:553-560 ``addWriteBlockOp``/``addPacketAckRoundTripTimeNanos``)
— enough to say *that* a write was slow, never *where* the time went.  This
module is the missing decomposition, re-designed for the one-vCPU DN host
whose only real overlaps are host-work-under-device-compute and
host-work-under-transport-waits (PERF_NOTES.md:round 4):

- Every block write opens a :class:`BlockTimeline` (ambient via contextvar,
  the bf1-buffer lifetime of BlockReceiver.java:877-897) into which named
  phase spans land — ``recv``, ``dedup_lookup``, ``wal_commit``,
  ``device_wait``, ``container_io``, ``mirror_stream``, ``ack`` — each a
  plain ``(phase, t0, t1, thread)`` tuple (one list append; no locks on the
  hot path, no syncs).  The device ledger (utils/device_ledger.py) feeds
  ``device_wait`` spans and event-id links at its existing readback hook, so
  host phases and device work join into one timeline.
- :func:`profile_spans` is the overlap accountant: it partitions a wall-clock
  window into four EXCLUSIVE classes — ``host_busy`` > ``device_busy`` >
  ``transport_wait`` > ``idle`` (priority order; host work always owns the
  single vCPU, so wait time under it is *hidden*, the desirable state) — and
  computes ``overlap_efficiency`` = hidden wait / hideable wait plus
  per-phase exclusive seconds, the numbers the gap-attribution table
  (tools/gap_report.py) and ROADMAP item 1's pipeline refactor are judged
  against.
- Counter tracks (in-flight blocks, outstanding dispatches, WAL queue depth)
  sample on every change into a bounded ring, rendered as Chrome ``C``
  events by tracing.chrome_trace for the /traces?format=chrome export.

Finished timelines observe per-phase latency histograms
(``phase_us|phase=<name>`` — utils/prom.py renders the ``|k=v`` key suffix
as extra labels) and overlap gauges into the ``write_profiler`` registry, so
every surface the observability spine already reaches (/prom, /metrics,
status_http.py, the gateway) serves them with no extra wiring.

The READ path (server/block_sender.py serve_read, the short-circuit server
and the EC degraded read) opens the same machinery via
:func:`read_timeline`: phases ``index_lookup``/``cache_probe``/
``container_decode`` (host), ``ec_gather``/``net_send`` (transport) and the
ledger-fed ``device_wait`` partition one serve's wall clock identically,
observing ``phase_us|op=read,phase=<name>`` histograms plus read-side
overlap gauges into the ``read_profiler`` registry — the serving-path twin
the reference never decomposes (DataNodeMetrics.java:553-560 counts read
ops, never where a read's time went).
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import sys
import threading
import time
from collections import deque
from typing import Any, Iterable, Iterator

from . import metrics, tracing

_M = metrics.registry("write_profiler")
_R = metrics.registry("read_profiler")

# Overlap classes, in wall-clock partition PRIORITY order (PERF_NOTES round
# 4: the 1-vCPU host is the scarce resource — an interval where host work
# runs counts host_busy even when device/transport waits are in flight;
# those waits are then HIDDEN, which is the state the pipeline wants).
HOST, DEVICE, TRANSPORT = "host", "device", "transport"
CLASSES = ("host_busy", "device_busy", "transport_wait", "idle")

PHASE_CLASS = {
    "recv": TRANSPORT, "mirror_stream": TRANSPORT, "ack": TRANSPORT,
    "dedup_lookup": HOST, "wal_commit": HOST, "container_io": HOST,
    "reduce_compute": HOST, "checksum": HOST, "buffer_assemble": HOST,
    "pipeline_submit": HOST,
    "device_wait": DEVICE,
    # Read-path phases (server/block_sender.py serve_read/read_logical):
    # index/cache/decode burn the single vCPU; stripe gathers and the
    # packet run to the client are network waits the host could hide.
    "index_lookup": HOST, "cache_probe": HOST, "container_decode": HOST,
    "ec_gather": TRANSPORT, "net_send": TRANSPORT,
    # A reader parked on the read coalescer's shared decode future
    # (server/read_plane.py): a hideable wait — the real decode burns the
    # vCPU under the LEAD reader's mirrored container_decode span, which
    # wins the interval's class, so this only attributes the queue/window
    # slack that nothing else covers.
    "decode_wait": TRANSPORT,
    # Control-plane RPC service-time phases (proto/rpc.py _serve_one):
    # frame/reply socket IO are transport waits; everything between them
    # is NN host work.  lock_wait is deliberately HOST, not transport —
    # the whole dispatch sits inside the covering ``handler`` span (HOST),
    # and the exclusive sweep resolves same-class overlaps by PHASE_ORDER,
    # so classifying it transport would hide every queued-on-the-namesystem
    # second under ``handler`` and the contention table would read clean.
    "frame_read": TRANSPORT, "reply": TRANSPORT,
    "dispatch_queue": HOST, "lock_wait": HOST, "locked": HOST,
    "serialize": HOST, "handler": HOST,
}

# Deterministic attribution order when several phases of the winning class
# overlap inside one elementary interval (rare: host phases are serial on
# this host) — first match wins.  Nested read phases (index_lookup inside a
# container_decode window) resolve to the innermost by listing it first.
PHASE_ORDER = ("device_wait", "wal_commit", "container_io", "dedup_lookup",
               "reduce_compute", "checksum", "buffer_assemble",
               "pipeline_submit", "index_lookup", "cache_probe",
               "container_decode",
               # RPC phases: lock_wait/locked win attribution inside the
               # covering ``handler`` window; handler last among them so it
               # only owns the time no finer phase explains.
               "lock_wait", "locked", "dispatch_queue", "serialize",
               "handler",
               "recv", "mirror_stream", "ack",
               "ec_gather", "decode_wait", "net_send", "frame_read", "reply")


def phase_class(name: str) -> str:
    """Overlap class of a phase; unknown names default to host work."""
    return PHASE_CLASS.get(name, HOST)


def _now() -> float:
    # Wall clock: phase spans must share a time base with tracing.Span.t0
    # and the device ledger's event t0 so one chrome export aligns them all.
    return time.time()


_PROC = f"{os.path.basename(sys.argv[0] or 'py')}:{os.getpid()}"

_RING_MAX = 1024          # finished timelines
_SPAN_RING_MAX = 65536    # raw spans (window_profile's source)
_COUNTER_RING_MAX = 8192  # counter-track samples

_lock = threading.Lock()
_timelines: deque["BlockTimeline"] = deque(maxlen=_RING_MAX)
_read_timelines: deque["BlockTimeline"] = deque(maxlen=_RING_MAX)
_span_ring: deque[tuple] = deque(maxlen=_SPAN_RING_MAX)
_counter_ring: deque[dict[str, Any]] = deque(maxlen=_COUNTER_RING_MAX)
_counters: dict[str, float] = {}
_counter_id = [0]
_thread_phase: dict[int, list[str]] = {}

_current: contextvars.ContextVar["BlockTimeline | None"] = \
    contextvars.ContextVar("hdrf_block_timeline", default=None)


# ------------------------------------------------------------ block timeline


class BlockTimeline:
    """Phase spans + device-ledger links for one block write."""

    __slots__ = ("block_id", "nbytes", "trace_id", "t0", "t1", "spans",
                 "ledger_ids")

    def __init__(self, block_id: int, nbytes: int = 0,
                 t0: float | None = None) -> None:
        self.block_id = block_id
        self.nbytes = nbytes
        ctx = tracing.current_context()
        self.trace_id = None if ctx is None else f"{ctx[0]:016x}"
        self.t0 = _now() if t0 is None else t0
        self.t1: float | None = None
        self.spans: list[tuple] = []          # (phase, t0, t1, thread)
        self.ledger_ids: list[int] = []       # device-ledger event ids

    def add_span(self, phase: str, t0: float, t1: float,
                 thread: int = 0) -> None:
        self.spans.append((phase, t0, t1, thread))

    def finish(self, t1: float | None = None) -> None:
        if self.t1 is None:
            self.t1 = _now() if t1 is None else t1

    def profile(self) -> dict[str, Any]:
        end = self.t1 if self.t1 is not None else _now()
        return profile_spans(self.spans, self.t0, end, nbytes=self.nbytes)

    def snapshot(self) -> dict[str, Any]:
        """JSON-safe dump (the gap_report/--input interchange shape)."""
        return {"block_id": self.block_id, "nbytes": self.nbytes,
                "trace_id": self.trace_id, "t0": self.t0, "t1": self.t1,
                "spans": [[p, a, b] for p, a, b, _ in self.spans],
                "ledger_ids": list(self.ledger_ids),
                "profile": self.profile()}


# --------------------------------------------------------- overlap accountant


def profile_spans(spans: Iterable, t0: float, t1: float,
                  nbytes: int = 0) -> dict[str, Any]:
    """Partition [t0, t1] into the four exclusive overlap classes and
    per-phase exclusive seconds via a boundary sweep.

    ``spans`` yields ``(phase, s0, s1)`` or ``(phase, s0, s1, thread)``.
    The class partition sums exactly to the wall clock (``idle`` is the
    remainder by construction).  ``overlap_efficiency`` = wait time hidden
    under host work / total device+transport wait time (1.0 when there was
    nothing to hide); ``attributed_frac`` = share of wall covered by at
    least one named phase (the >= 95% gap_report acceptance bar).
    """
    wall = max(t1 - t0, 0.0)
    classes = dict.fromkeys(CLASSES, 0.0)
    phases: dict[str, float] = {}
    hidden = hideable = 0.0
    events: list[tuple[float, int, str]] = []
    for sp in spans:
        name, s0, s1 = sp[0], max(sp[1], t0), min(sp[2], t1)
        if s1 > s0:
            events.append((s0, 1, name))
            events.append((s1, -1, name))
    events.sort(key=lambda e: e[0])

    active: dict[str, int] = {}
    cls_active = {HOST: 0, DEVICE: 0, TRANSPORT: 0}
    prev = t0
    i, n = 0, len(events)
    while i < n:
        t = events[i][0]
        if t > prev:
            dt = t - prev
            if cls_active[HOST] > 0:
                win, wc = "host_busy", HOST
            elif cls_active[DEVICE] > 0:
                win, wc = "device_busy", DEVICE
            elif cls_active[TRANSPORT] > 0:
                win, wc = "transport_wait", TRANSPORT
            else:
                win, wc = "idle", None
            classes[win] += dt
            if cls_active[DEVICE] > 0 or cls_active[TRANSPORT] > 0:
                hideable += dt
                if win == "host_busy":
                    hidden += dt
            if wc is not None:
                attr = None
                for name in PHASE_ORDER:
                    if active.get(name, 0) > 0 and phase_class(name) == wc:
                        attr = name
                        break
                if attr is None:  # phase outside the canonical order
                    for name in sorted(active):
                        if active[name] > 0 and phase_class(name) == wc:
                            attr = name
                            break
                if attr is not None:
                    phases[attr] = phases.get(attr, 0.0) + dt
            prev = t
        while i < n and events[i][0] == t:
            _, kind, name = events[i]
            active[name] = active.get(name, 0) + kind
            cls_active[phase_class(name)] += kind
            i += 1
    used = (classes["host_busy"] + classes["device_busy"]
            + classes["transport_wait"])
    classes["idle"] = wall - used  # exact partition by construction
    out = {
        "wall_s": wall,
        "classes": classes,
        "phases": phases,
        "hidden_wait_s": hidden,
        "hideable_wait_s": hideable,
        "overlap_efficiency": hidden / hideable if hideable > 0 else 1.0,
        "attributed_frac": used / wall if wall > 0 else 1.0,
    }
    if nbytes:
        out["bytes"] = nbytes
        out["mb_per_s"] = nbytes / wall / (1 << 20) if wall > 0 else 0.0
    return out


# --------------------------------------------------------------- ambient API


@contextlib.contextmanager
def block_timeline(block_id: int, nbytes: int = 0) -> Iterator[BlockTimeline]:
    """Open the ambient timeline for one block write; on exit the finished
    timeline lands in the ring and its per-phase histograms + overlap gauges
    are observed into the ``write_profiler`` registry."""
    tl = BlockTimeline(block_id, nbytes)
    tok = _current.set(tl)
    counter_add("inflight_blocks", 1)
    try:
        yield tl
    finally:
        _current.reset(tok)
        counter_add("inflight_blocks", -1)
        tl.finish()
        with _lock:
            _timelines.append(tl)
        _observe_finished(tl)


@contextlib.contextmanager
def read_timeline(block_id: int, nbytes: int = 0) -> Iterator[BlockTimeline]:
    """Open the ambient timeline for one block READ (serve_read /
    short-circuit serve / EC degraded read).  Same BlockTimeline machinery
    and exclusive-class partition as the write side — reconstruct code
    below it records ``index_lookup``/``container_decode``/``ec_gather``
    phases via the ordinary :func:`phase` ambient channel, and the device
    ledger's readback hook still lands ``device_wait`` spans — but finished
    timelines ring separately and observe into the ``read_profiler``
    registry as ``phase_us|op=read,phase=<name>`` histograms, so the read
    families sit next to the write families on /prom."""
    tl = BlockTimeline(block_id, nbytes)
    tok = _current.set(tl)
    counter_add("inflight_reads", 1)
    try:
        yield tl
    finally:
        _current.reset(tok)
        counter_add("inflight_reads", -1)
        tl.finish()
        with _lock:
            _read_timelines.append(tl)
        _observe_finished_read(tl)


def _observe_finished_read(tl: BlockTimeline) -> None:
    prof = tl.profile()
    for name, s in prof["phases"].items():
        _R.observe(f"phase_us|op=read,phase={name}", s * 1e6)
    _R.observe("read_wall_us", prof["wall_s"] * 1e6)
    _R.gauge("overlap_efficiency", prof["overlap_efficiency"])
    _R.gauge("attributed_frac", prof["attributed_frac"])
    _R.incr("reads_profiled")


def read_timelines_snapshot(limit: int = _RING_MAX) -> list[dict[str, Any]]:
    """Newest-last finished READ timelines as JSON-safe dicts — the
    read-path acceptance smoke's and slo_report's in-process source."""
    with _lock:
        tls = list(_read_timelines)
    return [t.snapshot() for t in tls[-limit:]]


def current_timeline() -> BlockTimeline | None:
    return _current.get()


@contextlib.contextmanager
def bind_timeline(tl: BlockTimeline | None) -> Iterator[BlockTimeline | None]:
    """Adopt an EXISTING timeline as this thread's ambient one.

    Contextvars do not propagate into worker threads, so the write
    pipeline's helper threads (the ack/checksum pump, the device-batch
    coalescer — server/write_pipeline.py) would otherwise record their
    spans ring-only and the per-block overlap accountant would never see
    the work they hid.  Binding does NOT finish the timeline or touch the
    inflight counter — ownership stays with the opening
    :func:`block_timeline` frame."""
    tok = _current.set(tl)
    try:
        yield tl
    finally:
        _current.reset(tok)


def _observe_finished(tl: BlockTimeline) -> None:
    prof = tl.profile()
    for name, s in prof["phases"].items():
        _M.observe(f"phase_us|phase={name}", s * 1e6)
    _M.observe("block_wall_us", prof["wall_s"] * 1e6)
    _M.gauge("overlap_efficiency", prof["overlap_efficiency"])
    _M.gauge("attributed_frac", prof["attributed_frac"])
    _M.incr("blocks_profiled")


def _record(name: str, t0: float, t1: float, thread: int) -> None:
    tl = _current.get()
    if tl is not None:
        tl.add_span(name, t0, t1, thread)
    with _lock:
        _span_ring.append((name, t0, t1, thread))


@contextlib.contextmanager
def phase(name: str) -> Iterator[None]:
    """Record a named phase span (ambient timeline + global ring).  Cost is
    two clock reads, one list append and one deque append — safe on the
    per-packet path."""
    tid = threading.get_ident()
    stack = _thread_phase.setdefault(tid, [])
    stack.append(name)
    t0 = _now()
    try:
        yield
    finally:
        t1 = _now()
        try:
            stack.pop()
        except IndexError:
            pass
        _record(name, t0, t1, tid)


def timed_iter(name: str, it: Iterable) -> Iterator:
    """Wrap an iterator so each ``next()`` wait becomes one phase span —
    the per-packet ``recv`` attribution of the client-stream wait."""
    src = iter(it)
    tid = threading.get_ident()
    stack = _thread_phase.setdefault(tid, [])
    while True:
        stack.append(name)
        t0 = _now()
        try:
            item = next(src)
        except StopIteration:
            return
        finally:
            try:
                stack.pop()
            except IndexError:
                pass
        _record(name, t0, _now(), tid)
        yield item


def thread_phase(thread_id: int | None = None) -> str | None:
    """Innermost phase currently open on a thread — the watchdog's
    cross-thread stall attribution probe."""
    if thread_id is None:
        thread_id = threading.get_ident()
    stack = _thread_phase.get(thread_id)
    if not stack:
        return None
    try:
        return stack[-1]
    except IndexError:
        return None


# ----------------------------------------------------------- device linkage


def note_device_dispatch() -> None:
    """Device-ledger hook: a dispatch was enqueued (counter track only)."""
    counter_add("outstanding_dispatches", 1)


def note_device_wait(op: str, t0: float, t1: float,
                     event_id: int | None = None,
                     counted: bool = True) -> None:
    """Device-ledger hook at readback: the [enqueue, forced-completion]
    window becomes a ``device_wait`` span, linked to the ledger event id on
    the ambient timeline."""
    if counted:
        counter_add("outstanding_dispatches", -1)
    tl = _current.get()
    if tl is not None and event_id is not None:
        tl.ledger_ids.append(event_id)
    _record("device_wait", t0, t1, threading.get_ident())


# ------------------------------------------------------------ counter tracks


def counter_add(name: str, delta: float) -> float:
    with _lock:
        v = _counters.get(name, 0.0) + delta
        _counters[name] = v
        _sample_locked(name, v)
    _M.gauge(name, v)
    return v


def counter_set(name: str, value: float) -> None:
    with _lock:
        _counters[name] = value
        _sample_locked(name, value)
    _M.gauge(name, value)


def _sample_locked(name: str, value: float) -> None:
    _counter_id[0] += 1
    _counter_ring.append({"t": _now(), "name": name, "value": value,
                          "proc": _PROC, "id": _counter_id[0]})


def counters_snapshot(limit: int = _COUNTER_RING_MAX) -> list[dict[str, Any]]:
    """Newest-last counter-track samples (chrome ``C`` event source)."""
    with _lock:
        out = list(_counter_ring)
    return out[-limit:]


# ----------------------------------------------------- run-level windowing


def mark() -> float:
    """Wall-clock stamp for window_profile (bench round boundaries)."""
    return _now()


def window_spans(t0: float, t1: float) -> list[tuple]:
    """Spans from ANY thread overlapping [t0, t1], clamped to it — the
    cross-thread view run-level accounting needs (the bench's commit worker
    records on its own thread; a contextvar would never see it)."""
    with _lock:
        spans = list(_span_ring)
    return [(p, max(s0, t0), min(s1, t1), tid)
            for p, s0, s1, tid in spans if s1 > t0 and s0 < t1]


def window_profile(t0: float, t1: float, nbytes: int = 0) -> dict[str, Any]:
    """Overlap profile of everything recorded in [t0, t1] across threads —
    the bench's ``phase_profile`` JSON stamp."""
    return profile_spans(window_spans(t0, t1), t0, t1, nbytes=nbytes)


# ------------------------------------------------------------- introspection


def timelines_snapshot(limit: int = _RING_MAX) -> list[dict[str, Any]]:
    """Newest-last finished timelines as JSON-safe dicts (profiles
    included) — gap_report's in-process source."""
    with _lock:
        tls = list(_timelines)
    return [t.snapshot() for t in tls[-limit:]]


def reset() -> None:
    """Drop rings + counters (tests / gap_report smoke isolation); the
    write_profiler registry's cumulative metrics are left alone."""
    with _lock:
        _timelines.clear()
        _read_timelines.clear()
        _span_ring.clear()
        _counter_ring.clear()
        _counters.clear()
