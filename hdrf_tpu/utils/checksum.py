"""Composable file-level checksums (FileChecksumHelper.java:56 /
BlockChecksumHelper.java:61 analog, in COMPOSITE_CRC mode).

The reference's default MD5-of-MD5-of-CRC file checksum depends on block and
cell boundaries, so a replicated file and an EC-striped file with identical
bytes hash differently; Hadoop added COMPOSITE_CRC (HDFS-13056) — a
mathematically *combinable* CRC over the logical byte stream — precisely so
layouts stay comparable.  This module is that combiner for CRC32C: given the
per-chunk CRCs the DataNodes already store in BlockMeta (no data reads), it
derives the CRC32C of the whole logical stream, which equals
``crc32c(file_bytes)`` by construction — a property the tests use as the
oracle.

``crc32c_combine(crc1, crc2, len2)`` follows zlib's crc32_combine GF(2)
matrix method with the Castagnoli polynomial: append ``len2`` zero bytes to
the stream behind ``crc1`` by repeated matrix squaring, then xor ``crc2``.
"""

from __future__ import annotations

_POLY = 0x82F63B78  # reflected Castagnoli (CRC32C)


def _matrix_times(mat: list[int], vec: int) -> int:
    s = 0
    i = 0
    while vec:
        if vec & 1:
            s ^= mat[i]
        vec >>= 1
        i += 1
    return s


def _matrix_square(mat: list[int]) -> list[int]:
    return [_matrix_times(mat, m) for m in mat]


def crc32c_combine(crc1: int, crc2: int, len2: int) -> int:
    """CRC32C of A+B from crc32c(A), crc32c(B), len(B)."""
    if len2 <= 0:
        return crc1
    # operator matrices: odd = one zero BIT appended
    odd = [0] * 32
    odd[0] = _POLY
    row = 1
    for n in range(1, 32):
        odd[n] = row
        row <<= 1
    even = _matrix_square(odd)   # 2 bits
    odd = _matrix_square(even)   # 4 bits
    # walk len2 (bytes): first squaring lands on 8 bits = 1 byte
    while True:
        even = _matrix_square(odd)
        if len2 & 1:
            crc1 = _matrix_times(even, crc1)
        len2 >>= 1
        if not len2:
            break
        odd = _matrix_square(even)
        if len2 & 1:
            crc1 = _matrix_times(odd, crc1)
        len2 >>= 1
        if not len2:
            break
    return (crc1 ^ crc2) & 0xFFFFFFFF


def compose_chunks(crcs: list[int], chunk: int, length: int,
                   crc: int = 0, pos: int = 0) -> tuple[int, int]:
    """Fold a run of per-chunk CRCs (each covering ``chunk`` bytes, the
    last possibly partial against ``length``) into a running stream CRC.
    Returns (crc, new_pos).  ``pos`` is the running stream position —
    only used to size the final partial chunk."""
    for i, c in enumerate(crcs):
        clen = min(chunk, length - i * chunk)
        if clen <= 0:
            break
        crc = c if pos == 0 else crc32c_combine(crc, c, clen)
        pos += clen
    return crc, pos
