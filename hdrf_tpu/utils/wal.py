"""Shared write-ahead-log framing.

One record = ``[u32 payload_len][u32 crc32c(payload)][payload]``.  A torn final
record (crash mid-append) fails the CRC and is dropped; a corrupt record stops
replay at the last good prefix.  Used by the chunk index (Redis replacement)
and the NameNode edit log (FSEditLog.java:124 analog).
"""

from __future__ import annotations

import struct
from typing import Iterator

from hdrf_tpu import native

_HDR = struct.Struct("<II")


def frame(payload: bytes) -> bytes:
    return _HDR.pack(len(payload), native.crc32c(payload)) + payload


def iter_frames(data: bytes) -> Iterator[bytes]:
    """Yield payloads of intact records; stop at the first torn/corrupt one."""
    payloads, _ = scan(data)
    yield from payloads


def scan(data: bytes) -> tuple[list[bytes], int]:
    """Intact payload list + length of the good prefix (bytes before the
    first torn/corrupt record)."""
    payloads: list[bytes] = []
    pos = 0
    while pos + _HDR.size <= len(data):
        ln, crc = _HDR.unpack_from(data, pos)
        payload = data[pos + _HDR.size : pos + _HDR.size + ln]
        if len(payload) < ln or native.crc32c(payload) != crc:
            break
        payloads.append(payload)
        pos += _HDR.size + ln
    return payloads, pos


def recover(path: str, truncate: bool = True) -> list[bytes]:
    """Read a WAL, return intact payloads, and TRUNCATE any torn tail so a
    subsequent append-open continues at the good prefix.  Without the
    truncation, records appended after a crash would land behind the garbage
    and be unreachable by the next replay — silently losing acked writes.
    ``truncate=False`` is the read-only mode (offline viewers)."""
    import os

    if not os.path.exists(path):
        return []
    with open(path, "rb") as f:
        data = f.read()
    payloads, good_len = scan(data)
    if truncate and good_len < len(data):
        with open(path, "r+b") as f:
            f.truncate(good_len)
    return payloads
