"""Crash-safe flight-recorder archive + cluster time-series math.

Long-horizon half of the flight plane (utils/flight_recorder.py:33-76):
every in-memory surface so far — the gauge ring, the device ledger, the
trace sink — dies with its daemon, yet the honest production number is a
*curve over restarts* (ROADMAP item 1).  This module persists each
daemon's flight samples with the same durability discipline the chunk
index WAL established (index/chunk_index.py:19-27, utils/wal.py:44-60;
the FSEditLog.java:124 lineage):

- **Append-only JSONL segments**: one compact JSON object per line,
  appended to the active segment ``flight-<seq>.jsonl``; sample dicts are
  JSON-plain by construction (flight_recorder.py snapshot contract).
- **Fsync'd rotation**: when the active segment exceeds
  ``segment_bytes`` it is flushed, fsync'd, and sealed (directory entry
  fsync'd too — the tmp+fsync+replace cousin used by container seals);
  a sealed segment is durable history, the active one is best-effort
  until sealed (or ``sync()`` is called).
- **Size/age-bounded GC**: after each rotation, oldest sealed segments
  are deleted until the directory fits ``max_bytes``; segments older
  than ``max_age_s`` (0 = disabled) age out regardless of size.
- **Torn-tail-tolerant replay**: a crash mid-append leaves a final line
  without a newline or with broken JSON; replay keeps each segment's
  good prefix and drops the tail (the WAL ``scan()`` good-prefix rule,
  utils/wal.py:29-41), and re-opening for append truncates the torn tail
  first so post-crash samples never land behind garbage.

Also hosts the cluster-series math the gateway's
``/timeseries?scope=cluster`` endpoint needs (server/http_gateway.py):
``filter_series`` (``?metric=``/``?since=``), ``merge_cluster`` (align
per-daemon samples into time buckets; quantile-class gauges merge as the
MAX across nodes — quantiles cannot be averaged, and the slowest node's
tail is the cluster tail a client actually sees — additive gauges sum,
ratios take the mean), and ``rollup`` (step-bucketed min/max/mean/last
downsampling so a million-sample archive renders bounded).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Iterable

from . import metrics

_M = metrics.registry("flight_archive")

SEGMENT_PREFIX = "flight-"
SEGMENT_SUFFIX = ".jsonl"


def _segment_name(seq: int) -> str:
    return f"{SEGMENT_PREFIX}{seq:08d}{SEGMENT_SUFFIX}"


def _segment_seq(name: str) -> int | None:
    if not (name.startswith(SEGMENT_PREFIX)
            and name.endswith(SEGMENT_SUFFIX)):
        return None
    body = name[len(SEGMENT_PREFIX):-len(SEGMENT_SUFFIX)]
    return int(body) if body.isdigit() else None


def list_segments(directory: str) -> list[str]:
    """Segment file names under ``directory``, oldest first."""
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    segs = [(s, n) for n in names
            if (s := _segment_seq(n)) is not None]
    return [n for _, n in sorted(segs)]


def scan_lines(data: bytes) -> tuple[list[dict], int]:
    """Good-prefix scan of one segment's bytes: parsed samples plus the
    byte length of the intact prefix.  Stops at the first line that fails
    to parse or lacks its terminating newline (torn tail)."""
    samples: list[dict] = []
    good = 0
    pos = 0
    n = len(data)
    while pos < n:
        nl = data.find(b"\n", pos)
        if nl < 0:
            break  # torn tail: bytes without a newline
        line = data[pos:nl]
        if line:
            try:
                doc = json.loads(line)
            except ValueError:
                break  # corrupt line: keep the good prefix only
            if not isinstance(doc, dict):
                break
            samples.append(doc)
        pos = nl + 1
        good = pos
    return samples, good


def replay_dir(directory: str, limit: int | None = None,
               since: float | None = None) -> list[dict]:
    """Read-only replay of every segment, oldest first, torn tails
    dropped — the shape ``slo_report --input <dir>`` and the query
    surfaces consume.  Never truncates (offline viewers must not mutate
    a live daemon's archive)."""
    out: list[dict] = []
    for name in list_segments(directory):
        try:
            with open(os.path.join(directory, name), "rb") as f:
                data = f.read()
        except OSError:
            continue
        samples, good = scan_lines(data)
        if good < len(data):
            _M.incr("torn_tail_drops")
        out.extend(samples)
    if since is not None:
        out = [s for s in out if float(s.get("t", 0.0)) >= since]
    if limit is not None and len(out) > limit:
        out = out[-limit:]
    _M.incr("replayed_samples", len(out))
    return out


class FlightArchive:
    """Append-only JSONL segment store for one daemon's flight samples."""

    def __init__(self, directory: str, max_bytes: int = 64 << 20,
                 segment_bytes: int = 1 << 20, max_age_s: float = 0.0,
                 wall=time.time):
        self.directory = directory
        self.max_bytes = int(max_bytes)
        self.segment_bytes = int(segment_bytes)
        self.max_age_s = float(max_age_s)
        self._wall = wall
        self._lock = threading.Lock()
        os.makedirs(directory, exist_ok=True)
        segs = list_segments(directory)
        self._seq = (_segment_seq(segs[-1]) or 0) if segs else 1
        self._open_active()
        self.gc()

    # ------------------------------------------------------------ append

    def _active_path(self) -> str:
        return os.path.join(self.directory, _segment_name(self._seq))

    def _open_active(self) -> None:
        """Open the active segment for append, truncating any torn tail
        first (utils/wal.py:44-60 ``recover``'s rule: records appended
        behind garbage would be unreachable by the next replay)."""
        path = self._active_path()
        if os.path.exists(path):
            with open(path, "rb") as f:
                data = f.read()
            _, good = scan_lines(data)
            if good < len(data):
                _M.incr("torn_tail_drops")
                with open(path, "r+b") as f:
                    f.truncate(good)
        self._f = open(path, "ab")

    def append(self, sample: dict) -> None:
        """Append one sample (one line).  Flushed to the OS on every
        append — a process crash loses nothing; only a host crash can
        tear the active segment's tail, which replay drops."""
        line = (json.dumps(sample, separators=(",", ":"),
                           default=float) + "\n").encode()
        with self._lock:
            self._f.write(line)
            self._f.flush()
            _M.incr("appends_total")
            if self._f.tell() >= self.segment_bytes:
                self._rotate_locked()

    def _rotate_locked(self) -> None:
        """Seal the active segment (fsync file + directory) and open the
        next one; then GC.  The fsync here is what upgrades best-effort
        appends into durable history."""
        os.fsync(self._f.fileno())
        self._f.close()
        self._fsync_dir()
        self._seq += 1
        self._f = open(self._active_path(), "ab")
        _M.incr("segments_rotated")
        self._gc_locked()

    def _fsync_dir(self) -> None:
        try:
            dfd = os.open(self.directory, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:
            pass  # platforms without directory fsync

    def sync(self) -> None:
        """Force-durability point (daemon shutdown): fsync the active
        segment without sealing it."""
        with self._lock:
            self._f.flush()
            os.fsync(self._f.fileno())

    # ---------------------------------------------------------------- gc

    def _gc_locked(self) -> int:
        """Delete oldest SEALED segments until the byte budget holds and
        every survivor is younger than ``max_age_s``.  The active segment
        is never deleted — the tail of history always survives."""
        removed = 0
        now = self._wall()
        active = _segment_name(self._seq)
        sealed = [n for n in list_segments(self.directory) if n != active]
        sizes = {}
        for n in list_segments(self.directory):
            try:
                sizes[n] = os.path.getsize(os.path.join(self.directory, n))
            except OSError:
                sizes[n] = 0
        total = sum(sizes.values())
        for n in list(sealed):
            path = os.path.join(self.directory, n)
            age = 0.0
            if self.max_age_s > 0:
                try:
                    age = now - os.path.getmtime(path)
                except OSError:
                    age = 0.0
            over_budget = total > self.max_bytes
            too_old = self.max_age_s > 0 and age > self.max_age_s
            if not (over_budget or too_old):
                break  # oldest survivor fits: younger ones fit too
            try:
                os.remove(path)
            except OSError:
                break
            total -= sizes.get(n, 0)
            removed += 1
            _M.incr("segments_gc")
        _M.gauge("archive_bytes", total)
        return removed

    def gc(self) -> int:
        with self._lock:
            return self._gc_locked()

    # ------------------------------------------------------------- reads

    def total_bytes(self) -> int:
        return sum(os.path.getsize(os.path.join(self.directory, n))
                   for n in list_segments(self.directory)
                   if os.path.exists(os.path.join(self.directory, n)))

    def replay(self, limit: int | None = None,
               since: float | None = None) -> list[dict]:
        """Samples across every segment, oldest first, torn tails
        dropped.  Reads see appended-but-unsealed lines too (same file)."""
        with self._lock:
            self._f.flush()
        return replay_dir(self.directory, limit=limit, since=since)

    def close(self) -> None:
        with self._lock:
            try:
                self._f.flush()
                os.fsync(self._f.fileno())
            except (OSError, ValueError):
                pass
            self._f.close()


# ===================================================== cluster series math

# Gauges that are per-node tallies: the cluster value is the SUM.
SUM_GAUGES = ("blocks", "inflight", "stalls", "sheds_total",
              "garbage_bytes", "scrub_corrupt_total", "fsck_violations",
              "breakers_open", "breakers_half_open", "tenant_count",
              "datanodes", "datanodes_live", "under_replicated",
              "pending_replication", "pending_recovery")
# Gauges that are latency quantiles: quantiles cannot be averaged, and
# the cluster tail a client experiences is governed by the slowest node,
# so the merge is the MAX (a conservative envelope).
QUANTILE_SUFFIXES = ("_p50_ms", "_p95_ms", "_p99_ms")


def merge_value(name: str, vals: list[float]) -> float:
    """One gauge's cluster value from its per-node values."""
    if name.endswith(QUANTILE_SUFFIXES):
        return max(vals)
    if name in SUM_GAUGES:
        return float(sum(vals))
    return sum(vals) / len(vals)


def filter_series(samples: Iterable[dict], metric: str | None = None,
                  since: float | None = None) -> list[dict]:
    """The ``?metric=``/``?since=`` projection: keep only the requested
    gauge(s) (comma-separated; clock stamps always survive) and samples
    at or after ``since`` (wall seconds)."""
    keep = None
    if metric:
        keep = {m.strip() for m in metric.split(",") if m.strip()}
    out = []
    for s in samples:
        if since is not None and float(s.get("t", 0.0)) < since:
            continue
        if keep is None:
            out.append(s)
        else:
            out.append({k: v for k, v in s.items()
                        if k in ("t", "mono") or k in keep})
    return out


def merge_cluster(series: list[tuple[str, list[dict]]],
                  step_s: float = 1.0) -> list[dict]:
    """Align per-daemon sample streams into one cluster series: bucket by
    ``floor(t / step_s)``, then fold each gauge across every sample that
    landed in the bucket with :func:`merge_value`.  Each output sample
    carries ``t`` (bucket start), ``nodes`` (distinct daemons that
    contributed), and the merged gauges — deterministic for injected
    clocks (tests pin the quantile/sum/mean arithmetic)."""
    step = max(float(step_s), 1e-9)
    buckets: dict[int, dict[str, list[float]]] = {}
    contributors: dict[int, set[str]] = {}
    for daemon, samples in series:
        for s in samples:
            b = int(float(s.get("t", 0.0)) // step)
            vals = buckets.setdefault(b, {})
            contributors.setdefault(b, set()).add(daemon)
            for k, v in s.items():
                if k in ("t", "mono") or not isinstance(v, (int, float)):
                    continue
                vals.setdefault(k, []).append(float(v))
    out = []
    for b in sorted(buckets):
        merged: dict[str, Any] = {"t": b * step,
                                  "nodes": len(contributors[b])}
        for name, vals in sorted(buckets[b].items()):
            merged[name] = merge_value(name, vals)
        out.append(merged)
    return out


def rollup(samples: list[dict], step_s: float) -> list[dict]:
    """Step-bucketed downsampling: one output row per ``step_s`` bucket
    with ``{min, max, mean, last}`` per gauge — the bounded-response
    rendering of an archive too long to ship sample-by-sample."""
    step = max(float(step_s), 1e-9)
    buckets: dict[int, list[dict]] = {}
    for s in samples:
        buckets.setdefault(int(float(s.get("t", 0.0)) // step),
                           []).append(s)
    out = []
    for b in sorted(buckets):
        group = buckets[b]
        gauges: dict[str, dict] = {}
        for s in group:
            for k, v in s.items():
                if k in ("t", "mono", "nodes") \
                        or not isinstance(v, (int, float)):
                    continue
                g = gauges.setdefault(
                    k, {"min": float(v), "max": float(v),
                        "sum": 0.0, "n": 0, "last": float(v)})
                g["min"] = min(g["min"], float(v))
                g["max"] = max(g["max"], float(v))
                g["sum"] += float(v)
                g["n"] += 1
                g["last"] = float(v)
        row = {"t": b * step, "n": len(group), "gauges": {}}
        for k, g in sorted(gauges.items()):
            row["gauges"][k] = {"min": g["min"], "max": g["max"],
                                "mean": g["sum"] / g["n"],
                                "last": g["last"]}
        out.append(row)
    return out


def query(recorder, archive: FlightArchive | None = None,
          metric: str | None = None, since: float | None = None,
          limit: int = 2048) -> dict:
    """One daemon's ``/timeseries`` answer over ring + archive: archived
    (restart-survived) history first, the live ring on top, de-duplicated
    by the ``(t, mono)`` clock stamp pair (ring samples were also
    appended to the archive), filtered, and tail-limited.  Shared by the
    DN ``flight_timeseries`` op and the NN ``flight_query`` RPC."""
    snap = recorder.snapshot()
    samples: list[dict] = []
    seen: set[tuple] = set()
    archived = archive.replay() if archive is not None else []
    for s in archived + list(snap["samples"]):
        key = (s.get("t"), s.get("mono"))
        if key in seen:
            continue
        seen.add(key)
        samples.append(s)
    samples = filter_series(samples, metric=metric, since=since)
    if len(samples) > limit:
        samples = samples[-limit:]
    return {"daemon": snap["daemon"], "interval_s": snap["interval_s"],
            "capacity": snap["capacity"], "archived": len(archived),
            "samples": samples}
