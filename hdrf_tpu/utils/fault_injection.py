"""Fault injection for tests.

Equivalent of the reference's injectable singletons (DataNodeFaultInjector.java:33,
BlockManagerFaultInjector, CheckpointFaultInjector, ...): main code declares named
points via :func:`point`; tests install handlers that raise/delay/count at precise
moments. Zero overhead when no handler is installed.
"""

from __future__ import annotations

import threading
from typing import Any, Callable

_handlers: dict[str, Callable[..., Any]] = {}
_lock = threading.Lock()


def point(name: str, **kw: Any) -> None:
    """Declare an injection point. Called from main code at precise moments,
    e.g. ``fault_injection.point("replica.finalize", block_id=bid)``."""
    h = _handlers.get(name)
    if h is not None:
        h(**kw)


def install(name: str, handler: Callable[..., Any]) -> None:
    with _lock:
        _handlers[name] = handler


def remove(name: str) -> None:
    with _lock:
        _handlers.pop(name, None)


def clear() -> None:
    with _lock:
        _handlers.clear()


class inject:
    """Context manager: ``with inject("dn.heartbeat", lambda **kw: 1/0): ...``"""

    def __init__(self, name: str, handler: Callable[..., Any]) -> None:
        self.name, self.handler = name, handler

    def __enter__(self) -> "inject":
        install(self.name, self.handler)
        return self

    def __exit__(self, *exc: Any) -> None:
        remove(self.name)
