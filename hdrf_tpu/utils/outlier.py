"""Median + MAD outlier detection for slow-peer / slow-volume flagging.

Re-expresses HDFS's OutlierDetector.java:61-103 (used by SlowPeerTracker
and SlowDiskTracker): given one latency statistic per resource, compute the
population median and the median absolute deviation, and flag resources
whose value exceeds ``max(median * min_ratio, median + k * MAD)`` — the
reference's ``upperLimit = max(median * DEVIATION_MULTIPLIER, median +
mad * DEVIATION_MULTIPLIER)`` with its ``minOutlierDetectionNodes``
population guard and ``lowThresholdMs`` absolute guard.  Straggler
flagging over reported latencies is the outlier-mitigation primitive the
coded-computing literature builds on (arXiv:1805.01993 §I).

Deterministic: pure functions of the input mapping, no wall clock.  The
stateful ``OutlierTracker`` adds flag timestamps with an injectable clock
so callers (server/namenode.py) can expose "currently flagged" gauges
without hidden time dependencies.

Degenerate windows are first-class: with MAD == 0 (all values equal, the
common all-healthy case) the threshold collapses to ``median * min_ratio``,
so a planted straggler still flags and a uniform population never does.
"""

from __future__ import annotations

import statistics
import time

# Consistency constant: scaled MAD estimates the standard deviation for
# normally distributed data (the reference's MAD_MULTIPLIER = 1.4826).
MAD_SCALE = 1.4826


def mad(values: list[float], med: float | None = None) -> float:
    """Scaled median absolute deviation; 0.0 for empty input."""
    if not values:
        return 0.0
    m = statistics.median(values) if med is None else med
    return MAD_SCALE * statistics.median([abs(v - m) for v in values])


def detect(values: dict, *, k: float = 3.0, min_ratio: float = 3.0,
           min_points: int = 3, floor: float = 0.0,
           abs_floor: float | None = None) -> dict:
    """Flag outliers in ``values`` (resource -> latency statistic).

    Two rules, mirroring the reference pair:

    - **MAD rule** (needs >= ``min_points`` resources): flag values above
      ``max(median * min_ratio, median + k * MAD)``; values must also
      exceed ``floor`` (the lowThreshold guard — a 'slow' peer in a
      uniformly sub-millisecond population is not actionable).
    - **absolute rule** (any population size): when ``abs_floor`` is set,
      a value above it is pathological regardless of the population —
      the no-baseline case (tiny cluster, skewed placement) where the
      MAD rule has nothing to compare against.

    Returns {resource: {"value", "median", "mad", "upper", "rule"}}, empty
    when nothing flags.  Deterministic: no clock, no randomness.
    """
    out: dict = {}
    vs = [float(v) for v in values.values()]
    med = statistics.median(vs) if vs else 0.0
    spread = mad(vs, med)
    upper = max(med * min_ratio, med + k * spread)
    for key, v in values.items():
        v = float(v)
        rule = None
        if len(vs) >= min_points and v > upper and v > floor:
            rule = "mad"
        elif abs_floor is not None and v > abs_floor:
            rule = "absolute"
        if rule:
            out[key] = {"value": v, "median": med, "mad": spread,
                        "upper": upper, "rule": rule}
    return out


class OutlierTracker:
    """detect() plus flag bookkeeping: remembers when each resource was
    last flagged and expires stale flags after ``expiry_s`` without a
    re-flag — so a gauge built on ``report()`` recovers once the slow
    resource heals instead of latching forever.  Clock injectable for
    deterministic tests."""

    def __init__(self, expiry_s: float = 300.0, clock=time.monotonic,
                 **detect_kw):
        self.expiry_s = expiry_s
        self._clock = clock
        self._detect_kw = detect_kw
        self._flags: dict = {}   # resource -> {"since", "last", **detail}

    def observe(self, values: dict, now: float | None = None) -> dict:
        """Run detection over a fresh snapshot and fold into the flag set.
        Returns the currently flagged resources (same shape as report())."""
        t = self._clock() if now is None else now
        for key, detail in detect(values, **self._detect_kw).items():
            prev = self._flags.get(key)
            self._flags[key] = {**detail,
                                "since": prev["since"] if prev else t,
                                "last": t}
        return self.report(now=t)

    def report(self, now: float | None = None) -> dict:
        t = self._clock() if now is None else now
        for key in [k for k, f in self._flags.items()
                    if t - f["last"] > self.expiry_s]:
            del self._flags[key]
        return {k: dict(f) for k, f in self._flags.items()}
