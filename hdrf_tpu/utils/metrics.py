"""Metrics registry.

Equivalent of Hadoop metrics2 (`DataNodeMetrics.java:53`, `NameNodeMetrics.java:42`,
`FSDatasetMBean`): named counters/gauges/histograms on a process-wide registry,
snapshot-able as a dict (the JMX-MXBean analog) and served by the daemons' HTTP
status endpoints.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from typing import Any


class Histogram:
    """Fixed-bucket latency/size histogram with mean/max tracking."""

    __slots__ = ("count", "total", "max", "_buckets")

    # Power-of-2 bucket upper bounds (microseconds or bytes, caller's choice).
    BOUNDS = tuple(2 ** i for i in range(32))

    def __init__(self) -> None:
        self.count = 0
        self.total = 0.0
        self.max = 0.0
        self._buckets = [0] * (len(self.BOUNDS) + 1)

    def update(self, value: float) -> None:
        self.count += 1
        self.total += value
        if value > self.max:
            self.max = value
        for i, b in enumerate(self.BOUNDS):
            if value <= b:
                self._buckets[i] += 1
                return
        self._buckets[-1] += 1

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def quantile(self, q: float) -> float:
        """Approximate quantile from the power-of-2 buckets (upper bound)."""
        target = q * self.count
        seen = 0
        for i, c in enumerate(self._buckets):
            seen += c
            if seen >= target:
                return float(self.BOUNDS[i]) if i < len(self.BOUNDS) else self.max
        return self.max

    def snapshot(self) -> dict[str, Any]:
        """Snapshot with CUMULATIVE bucket counts (the Prometheus histogram
        contract: each ``le`` bucket counts all observations <= the bound, and
        the implicit ``+Inf`` bucket equals ``count``).  Only bounds where the
        cumulative count increases are emitted, so 32 power-of-2 bounds don't
        bloat every snapshot; p50/p99 stay for existing JSON consumers."""
        buckets: list[list[float]] = []
        cum = 0
        for i, c in enumerate(self._buckets[:-1]):
            if c:
                cum += c
                buckets.append([float(self.BOUNDS[i]), cum])
        return {"count": self.count, "sum": self.total, "mean": self.mean,
                "max": self.max, "p50": self.quantile(0.50),
                "p99": self.quantile(0.99), "buckets": buckets}


class MetricsRegistry:
    def __init__(self, name: str) -> None:
        self.name = name
        self._lock = threading.Lock()
        self._counters: dict[str, int] = defaultdict(int)
        self._gauges: dict[str, float] = {}
        self._histograms: dict[str, Histogram] = {}

    def incr(self, key: str, delta: int = 1) -> None:
        with self._lock:
            self._counters[key] += delta

    def gauge(self, key: str, value: float) -> None:
        with self._lock:
            self._gauges[key] = value

    def observe(self, key: str, value: float) -> None:
        with self._lock:
            h = self._histograms.get(key)
            if h is None:
                h = self._histograms[key] = Histogram()
            h.update(value)

    def time(self, key: str) -> "_Timer":
        return _Timer(self, key)

    def counter(self, key: str) -> int:
        with self._lock:
            return self._counters[key]

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            return {
                "name": self.name,
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {k: h.snapshot() for k, h in self._histograms.items()},
            }


class _Timer:
    def __init__(self, reg: MetricsRegistry, key: str) -> None:
        self._reg, self._key = reg, key

    def __enter__(self) -> "_Timer":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc: Any) -> None:
        self._reg.observe(self._key, (time.perf_counter() - self._t0) * 1e6)


_registries: dict[str, MetricsRegistry] = {}
_registries_lock = threading.Lock()


def registry(name: str) -> MetricsRegistry:
    with _registries_lock:
        reg = _registries.get(name)
        if reg is None:
            reg = _registries[name] = MetricsRegistry(name)
        return reg


def all_snapshots() -> dict[str, Any]:
    with _registries_lock:
        regs = list(_registries.values())
    return {r.name: r.snapshot() for r in regs}
