"""Per-tenant (client) attribution: op/byte counters + rolling SLO gauges.

The reference has no per-client metrics at all — DataNodeMetrics.java:553-560
counts ops per daemon and NameNode audit logging (FSNamesystem.java:8040
``logAuditEvent``) records *who* called but never aggregates per caller — so
a noisy neighbor is invisible until it moves the daemon-wide p99.  Here every
RPC and data-transfer op carries a ``_client`` id on the existing side-channel
(proto/rpc.py:123-145's ``_trace``/``_dtoken``/``_user`` kwarg strip;
proto/datatransfer.py:74-83's header-field stamp), and the serving daemons
feed one process-wide tracker:

- cumulative ``tenant_ops|tenant=<t>,op=<kind>`` /
  ``tenant_bytes|tenant=<t>,op=<kind>`` counters (utils/prom.py renders the
  ``|k=v`` suffix as labels, so /prom gets real per-tenant series);
- rolling p50/p95/p99 latency gauges (``tenant_p50_ms`` etc.) over decayed
  windows (utils/rollwin.py:27-74's RollingWindow via the nearest-rank
  ``quantiles()`` extension) — the per-tenant SLO surface ROADMAP item 2's
  QoS/admission work will act on.

Tenancy here is attribution, not authentication: the ``_client`` id is the
client's self-reported name (client/filesystem.py stamps it), exactly like
the reference's clientName field on writeBlock (DataTransferProtocol.java's
clientname) — the authenticated principal stays ``_user``.
"""

from __future__ import annotations

import threading
import time

from . import metrics, rollwin

_M = metrics.registry("tenants")

DEFAULT_TENANT = "anon"  # ops arriving without a _client id

_PCTS = (50, 95, 99)


class TenantTracker:
    """Process-wide per-tenant accounting: cumulative counters into the
    ``tenants`` registry plus decayed latency windows per (tenant, op)."""

    def __init__(self, window_s: float = 300.0, maxlen: int = 128,
                 clock=time.monotonic):
        self._lat = rollwin.WindowMap(window_s, maxlen, clock)
        self._lock = threading.Lock()
        self._seen: set[str] = set()

    def note_op(self, tenant: str | None, op: str, nbytes: int = 0,
                latency_s: float | None = None,
                now: float | None = None) -> None:
        """One served op for ``tenant``: bumps the op counter, adds
        ``nbytes`` to the byte counter, and (when a latency is supplied)
        folds it into the rolling window and refreshes that series'
        p50/p95/p99 gauges."""
        t = tenant or DEFAULT_TENANT
        with self._lock:
            self._seen.add(t)
        _M.incr(f"tenant_ops|tenant={t},op={op}")
        if nbytes:
            _M.incr(f"tenant_bytes|tenant={t},op={op}", int(nbytes))
        if latency_s is not None:
            self._lat.note((t, op), latency_s * 1e3, now=now)
            with self._lat._lock:
                win = self._lat._wins.get((t, op))
            qs = win.quantiles(_PCTS, now=now) if win is not None else None
            if qs:
                for p in _PCTS:
                    _M.gauge(f"tenant_p{p}_ms|tenant={t},op={op}", qs[f"p{p}"])

    def tenant_count(self) -> int:
        """Distinct tenants seen since process start (cumulative — decayed
        windows don't shrink it; the bench's ``tenant_count`` stamp)."""
        with self._lock:
            return len(self._seen)

    def summaries(self, now: float | None = None) -> dict:
        """``"<tenant>/<op>" -> {"p50","p95","p99"}`` over live windows —
        the JSON shape /health and the flight recorder embed."""
        out = {}
        for (t, op), s in self._lat.summaries(now).items():
            with self._lat._lock:
                win = self._lat._wins.get((t, op))
            qs = win.quantiles(_PCTS, now=now) if win is not None else None
            if qs is not None:
                out[f"{t}/{op}"] = qs
        return out

    def reset(self) -> None:
        """Drop windows + the seen set (test isolation); the cumulative
        ``tenants`` registry counters are left alone, like profiler.reset."""
        with self._lock:
            self._seen.clear()
        with self._lat._lock:
            self._lat._wins.clear()


TRACKER = TenantTracker()


def note_op(tenant: str | None, op: str, nbytes: int = 0,
            latency_s: float | None = None, now: float | None = None) -> None:
    TRACKER.note_op(tenant, op, nbytes=nbytes, latency_s=latency_s, now=now)


def tenant_count() -> int:
    return TRACKER.tenant_count()


def summaries(now: float | None = None) -> dict:
    return TRACKER.summaries(now)
