"""One source of truth for the corrected XLA:CPU process environment.

The dev tunnel's sitecustomize force-registers its TPU backend whenever
``PALLAS_AXON_POOL_IPS`` is present, and platform selection only takes
effect via process env at interpreter start — so any code that needs a
true n-device XLA:CPU mesh (tests/conftest.py, __graft_entry__'s dryrun)
must re-exec a child with the env built here (tests/conftest.py:43-44's
relaunch).  Keeping the recipe in one place means a future tunnel change
is fixed once, not per-caller.
"""

from __future__ import annotations

import os


def clean_cpu_env(n_devices: int, base: dict | None = None,
                  keep_existing_count: bool = False) -> dict:
    """Env dict for a child process with ``n_devices`` virtual CPU devices.

    ``keep_existing_count=True`` preserves an operator-set
    ``--xla_force_host_platform_device_count`` flag (``n_devices`` is then
    only the default); ``False`` forces exactly ``n_devices``.
    """
    env = dict(os.environ if base is None else base)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = _with_device_count_flag(
        env.get("XLA_FLAGS", ""), n_devices, keep_existing_count)
    return env


def ensure_device_count_flag(n_devices: int) -> None:
    """Append the virtual-device-count flag to os.environ if absent."""
    os.environ["XLA_FLAGS"] = _with_device_count_flag(
        os.environ.get("XLA_FLAGS", ""), n_devices, keep_existing=True)


def _with_device_count_flag(flags_str: str, n_devices: int,
                            keep_existing: bool) -> str:
    flags = flags_str.split()
    existing = [f for f in flags
                if "xla_force_host_platform_device_count" in f]
    if existing and keep_existing:
        return flags_str
    flags = [f for f in flags if f not in existing]
    flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    return " ".join(flags)


def env_is_tunneled() -> bool:
    """True when the axon sitecustomize will hijack platform selection."""
    return "PALLAS_AXON_POOL_IPS" in os.environ
