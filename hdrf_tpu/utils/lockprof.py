"""Instrumented namesystem lock: per-method wait/hold attribution.

The reference wraps its global FSNamesystem lock in a dedicated
instrumented type (FSNamesystemLock.java:60) that stamps every
acquire/release pair with hold time, logs a stack trace when a writer
holds past ``dfs.namenode.write-lock-reporting-threshold-ms``
(FSNamesystemLock.java:252-267) and feeds per-operation read/write hold
metrics keyed by the RPC op name (FSNamesystemLock.java:160 ``
addMetric``).  This module re-expresses that plane for the single
``RLock`` our NameNode uses (server/namenode.py:269 — "the FSNamesystem
lock analog"):

- :class:`InstrumentedRLock` is a drop-in ``with``-compatible RLock.
  Every OUTERMOST acquire records wait (entry -> lock granted) and hold
  (granted -> final release) seconds, attributed to the ambient RPC
  method (:func:`bind_request`, a contextvar the RPC server stamps in
  dispatch — the same side-channel ride as ``_trace``,
  proto/rpc.py:138).  Reentrant acquires ride the owner fast path: one
  attribute compare, no clock reads, no books (counted once, like the
  reference's read-lock reentrancy counting, FSNamesystemLock.java:125).
- Cumulative books and the rolling p50/p95/p99 windows
  (utils/rollwin.py) are mutated while the caller still HOLDS the inner
  lock, so the lock itself serializes them — no secondary mutex can ever
  block an acquirer (the "no extra blocking" contract the overhead
  guard test pins).  Registry emission (``nn_lock_wait_us|method=`` /
  ``nn_lock_hold_us|method=`` histograms) happens AFTER release.
- ``saturation()`` = fraction of the trailing window the lock was held,
  from a bounded ring of ``(t0, t1)`` hold intervals — the
  ``nn_lock_saturation`` gauge, exact under an injected clock.
- A hold past ``long_hold_s`` captures the holder's stack into a bounded
  ring and fires the ``lockprof.long_hold`` fault point (the
  writeLockReport analog); ``holder()`` exposes the live owner
  (thread id, method, held-for) for the watchdog's convoy capture.

Readers (flight sampler, ``/contention``) take NO lock: they snapshot
the deques/dicts with a retry-on-RuntimeError loop and tolerate the
bounded raciness — observability must never queue behind the very lock
it measures.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
import time
import traceback
from collections import deque
from typing import Any, Iterator

from . import fault_injection, rollwin


class RequestContext:
    """Ambient request identity + span sink for one RPC dispatch."""

    __slots__ = ("method", "spans")

    def __init__(self, method: str, spans: list | None = None) -> None:
        self.method = method
        self.spans = spans if spans is not None else []


_request: contextvars.ContextVar[RequestContext | None] = \
    contextvars.ContextVar("hdrf_rpc_request", default=None)


def current_request() -> RequestContext | None:
    return _request.get()


def current_method() -> str | None:
    ctx = _request.get()
    return None if ctx is None else ctx.method


@contextlib.contextmanager
def bind_request(method: str,
                 spans: list | None = None) -> Iterator[RequestContext]:
    """Stamp the ambient RPC method for the dispatch window.  The lock's
    wait/hold books attribute to it, and ``lock_wait`` / ``locked`` spans
    land in ``spans`` for the server's service-time decomposition."""
    ctx = RequestContext(method, spans)
    tok = _request.set(ctx)
    try:
        yield ctx
    finally:
        _request.reset(tok)


def _snapshot(seq):
    """Copy a deque/dict being mutated by the holder thread; retry the
    rare mid-resize RuntimeError instead of taking any lock."""
    while True:
        try:
            return list(seq)
        except RuntimeError:
            continue


class InstrumentedRLock:
    """Drop-in ``threading.RLock`` with wait/hold/saturation books."""

    _LONG_RING = 32
    _HOLD_RING = 4096

    def __init__(self, name: str = "lock", registry=None,
                 clock=time.perf_counter, long_hold_s: float = 0.5,
                 window_s: float = 300.0, maxlen: int = 512,
                 sat_window_s: float = 60.0) -> None:
        self.name = name
        self.long_hold_s = long_hold_s
        self.sat_window_s = sat_window_s
        self._inner = threading.RLock()
        self._clock = clock
        self._reg = registry
        self._epoch = clock()
        # Owner state: written only by the holder (serialized by the lock).
        self._owner = 0
        self._depth = 0
        self._hold_t0 = 0.0
        self._owner_method: str | None = None
        self._pending_wait = 0.0
        # Cumulative books + rolling windows, mutated under the lock.
        self._acquires = 0
        self._wait_total_s = 0.0
        self._hold_total_s = 0.0
        self._by_method: dict[str | None, list] = {}  # m -> [acq, wait, hold]
        self._wait_win = rollwin.RollingWindow(window_s, maxlen, clock=clock)
        self._hold_win = rollwin.RollingWindow(window_s, maxlen, clock=clock)
        self._hold_win_by_method: dict[str | None, rollwin.RollingWindow] = {}
        self._holds: deque[tuple[float, float]] = deque(maxlen=self._HOLD_RING)
        self._long_holds: deque[dict[str, Any]] = deque(maxlen=self._LONG_RING)

    # ------------------------------------------------------------- lock API

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        me = threading.get_ident()
        if self._owner == me:  # reentrant: cannot block, skip the books
            self._inner.acquire()
            self._depth += 1
            return True
        t0 = self._clock()
        if not self._inner.acquire(blocking, timeout):
            return False
        t1 = self._clock()
        wait = t1 - t0
        self._owner = me
        self._depth = 1
        self._hold_t0 = t1
        self._pending_wait = wait
        ctx = _request.get()
        m = self._owner_method = None if ctx is None else ctx.method
        # Books under the lock we just took — serialized by construction.
        self._acquires += 1
        self._wait_total_s += wait
        rec = self._by_method.get(m)
        if rec is None:
            rec = self._by_method[m] = [0, 0.0, 0.0]
        rec[0] += 1
        rec[1] += wait
        self._wait_win.add(wait * 1e6, now=t1)
        if ctx is not None and ctx.spans is not None:
            ctx.spans.append(("lock_wait", t0, t1))
        return True

    def release(self) -> None:
        me = threading.get_ident()
        if self._owner != me or self._depth > 1:
            if self._owner == me:
                self._depth -= 1
            self._inner.release()  # raises for non-owners, like RLock
            return
        t1 = self._clock()
        hold_t0, m = self._hold_t0, self._owner_method
        hold = t1 - hold_t0
        wait = self._pending_wait
        # Final-release books, still under the lock.
        self._hold_total_s += hold
        rec = self._by_method.get(m)
        if rec is not None:
            rec[2] += hold
        win = self._hold_win_by_method.get(m)
        if win is None:
            win = self._hold_win_by_method[m] = rollwin.RollingWindow(
                self._wait_win.window_s, self._wait_win.maxlen,
                clock=self._clock)
        win.add(hold * 1e6, now=t1)
        self._hold_win.add(hold * 1e6, now=t1)
        self._holds.append((hold_t0, t1))
        cutoff = t1 - self.sat_window_s
        while self._holds and self._holds[0][1] < cutoff:
            self._holds.popleft()
        long_hold = hold >= self.long_hold_s
        if long_hold:  # slow path by definition — allocation is fine here
            self._long_holds.append({
                "ts": time.time(), "method": m, "hold_s": round(hold, 6),
                "stack": traceback.format_stack()})
        ctx = _request.get()
        if ctx is not None and ctx.spans is not None:
            ctx.spans.append(("locked", hold_t0, t1))
        self._owner = 0
        self._depth = 0
        self._owner_method = None
        self._inner.release()
        # Emission AFTER release: the registry mutex never extends a hold.
        if self._reg is not None:
            lbl = m or "other"
            self._reg.observe(f"nn_lock_wait_us|method={lbl}", wait * 1e6)
            self._reg.observe(f"nn_lock_hold_us|method={lbl}", hold * 1e6)
            if long_hold:
                self._reg.incr("nn_lock_long_holds")
        if long_hold:
            fault_injection.point("lockprof.long_hold", lock=self.name,
                                  method=m, hold_s=hold)

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc: Any) -> None:
        self.release()

    # ---------------------------------------------------------- introspection

    def holder(self) -> dict[str, Any] | None:
        """Live owner (thread id, ambient method, held-for seconds), or
        None.  Racy by design — the watchdog's convoy probe must never
        queue behind the lock it is diagnosing."""
        owner = self._owner
        if not owner:
            return None
        return {"thread": owner, "method": self._owner_method,
                "held_for_s": max(0.0, self._clock() - self._hold_t0)}

    def saturation(self, now: float | None = None) -> float:
        """Fraction of the trailing ``sat_window_s`` the lock was held
        (hold-interval overlap + any in-progress hold, clamped to [0, 1];
        the window shrinks to the lock's age early in life so the
        fraction is exact from the first sample)."""
        t = self._clock() if now is None else now
        wall = min(self.sat_window_s, t - self._epoch)
        if wall <= 0:
            return 0.0
        w0 = t - self.sat_window_s
        held = 0.0
        in_progress = self._hold_t0 if self._owner else None
        for a, b in _snapshot(self._holds):
            if in_progress is not None and a == in_progress:
                in_progress = None  # raced with release: interval now rung
            held += max(0.0, min(b, t) - max(a, w0))
        if in_progress is not None:
            held += max(0.0, t - max(in_progress, w0))
        v = min(1.0, held / wall)
        if self._reg is not None:
            self._reg.gauge("nn_lock_saturation", v)
        return v

    def wait_p99_us(self, now: float | None = None) -> float:
        q = self._wait_win.quantiles((99,), now=now)
        return (q or {}).get("p99", 0.0)

    def top_methods(self, n: int = 3) -> list[tuple[str, float]]:
        """Top-``n`` methods by cumulative hold seconds with their rolling
        hold p99 (µs) — the flight sample's per-method lock axis."""
        items = sorted(((m, rec[2]) for m, rec in
                        _snapshot(self._by_method.items())),
                       key=lambda kv: kv[1], reverse=True)[:n]
        out = []
        for m, _hold in items:
            win = self._hold_win_by_method.get(m)
            q = win.quantiles((99,)) if win is not None else None
            out.append((m or "other", (q or {}).get("p99", 0.0)))
        return out

    def contention_summary(self, now: float | None = None) -> dict[str, Any]:
        """JSON-safe contention books: cumulative + rolling + per-method
        table with hold shares — the ``/contention`` lock block."""
        total_hold = self._hold_total_s
        by: dict[str, Any] = {}
        for m, rec in _snapshot(self._by_method.items()):
            acq, wait_s, hold_s = rec[0], rec[1], rec[2]
            win = self._hold_win_by_method.get(m)
            q = win.quantiles((99,), now=now) if win is not None else None
            by[m or "other"] = {
                "acquires": acq,
                "wait_s": round(wait_s, 6),
                "hold_s": round(hold_s, 6),
                "hold_share": hold_s / total_hold if total_hold > 0 else 0.0,
                "hold_p99_us": (q or {}).get("p99", 0.0),
            }
        return {
            "name": self.name,
            "acquires": self._acquires,
            "wait_s": round(self._wait_total_s, 6),
            "hold_s": round(total_hold, 6),
            "saturation": self.saturation(now=now),
            "wait_us": self._wait_win.quantiles((50, 95, 99), now=now) or {},
            "hold_us": self._hold_win.quantiles((50, 95, 99), now=now) or {},
            "by_method": by,
            "long_holds": list(self._long_holds),
        }
