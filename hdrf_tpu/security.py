"""Block access tokens: the BlockTokenSecretManager analog.

The reference gates DataNode ops with HMAC'd block tokens minted by the
NameNode and verified by DataNodes sharing a rolling secret
(`security/token/block/BlockTokenSecretManager`).  Same scheme here:

- the NN keeps a current + previous key (rolled every ``roll_interval_s``;
  verification accepts both, so a roll never invalidates in-flight tokens);
- keys reach DNs in heartbeat responses (the reference ships them in
  ExportedBlockKeys via DatanodeProtocol);
- a token binds (block_id, modes, expiry) with HMAC-SHA256; clients receive
  tokens inside block locations / allocations and echo them in the
  data-transfer op header; DNs verify before serving.

Enabled by ``NameNodeConfig.block_tokens`` (off by default, like
``dfs.block.access.token.enable``).
"""

from __future__ import annotations

import hashlib
import hmac
import os
import time

from hdrf_tpu.utils import metrics

_M = metrics.registry("block_tokens")


def _sign(key: bytes, block_id: int, modes: str, expiry: int) -> bytes:
    msg = f"{block_id}:{modes}:{expiry}".encode()
    return hmac.new(key, msg, hashlib.sha256).digest()


class BlockTokenSecretManager:
    def __init__(self, lifetime_s: float = 600.0, roll_interval_s: float = 300.0):
        self.lifetime_s = lifetime_s
        self.roll_interval_s = roll_interval_s
        self._cur = os.urandom(32)
        self._prev = self._cur
        self._rolled = time.time()

    # ------------------------------------------------------------- NN side

    def maybe_roll(self) -> None:
        if time.time() - self._rolled >= self.roll_interval_s:
            self._prev, self._cur = self._cur, os.urandom(32)
            self._rolled = time.time()
            _M.incr("key_rolls")

    def keys(self) -> list[bytes]:
        """Exported keys for DN heartbeats (ExportedBlockKeys analog)."""
        return [self._cur, self._prev]

    def mint(self, block_id: int, modes: str = "r") -> dict:
        """Token for ``block_id`` allowing ``modes`` ('r', 'w', or 'rw')."""
        expiry = int(time.time() + self.lifetime_s)
        _M.incr("tokens_minted")
        return {"block_id": block_id, "modes": modes, "expiry": expiry,
                "sig": _sign(self._cur, block_id, modes, expiry)}

    # ------------------------------------------------------------- DN side


class BlockTokenVerifier:
    """DN-side verification against the NN-distributed key set."""

    def __init__(self):
        self._keys: list[bytes] = []

    def update_keys(self, keys: list[bytes]) -> None:
        self._keys = [bytes(k) for k in keys]

    @property
    def enabled(self) -> bool:
        return bool(self._keys)

    def mint(self, block_id: int, modes: str, lifetime_s: float = 600.0) -> dict | None:
        """DN-side minting for DN->DN transfer legs (the reference's DNs hold
        the same symmetric keys and mint transfer tokens the same way)."""
        if not self._keys:
            return None
        expiry = int(time.time() + lifetime_s)
        return {"block_id": block_id, "modes": modes, "expiry": expiry,
                "sig": _sign(self._keys[0], block_id, modes, expiry)}

    def verify(self, token: dict | None, block_id: int, mode: str) -> None:
        """Raise PermissionError unless ``token`` authorizes ``mode`` on
        ``block_id`` under a known key."""
        if not self.enabled:
            return  # tokens not enabled cluster-wide
        if token is None:
            _M.incr("tokens_missing")
            raise PermissionError(f"block token required for {mode} "
                                  f"on block {block_id}")
        try:
            ok = (int(token["block_id"]) == block_id
                  and mode in token["modes"]
                  and token["expiry"] >= time.time()
                  and any(hmac.compare_digest(
                      _sign(k, block_id, token["modes"], token["expiry"]),
                      bytes(token["sig"])) for k in self._keys))
        except (KeyError, TypeError, ValueError):
            ok = False
        if not ok:
            _M.incr("tokens_rejected")
            raise PermissionError(f"invalid block token for {mode} "
                                  f"on block {block_id}")
        _M.incr("tokens_verified")
