"""Block access tokens: the BlockTokenSecretManager analog.

The reference gates DataNode ops with HMAC'd block tokens minted by the
NameNode and verified by DataNodes sharing a rolling secret
(security/token/block/BlockTokenSecretManager.java:112).  Same scheme
here:

- the NN keeps a current + previous key (rolled every ``roll_interval_s``;
  verification accepts both, so a roll never invalidates in-flight tokens);
- keys reach DNs in heartbeat responses (the reference ships them in
  ExportedBlockKeys via DatanodeProtocol);
- a token binds (block_id, modes, expiry) with HMAC-SHA256; clients receive
  tokens inside block locations / allocations and echo them in the
  data-transfer op header; DNs verify before serving.

Enabled by ``NameNodeConfig.block_tokens`` (off by default, like
``dfs.block.access.token.enable``).
"""

from __future__ import annotations

import hashlib
import hmac
import os
import time

from hdrf_tpu.utils import metrics

_M = metrics.registry("block_tokens")


def _sign(key: bytes, block_id: int, modes: str, expiry: int) -> bytes:
    msg = f"{block_id}:{modes}:{expiry}".encode()
    return hmac.new(key, msg, hashlib.sha256).digest()


class BlockTokenSecretManager:
    def __init__(self, lifetime_s: float = 600.0, roll_interval_s: float = 300.0):
        self.lifetime_s = lifetime_s
        self.roll_interval_s = roll_interval_s
        self._cur = os.urandom(32)
        self._prev = self._cur
        self._rolled = time.time()

    # ------------------------------------------------------------- NN side

    def maybe_roll(self) -> None:
        if time.time() - self._rolled >= self.roll_interval_s:
            self._prev, self._cur = self._cur, os.urandom(32)
            self._rolled = time.time()
            _M.incr("key_rolls")

    def keys(self) -> list[bytes]:
        """Exported keys for DN heartbeats (ExportedBlockKeys analog)."""
        return [self._cur, self._prev]

    def mint(self, block_id: int, modes: str = "r") -> dict:
        """Token for ``block_id`` allowing ``modes`` ('r', 'w', or 'rw')."""
        expiry = int(time.time() + self.lifetime_s)
        _M.incr("tokens_minted")
        return {"block_id": block_id, "modes": modes, "expiry": expiry,
                "sig": _sign(self._cur, block_id, modes, expiry)}

    # ------------------------------------------------------------- DN side


class BlockTokenVerifier:
    """DN-side verification against the NN-distributed key set."""

    def __init__(self):
        self._keys: list[bytes] = []

    def update_keys(self, keys: list[bytes]) -> None:
        self._keys = [bytes(k) for k in keys]

    @property
    def enabled(self) -> bool:
        return bool(self._keys)

    def mint(self, block_id: int, modes: str, lifetime_s: float = 600.0) -> dict | None:
        """DN-side minting for DN->DN transfer legs (the reference's DNs hold
        the same symmetric keys and mint transfer tokens the same way)."""
        if not self._keys:
            return None
        expiry = int(time.time() + lifetime_s)
        return {"block_id": block_id, "modes": modes, "expiry": expiry,
                "sig": _sign(self._keys[0], block_id, modes, expiry)}

    def verify(self, token: dict | None, block_id: int, mode: str) -> None:
        """Raise PermissionError unless ``token`` authorizes ``mode`` on
        ``block_id`` under a known key."""
        if not self.enabled:
            return  # tokens not enabled cluster-wide
        if token is None:
            _M.incr("tokens_missing")
            raise PermissionError(f"block token required for {mode} "
                                  f"on block {block_id}")
        try:
            ok = (int(token["block_id"]) == block_id
                  and mode in token["modes"]
                  and token["expiry"] >= time.time()
                  and any(hmac.compare_digest(
                      _sign(k, block_id, token["modes"], token["expiry"]),
                      bytes(token["sig"])) for k in self._keys))
        except (KeyError, TypeError, ValueError):
            ok = False
        if not ok:
            _M.incr("tokens_rejected")
            raise PermissionError(f"invalid block token for {mode} "
                                  f"on block {block_id}")
        _M.incr("tokens_verified")


# ---------------------------------------------------------------------------
# Data-transfer encryption (the datatransfer/sasl analog)
# ---------------------------------------------------------------------------
#
# The reference encrypts the block-data wire with SASL (DIGEST-MD5 privacy /
# AES via DataTransferSaslUtil), keyed by the block access token.  Same trust
# model here, modern construction: both ends hold the token's HMAC signature
# (the client got it from the NN inside the block locations; the DN recomputes
# it from the NN-distributed block keys), a two-nonce handshake proves both
# sides know it and derives per-direction ChaCha20-Poly1305 session keys
# (native/src/chacha20.cpp, RFC 8439), and every subsequent frame is an AEAD
# record with a counter nonce — tamper or replay fails the tag, not just a
# checksum.

HANDSHAKE_OP = "sasl_handshake"


def _hkdf(secret: bytes, *parts: bytes) -> bytes:
    msg = b"|".join(parts)
    return hmac.new(secret, msg, hashlib.sha256).digest()


def session_keys(secret: bytes, nonce_c: bytes, nonce_s: bytes):
    """(client->server key, server->client key, proof key) from the shared
    token secret + both nonces."""
    base = _hkdf(secret, b"hdrf-dt-v1", nonce_c, nonce_s)
    return (_hkdf(base, b"c2s"), _hkdf(base, b"s2c"), _hkdf(base, b"proof"))


def token_secret(token: dict) -> bytes:
    """The shared secret for a handshake: the token's HMAC signature."""
    return bytes(token["sig"])


class EncryptedSocket:
    """AEAD record layer over a connected socket.

    Implements the two calls the transport helpers use (``sendall`` and
    ``recv_into``), so proto/datatransfer.py and proto/rpc.py frame codecs
    compose unchanged.  Records: ``[u32 ct_len][ciphertext || tag]``; nonce =
    4-byte direction tag + 8-byte LE counter (never reused per key; replay or
    reordering fails the tag because the counter is the implicit AAD)."""

    _LEN = 4

    def __init__(self, sock, send_key: bytes, recv_key: bytes):
        self._sock = sock
        self._send_key = send_key
        self._recv_key = recv_key
        self._send_ctr = 0
        self._recv_ctr = 0
        self._rbuf = bytearray()

    @staticmethod
    def _nonce(direction: bytes, ctr: int) -> bytes:
        return direction + ctr.to_bytes(8, "little")

    def sendall(self, data: bytes) -> None:
        from hdrf_tpu import native

        sealed = native.aead_seal(self._send_key,
                                  self._nonce(b"dtx\0", self._send_ctr),
                                  b"", bytes(data))
        self._send_ctr += 1
        self._sock.sendall(len(sealed).to_bytes(4, "little") + sealed)

    def _read_record(self) -> None:
        from hdrf_tpu import native
        from hdrf_tpu.proto.rpc import recv_exact

        ln = int.from_bytes(recv_exact(self._sock, 4), "little")
        if ln < 16 or ln > (64 << 20):
            raise IOError(f"bad encrypted record length {ln}")
        sealed = recv_exact(self._sock, ln)
        pt = native.aead_open(self._recv_key,
                              self._nonce(b"dtx\0", self._recv_ctr),
                              b"", sealed)
        if pt is None:
            raise IOError("encrypted record failed authentication")
        self._recv_ctr += 1
        self._rbuf += pt

    def recv_into(self, view, n: int) -> int:
        while not self._rbuf:
            self._read_record()
        take = min(n, len(self._rbuf))
        view[:take] = self._rbuf[:take]
        del self._rbuf[:take]
        return take

    def recv(self, n: int) -> bytes:
        while not self._rbuf:
            self._read_record()
        take = min(n, len(self._rbuf))
        out = bytes(self._rbuf[:take])
        del self._rbuf[:take]
        return out

    # pass-throughs so existing call sites keep working
    def setsockopt(self, *a) -> None:
        self._sock.setsockopt(*a)

    def settimeout(self, t) -> None:
        self._sock.settimeout(t)

    def shutdown(self, how) -> None:
        self._sock.shutdown(how)

    def close(self) -> None:
        self._sock.close()


def client_handshake(sock, token: dict):
    """Negotiate encryption as the connecting side; returns EncryptedSocket.
    Order: client offers (token identity + nonce), server challenges with
    its nonce, client proves knowledge of the token secret FIRST (the server
    holds two rolled keys and picks whichever candidate secret matches),
    then the server proves its own knowledge.  The op frame and everything
    after it ride the encrypted channel."""
    from hdrf_tpu.proto.rpc import recv_frame, send_frame

    nonce_c = os.urandom(16)
    pub = {k: token[k] for k in ("block_id", "modes", "expiry")}
    send_frame(sock, [HANDSHAKE_OP, {"token": pub, "nonce": nonce_c}])
    ch = recv_frame(sock)
    if ch.get("status") != 0:
        raise PermissionError(f"handshake rejected: {ch.get('message')}")
    nonce_s = bytes(ch["nonce"])
    k_c2s, k_s2c, k_proof = session_keys(token_secret(token),
                                         nonce_c, nonce_s)
    transcript = nonce_c + nonce_s
    send_frame(sock, {"proof": hmac.new(k_proof, transcript + b"c",
                                        hashlib.sha256).digest()})
    fin = recv_frame(sock)
    if fin.get("status") != 0:
        raise PermissionError(f"handshake rejected: {fin.get('message')}")
    if not hmac.compare_digest(bytes(fin["proof"]),
                               hmac.new(k_proof, transcript + b"s",
                                        hashlib.sha256).digest()):
        raise PermissionError("server failed handshake proof")
    _M.incr("handshakes_client")
    return EncryptedSocket(sock, k_c2s, k_s2c)


def server_handshake(sock, fields: dict, keys: list[bytes]):
    """DN side, called when the first op frame is HANDSHAKE_OP (``fields``
    already read).  The token secret is its HMAC signature, which this side
    re-derives from the NN-distributed block keys (current or previous —
    the client's proof selects which); a client that cannot produce the
    proof holds no valid token and is refused before any data moves.
    Returns (EncryptedSocket, token dict with recovered sig) — the next
    frame on the encrypted channel is the real op."""
    from hdrf_tpu.proto.rpc import recv_frame, send_frame

    token = fields["token"]
    nonce_c = bytes(fields["nonce"])
    try:
        bid = int(token["block_id"])
        modes = token["modes"]
        expiry = int(token["expiry"])
    except (KeyError, TypeError, ValueError):
        send_frame(sock, {"status": 1, "message": "malformed token"})
        raise PermissionError("malformed token in handshake")
    if expiry < time.time():
        send_frame(sock, {"status": 1, "message": "expired token"})
        raise PermissionError("expired token in handshake")
    if not keys:
        send_frame(sock, {"status": 1, "message": "no block keys"})
        raise PermissionError("no block keys available for handshake")
    nonce_s = os.urandom(16)
    send_frame(sock, {"status": 0, "nonce": nonce_s})
    proof_c = bytes(recv_frame(sock)["proof"])
    transcript = nonce_c + nonce_s
    for k in keys:
        sig = _sign(k, bid, modes, expiry)
        k_c2s, k_s2c, k_proof = session_keys(sig, nonce_c, nonce_s)
        if hmac.compare_digest(proof_c,
                               hmac.new(k_proof, transcript + b"c",
                                        hashlib.sha256).digest()):
            send_frame(sock, {"status": 0,
                              "proof": hmac.new(k_proof, transcript + b"s",
                                                hashlib.sha256).digest()})
            _M.incr("handshakes_server")
            return (EncryptedSocket(sock, k_s2c, k_c2s),
                    {**token, "sig": sig})
    send_frame(sock, {"status": 1, "message": "bad proof"})
    _M.incr("handshakes_rejected")
    raise PermissionError("client failed handshake proof")


# ---------------------------------------------------------------------------
# Delegation tokens (security/token/delegation analog)
# ---------------------------------------------------------------------------


class DelegationTokenManager:
    """NN-side issue/renew/cancel/verify of delegation tokens
    (AbstractDelegationTokenSecretManager + DelegationTokenSecretManager).

    A token = identifier {owner, renewer, issue, max_date, seq, key_id} +
    password = HMAC(master_key, identifier).  Master keys roll; keys and
    token lifecycle events are JOURNALED by the NameNode (the reference
    persists DelegationKey and token ops in the edit log the same way), so
    a standby promoted mid-lifetime keeps verifying and renewing.  The
    Kerberos leg that bootstraps token issuance in the reference has no
    analog here — token issuance is open, the managed lifecycle is the
    capability re-expressed."""

    def __init__(self, renew_interval_s: float = 86400.0,
                 max_lifetime_s: float = 7 * 86400.0,
                 key_roll_s: float = 86400.0):
        self.renew_interval_s = renew_interval_s
        self.max_lifetime_s = max_lifetime_s
        self.key_roll_s = key_roll_s
        self._keys: dict[int, bytes] = {}
        self._key_times: dict[int, float] = {}
        self._next_key_id = 1
        self._next_seq = 1
        self._tokens: dict[int, dict] = {}  # seq -> {ident..., expiry}

    # -- journaled state transitions (called from NN._apply AND live path)

    def apply_key(self, key_id: int, key: bytes,
                  created: float = 0.0) -> None:
        self._keys[key_id] = bytes(key)
        self._key_times[key_id] = created
        self._next_key_id = max(self._next_key_id, key_id + 1)

    def apply_issue(self, ident: dict, expiry: float) -> None:
        self._tokens[ident["seq"]] = {**ident, "expiry": expiry}
        self._next_seq = max(self._next_seq, ident["seq"] + 1)

    def apply_renew(self, seq: int, expiry: float) -> None:
        if seq in self._tokens:
            self._tokens[seq]["expiry"] = expiry

    def apply_cancel(self, seq: int) -> None:
        self._tokens.pop(seq, None)

    def snapshot(self) -> dict:
        return {"keys": {i: k for i, k in self._keys.items()},
                "key_times": dict(self._key_times),
                "tokens": dict(self._tokens),
                "next_key_id": self._next_key_id,
                "next_seq": self._next_seq}

    def restore(self, snap: dict) -> None:
        self._keys = {int(i): bytes(k) for i, k in snap["keys"].items()}
        self._key_times = {int(i): float(t)
                           for i, t in snap.get("key_times", {}).items()}
        self._tokens = {int(s): dict(t) for s, t in snap["tokens"].items()}
        self._next_key_id = snap["next_key_id"]
        self._next_seq = snap["next_seq"]

    # -- live-path helpers (NN builds the records, journals, then applies)

    def need_key(self) -> tuple[int, bytes, float] | None:
        """(key_id, key, created) to journal when no master key exists or
        the newest one is due for a roll (the rolling DelegationKey — old
        keys stay until their tokens' max_date passes, so a roll never
        invalidates an outstanding token)."""
        if not self._keys or \
                time.time() - self._key_times.get(max(self._keys), 0) \
                >= self.key_roll_s:
            return self._next_key_id, os.urandom(32), time.time()
        return None

    def purge_expired(self) -> int:
        """Drop tokens past expiry and master keys no outstanding token can
        reference (ExpiredTokenRemover analog).  Purely in-memory and
        time-deterministic, so active and standby both run it without
        journal records; verification re-checks expiry anyway."""
        now = time.time()
        dead = [s for s, t in self._tokens.items() if t["expiry"] < now]
        for s in dead:
            del self._tokens[s]
        if self._keys:
            live_keys = {int(t["key_id"]) for t in self._tokens.values()}
            live_keys.add(max(self._keys))  # the signing key stays
            for kid in [k for k in self._keys if k not in live_keys]:
                del self._keys[kid]
                self._key_times.pop(kid, None)
        return len(dead)

    def build_identifier(self, owner: str, renewer: str) -> dict:
        now = time.time()
        return {"owner": owner, "renewer": renewer, "issue": now,
                "max_date": now + self.max_lifetime_s,
                "seq": self._next_seq, "key_id": max(self._keys)}

    def password(self, ident: dict) -> bytes:
        key = self._keys[int(ident["key_id"])]
        msg = (f"{ident['owner']}:{ident['renewer']}:{ident['issue']}:"
               f"{ident['max_date']}:{ident['seq']}:"
               f"{ident['key_id']}").encode()
        return hmac.new(key, msg, hashlib.sha256).digest()

    def verify(self, token: dict | None) -> str:
        """Returns the owner on success; raises PermissionError otherwise."""
        if token is None:
            raise PermissionError("delegation token required")
        try:
            ident = {k: token[k] for k in ("owner", "renewer", "issue",
                                           "max_date", "seq", "key_id")}
            live = self._tokens.get(int(token["seq"]))
            ok = (live is not None
                  and live["expiry"] >= time.time()
                  and int(token["key_id"]) in self._keys
                  and hmac.compare_digest(self.password(ident),
                                          bytes(token["password"])))
        except (KeyError, TypeError, ValueError):
            ok = False
        if not ok:
            _M.incr("dtokens_rejected")
            raise PermissionError("invalid or expired delegation token")
        return token["owner"]

    def check_renew(self, seq: int, renewer: str) -> float:
        """Validate a renewal and return the new expiry (to journal)."""
        t = self._tokens.get(int(seq))
        if t is None:
            raise PermissionError(f"unknown delegation token {seq}")
        if t["renewer"] != renewer:
            raise PermissionError(f"{renewer} may not renew token {seq}")
        return min(time.time() + self.renew_interval_s, t["max_date"])

    def check_cancel(self, seq: int, who: str) -> None:
        t = self._tokens.get(int(seq))
        if t is None:
            raise PermissionError(f"unknown delegation token {seq}")
        if who not in (t["owner"], t["renewer"]):
            raise PermissionError(f"{who} may not cancel token {seq}")
