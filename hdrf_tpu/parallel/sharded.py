"""Multi-chip sharded reduction over a ``jax.sharding.Mesh``.

The reference scales one logical object across nodes only via EC striping
(client DFSStripedOutputStream.java:81; DN-side StripedBlockReconstructor) and
scales the per-block hot loops across 2-3 CPU threads with hand-rolled
recursive thread spawns (DataDeduplicator.threadedHasher :536-650,
threadedStorer :652-845, DataConstructor.threadedConstructor :430-567).

Here the analogous capability is expressed TPU-natively with two mesh axes:

- ``seq`` — *sequence parallelism* over one block's byte axis: the Gear
  rolling-hash candidate scan (ops/gear.py) shards its positions across
  devices; each device needs the previous device's last ``WINDOW-1`` bytes, a
  halo that travels over ICI via ``lax.ppermute`` (the ring-attention-style
  neighbor exchange).  Because ``G[0] == 0`` (fmix32 preserves zero), the first
  shard's zero halo reproduces exactly the partial-window hashes of the
  single-device scan, so sharded output is bit-identical to ops.gear.
- ``data`` — *data parallelism* over independent blocks (and over SHA-256 lane
  tiles): no communication; the embarrassingly parallel axis.

Cross-device reductions (candidate counts, byte stats) ride ``psum``.
"""

from __future__ import annotations

import dataclasses
import functools
import threading

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.6 exports shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map

from hdrf_tpu.ops import gear
from hdrf_tpu.utils import device_ledger as _ledger
from hdrf_tpu.utils import fault_injection
from hdrf_tpu.utils import metrics as _metrics

WINDOW = gear.WINDOW
_HALO = WINDOW - 1

_MP = _metrics.registry("mesh_plane")


def _put_global(arr: np.ndarray, sharding) -> jax.Array:
    """Host array -> sharded jax.Array; in multi-process mode each rank
    feeds only its addressable shards (parallel/launch.py runs the host
    stages replicated, so every rank holds the same logical array).  The
    single H2D chokepoint of the sharded pipeline — ledger transfer
    accounting lives here so callers never double-count."""
    _ledger.transfer("h2d", "sharded.put", arr.nbytes)
    if jax.process_count() == 1:
        return jax.device_put(arr, sharding)
    return jax.make_array_from_callback(arr.shape, sharding,
                                        lambda idx: arr[idx])


def _fetch_global(x: jax.Array) -> np.ndarray:
    """Sharded jax.Array -> full numpy on every host (the host-side cut
    selection must see ALL candidate words regardless of process count)."""
    if jax.process_count() == 1:
        return np.asarray(x)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(x, tiled=True))


class _LruJitCache:
    """Bounded compiled-fn cache: (mesh, shape-key) tuples accumulate one
    entry per distinct mesh/bucket/pad combination, and a long-lived
    worker crossing many mesh shapes must not grow it without bound (r4
    verdict weak #3)."""

    def __init__(self, cap: int = 8):
        from collections import OrderedDict
        self._d = OrderedDict()
        self._cap = cap

    def get(self, key):
        fn = self._d.get(key)
        if fn is not None:
            self._d.move_to_end(key)
        return fn

    def put(self, key, fn) -> None:
        self._d[key] = fn
        self._d.move_to_end(key)
        while len(self._d) > self._cap:
            self._d.popitem(last=False)


def make_mesh(n_data: int = 1, n_seq: int | None = None,
              devices=None) -> Mesh:
    """A 2D ('data', 'seq') mesh over ``devices`` (default: all devices)."""
    devices = list(devices if devices is not None else jax.devices())
    if n_seq is None:
        n_seq = len(devices) // n_data
    if n_data * n_seq != len(devices):
        raise ValueError(f"mesh {n_data}x{n_seq} != {len(devices)} devices")
    arr = np.array(devices).reshape(n_data, n_seq)
    return Mesh(arr, ("data", "seq"))


def _local_candidate_words(local: jax.Array, mask: jax.Array,
                           n_seq: int) -> tuple[jax.Array, jax.Array]:
    """Per-shard candidate bitmap words for a seq-sharded block.

    local: u8[m] — this device's byte range (m % 256 == 0).
    Returns (u32[m/32] packed candidate words, i32[] local candidate count).
    """
    m = local.shape[0]
    idx = jax.lax.axis_index("seq")
    # Halo: last WINDOW-1 bytes of the previous shard (zeros for shard 0 —
    # ppermute leaves unaddressed targets zero-filled, which is exactly the
    # zero-pad the single-device scan uses).  The halo-prefixed scan yields
    # full-window hashes for every local position; the first _HALO outputs
    # belong to the previous shard and are dropped by scanning the
    # concatenation and packing only the local tail.
    halo = jax.lax.ppermute(local[-_HALO:], "seq",
                            [(i, i + 1) for i in range(n_seq - 1)])
    ext = jnp.concatenate([halo, local])
    t = gear._gear_map(ext)
    h = gear._doubling_hashes(t)[_HALO:]  # full-window hash per local position
    base = (idx * m).astype(jnp.uint32)
    pos1 = base + jnp.arange(1, m + 1, dtype=jnp.uint32)
    is_cand = ((h & mask) == 0) & (pos1 >= WINDOW)
    words = gear.pack_bitmap_words(is_cand)
    return words, jnp.sum(is_cand.astype(jnp.int32))


def candidate_words_sharded(mesh: Mesh, fused: str | None = None):
    """Jitted all-position Gear candidate scan, byte axis sharded over 'seq'.

    Returns ``fn(block u8[N], mask u32) -> (words u32[N/32], count i32)`` with
    the block sharded P('seq'); words come back with the same layout.  Output
    is bit-identical to the single-device ops.gear._candidate_words bitmap.

    ``fused`` routes the per-shard scan through the fused Pallas kernel
    (ops/cdc_pallas.py) instead of the XLA doubling scan — same halo, same
    packed-bitmap contract, asserted bit-identical in tests/test_cdc_pallas.py.
    None resolves via cdc_pallas.cdc_pallas_mode() ('off' on the CPU mesh).
    """
    from hdrf_tpu.ops import cdc_pallas

    n_seq = mesh.shape["seq"]
    if fused is None:
        fused = cdc_pallas.cdc_pallas_mode()

    kw = {}
    if fused != "off":
        interp = fused == "interpret"
        # shard_map has no replication rule for pallas_call; the psum below
        # makes the count output replicated by construction, so the check
        # is safely skipped on the fused route.
        kw["check_rep"] = False

        def scan(block: jax.Array, mask: jax.Array):
            words, cnt = cdc_pallas.local_candidate_words_pallas(
                block, mask, n_seq, interpret=interp)
            return words, jax.lax.psum(cnt, "seq")
    else:
        def scan(block: jax.Array, mask: jax.Array):
            words, cnt = _local_candidate_words(block, mask, n_seq)
            return words, jax.lax.psum(cnt, "seq")

    fn = _shard_map(scan, mesh=mesh, in_specs=(P("seq"), P()),
                    out_specs=(P("seq"), P()), **kw)
    return jax.jit(fn)


def sha256_lanes_sharded(mesh: Mesh):
    """SHA-256 lane hashing with lanes sharded over the 'data' axis.

    Pure data parallelism: ``fn(blocks u8[L, B*64], nblocks i32[L]) ->
    u8[L, 32]``; L must be a multiple of 128 * mesh.shape['data'].
    """
    from hdrf_tpu.ops import sha256 as sha

    def hash_local(blocks_u8: jax.Array, nblocks: jax.Array) -> jax.Array:
        return sha.sha256_lanes(blocks_u8, nblocks)

    fn = _shard_map(hash_local, mesh=mesh,
                    in_specs=(P("data"), P("data")), out_specs=P("data"))
    return jax.jit(fn)


# --------------------------------------------------------------------------
# Full sharded reduction step (what the driver's dryrun compiles + runs)
# --------------------------------------------------------------------------

def _segment_sha_pad(seg: int) -> np.ndarray:
    """The constant SHA-256 terminal block for a fixed ``seg``-byte message
    (seg % 64 == 0): 0x80 marker + big-endian bit length."""
    pad = np.zeros(64, dtype=np.uint8)
    pad[0] = 0x80
    pad[56:64] = np.frombuffer(np.uint64(seg * 8).byteswap().tobytes(),
                               dtype=np.uint8)
    return pad


def reduction_step(mesh: Mesh, seg: int = 512):
    """The full per-batch reduction forward, sharded over ('data', 'seq').

    Input ``blocks u8[B, N]``: B blocks data-parallel over 'data', each
    block's N bytes sequence-parallel over 'seq'.  Per block the step runs

    1. the Gear CDC candidate scan with ICI halo exchange (``ppermute``),
    2. SHA-256 fingerprints of the block's fixed ``seg``-byte segments
       (the jit-static stand-in for variable CDC chunks, whose SHA padding
       is data-dependent and therefore host-side in the serving path),
    3. global stats via ``psum`` over both axes.

    Returns ``fn(blocks) -> dict(words, digests, candidates)``; everything
    stays device-resident, sharded P('data','seq').
    """
    from hdrf_tpu.ops import sha256 as sha

    n_seq = mesh.shape["seq"]
    pad_const = _segment_sha_pad(seg)

    def step(blocks: jax.Array, mask: jax.Array):
        b_local, m = blocks.shape
        words, counts = jax.vmap(
            lambda blk: _local_candidate_words(blk, mask, n_seq))(blocks)
        # Fixed-size segment fingerprints: (lanes, seg) + constant pad block.
        lanes = blocks.reshape(-1, seg)
        n_lanes = lanes.shape[0]
        lane_pad = (-n_lanes) % 128
        lanes = jnp.pad(lanes, ((0, lane_pad), (0, 0)))
        msgs = jnp.concatenate(
            [lanes, jnp.broadcast_to(jnp.asarray(pad_const),
                                     (lanes.shape[0], 64))], axis=1)
        nblocks = jnp.where(jnp.arange(lanes.shape[0]) < n_lanes,
                            seg // 64 + 1, 0).astype(jnp.int32)
        digests = sha.sha256_lanes(msgs, nblocks)[:n_lanes]
        digests = digests.reshape(b_local, m // seg, 32)
        total = jax.lax.psum(jax.lax.psum(jnp.sum(counts), "seq"), "data")
        return {"words": words, "digests": digests, "candidates": total}

    fn = _shard_map(step, mesh=mesh,
                    in_specs=(P("data", "seq"), P()),
                    out_specs={"words": P("data", "seq"),
                               "digests": P("data", "seq"),
                               "candidates": P()})
    return jax.jit(fn)


# --------------------------------------------------------------------------
# The REAL variable-chunk pipeline, sharded (the serving path's multi-chip
# form: seq-parallel candidate scan -> host cut select -> chunk-parallel
# SHA over the actual CDC chunks, lanes spread across every device)
# --------------------------------------------------------------------------

_sha_fns = _LruJitCache()


def _sha_chunks_sharded(mesh: Mesh, bucket: int, pad_words: int):
    """Variable-chunk SHA with lanes sharded over the FLATTENED mesh.  The
    block arrives SEQ-SHARDED (the same resident shards the candidate scan
    used — one H2D total); each device all-gathers the full byte image
    over ICI, word-images it, and DMA/gathers + hashes its own lane
    subset.  Chunk fingerprints are embarrassingly parallel once cuts are
    known; the all_gather is the only collective."""
    from hdrf_tpu.ops.resident import _bucket_sha, be_word_image

    key = (mesh, bucket, pad_words)  # Mesh hashes by devices+axis names
    fn = _sha_fns.get(key)
    if fn is not None:
        return fn
    axes = tuple(mesh.axis_names)

    def local(block_shard: jax.Array, ol: jax.Array) -> jax.Array:
        full = jax.lax.all_gather(block_shard, "seq", tiled=True)
        words = jnp.concatenate([be_word_image(full),
                                 jnp.zeros(pad_words, jnp.uint32)])
        return _bucket_sha(words, ol, bucket)

    fn = jax.jit(_shard_map(
        local, mesh=mesh,
        in_specs=(P("seq"), P(None, axes)), out_specs=P(axes)))
    _sha_fns.put(key, fn)
    return fn


_sha_halo_fns = _LruJitCache()


def _sha_chunks_halo(mesh: Mesh, bucket: int, pad_words: int,
                     halo_shards: int):
    """Data-LOCAL sharded SHA: each chunk is hashed by a device at its
    OWNING seq position, whose image is its own shard plus ``halo_shards``
    neighbor shards fetched with a ppermute ring walk — ICI traffic is
    halo_shards x (block/n_seq) per device instead of the full-image
    all_gather's (n_seq-1) x (block/n_seq) (the r3 verdict's economics
    note; the halo pattern is the scaling-book neighbor-exchange recipe,
    same as the candidate scan's WINDOW halo).  Over-read bytes past a
    chunk (next chunks' data, or ring-wrapped bytes on the last shard)
    are masked by _bucket_sha's SHA-padding splice, so output stays
    bit-identical.  Lanes land as (n_data, n_seq, Lmax) blocks; the host
    unpermutes digests by its own owner assignment."""
    from hdrf_tpu.ops.resident import _bucket_sha, be_word_image

    key = (mesh, bucket, pad_words, halo_shards)
    fn = _sha_halo_fns.get(key)
    if fn is not None:
        return fn
    n_seq = mesh.shape["seq"]
    perm = [(i, (i - 1) % n_seq) for i in range(n_seq)]  # fetch NEXT shard

    def local(block_shard: jax.Array, ol: jax.Array) -> jax.Array:
        parts = [block_shard]
        cur = block_shard
        for _ in range(halo_shards):
            cur = jax.lax.ppermute(cur, "seq", perm)
            parts.append(cur)
        img = jnp.concatenate(parts)
        words = jnp.concatenate([be_word_image(img),
                                 jnp.zeros(pad_words, jnp.uint32)])
        return _bucket_sha(words, ol[0, 0], bucket)

    fn = jax.jit(_shard_map(
        local, mesh=mesh,
        in_specs=(P("seq"), P("data", "seq")),
        out_specs=P(("data", "seq"))))
    _sha_halo_fns.put(key, fn)
    return fn


def reduce_sharded(data: bytes | np.ndarray, cdc, mesh: Mesh):
    """(cuts, digests) for ONE block with every stage on the mesh — the
    multi-chip form of ops.dispatch.chunk_and_fingerprint, bit-identical
    to the native oracle (asserted in tests/test_sharding.py and the
    driver's dryrun):

    1. all-position Gear candidate scan, byte axis sharded over 'seq' with
       the ppermute halo exchange (ICI neighbor traffic);
    2. host cut selection over the sparse candidates (O(chunks) control
       flow — data-dependent, so host-side, same as single-device);
    3. SHA-256 of the actual VARIABLE chunks, lanes sharded across every
       device; the byte image reaches each chip via an ICI all_gather of
       the SAME seq-sharded resident bytes stage 1 used — the block
       crosses the host->device boundary exactly once.
    """
    from hdrf_tpu import native
    from hdrf_tpu.ops.dispatch import gear_mask
    from hdrf_tpu.ops.resident import _bucket_of

    a = (np.frombuffer(data, dtype=np.uint8)
         if not isinstance(data, np.ndarray) else data)
    n = a.size
    if n == 0:  # same contract as ResidentReducer's n==0 special case
        return np.empty(0, dtype=np.uint64), np.empty((0, 32), np.uint8)
    assert n < (1 << 31), "i32 lane offsets: shard blocks beyond 2 GiB"
    mask = gear_mask(cdc)
    n_seq = mesh.shape["seq"]
    # one padded image serves BOTH stages: shard-size granularity for the
    # scan (each seq shard % 256) and the word-image grid (% 512)
    grid = 512 * n_seq
    buf = np.zeros(n + ((-n) % grid), dtype=np.uint8)
    buf[:n] = a
    block_sh = _put_global(buf, NamedSharding(mesh, P("seq")))
    from hdrf_tpu.ops.cdc_pallas import cdc_pallas_mode
    scan_mode = cdc_pallas_mode()
    ev = _ledger.dispatch("sharded.scan", key=(buf.size, n_seq, scan_mode))
    words, _ = candidate_words_sharded(mesh, fused=scan_mode)(
        block_sh, jnp.uint32(mask & 0xFFFFFFFF))
    wv = _fetch_global(words)
    _ledger.readback(ev, d2h_bytes=wv.nbytes)
    (idx,) = np.nonzero(wv)
    vals = wv[idx]
    # Skip-ahead dead-zone filter (gear.skip_ahead_threshold): candidates
    # below max(WINDOW, min_chunk) can never be selected — every window
    # opens at prev+min — so dropping them before the unpack+select walk
    # is provably cut-identical and shrinks the O(candidates) host leg.
    # Applied to the SPARSE (idx, vals) pairs, not the dense bitmap (the
    # fetched word image may be a read-only view of device memory); the
    # packed-bitmap D2H contract above stays untouched (the scan-only
    # kernel and gear_candidates_sharded keep their bit-identity tests).
    thr = gear.skip_ahead_threshold(cdc.min_chunk)
    if thr > gear.MIN_CANDIDATE_POS1 and idx.size:
        w_t, rem = divmod(thr - 1, 32)
        keep = idx >= w_t
        if rem:
            at = np.nonzero(idx == w_t)[0]
            if at.size:
                vals[at] &= np.uint32((0xFFFFFFFF << rem) & 0xFFFFFFFF)
                keep[at] = vals[at] != 0
        idx, vals = idx[keep], vals[keep]
    pos = gear._words_to_positions(idx.astype(np.uint32), vals, n)
    cuts = native.cdc_select(pos, n, cdc.min_chunk, cdc.max_chunk)
    starts = np.concatenate([[0], cuts[:-1]]).astype(np.int64)
    lens = (cuts - starts).astype(np.int64)
    nchunks = len(cuts)
    ndev = int(np.prod([mesh.shape[ax] for ax in mesh.axis_names]))
    # one bucket sized for max_chunk: a stable jit key across blocks (the
    # single-device path's finer bucketing is a padded-FLOPs optimization,
    # not a correctness requirement)
    bucket = _bucket_of((cdc.max_chunk + 9 + 63) // 64)
    pad_words = -(-(bucket * 16 + 16) // 128) * 128
    n_data, n_seq = mesh.shape["data"], mesh.shape["seq"]
    shard_bytes = buf.size // n_seq
    # halo shards covering one full gather window past a shard boundary
    halo = -(-(bucket * 64 + 64) // shard_bytes)
    if halo < n_seq - 1:
        # DATA-LOCAL SHA: each chunk hashed at its owning seq position
        # (+round-robin over 'data'), image = own shard + ppermute halo —
        # ICI bytes per device drop from (n_seq-1) to `halo` shards
        # vectorized owner assignment (a 1 GiB block has ~131k chunks;
        # python-loop assignment would stall the pipeline between
        # dispatches): rank chunks within their seq shard, round-robin
        # the rank across 'data', lane index = rank // n_data
        owner_seq = np.minimum(starts // shard_bytes,
                               n_seq - 1).astype(np.int64)
        counts = np.bincount(owner_seq, minlength=n_seq)
        order = np.argsort(owner_seq, kind="stable")
        group_base = np.cumsum(counts) - counts
        rank = np.empty(nchunks, dtype=np.int64)
        rank[order] = (np.arange(nchunks)
                       - np.repeat(group_base, counts))
        d_arr = rank % n_data
        j_arr = rank // n_data
        # jit shape key: quantize the per-cell lane count to power-of-two
        # 128-lane steps — a data-dependent exact lmax would retrace per
        # block (the stable-key property the bucket choice exists for)
        max_cell = max(int(j_arr.max()) + 1 if nchunks else 1, 1)
        lmax = 128 << max(0, (max_cell - 1).bit_length() - 7) \
            if max_cell > 128 else 128
        ol_all = np.zeros((n_data, n_seq, 2, lmax), dtype=np.int32)
        ol_all[d_arr, owner_seq, 0, j_arr] = starts - owner_seq * shard_bytes
        ol_all[d_arr, owner_seq, 1, j_arr] = lens
        fn = _sha_chunks_halo(mesh, bucket, pad_words, halo)
        ol_dev = _put_global(
            ol_all, NamedSharding(mesh, P("data", "seq")))
        ev = _ledger.dispatch("sharded.sha", batch=nchunks,
                              key=(bucket, lmax, halo))
        out = _fetch_global(fn(block_sh, ol_dev))
        _ledger.readback(ev, d2h_bytes=out.nbytes)
        digests = out[(d_arr * n_seq + owner_seq) * lmax + j_arr]
        return cuts, digests
    # tiny blocks / shards smaller than the gather window: the halo walk
    # would re-build the full image anyway — all_gather is the right tool
    lane_grid = 128 * ndev
    L = max(-(-nchunks // lane_grid) * lane_grid, lane_grid)
    ol = np.zeros((2, L), dtype=np.int32)
    ol[0, :nchunks] = starts
    ol[1, :nchunks] = lens
    fn = _sha_chunks_sharded(mesh, bucket, pad_words)
    ol_dev = _put_global(
        ol, NamedSharding(mesh, P(None, tuple(mesh.axis_names))))
    ev = _ledger.dispatch("sharded.sha", batch=nchunks, key=(bucket, L))
    digests = _fetch_global(fn(block_sh, ol_dev))
    _ledger.readback(ev, d2h_bytes=digests.nbytes)
    digests = digests[:nchunks]
    return cuts, digests


def gear_candidates_sharded(data: bytes | np.ndarray, mask: int,
                            mesh: Mesh) -> np.ndarray:
    """Host-facing sharded candidate scan; same contract (and bit-identical
    output) as ops.gear.gear_candidates_jax, bytes spread over mesh['seq']."""
    a = np.frombuffer(data, dtype=np.uint8) if not isinstance(data, np.ndarray) else data
    n = a.size
    n_seq = mesh.shape["seq"]
    chunk = 256 * n_seq
    padded = n + ((-n) % chunk)
    buf = np.zeros(padded, dtype=np.uint8)
    buf[:n] = a
    sharding = NamedSharding(mesh, P("seq"))
    block = _put_global(buf, sharding)
    fn = candidate_words_sharded(mesh)
    words, _ = fn(block, jnp.uint32(mask & 0xFFFFFFFF))
    wv = _fetch_global(words)
    (idx,) = np.nonzero(wv)
    pos = gear._words_to_positions(idx.astype(np.uint32), wv[idx], n)
    return pos


# --------------------------------------------------------------------------
# Mesh-sharded reduction plane: a coalesced write-pipeline group becomes ONE
# ledger-visible dispatch per mesh step.  Blocks are data-parallel over
# 'data'; each device runs CDC cut selection, SHA-256 of both lane buckets,
# and its partition of the dedup bucket probe; an all_gather + psum makes
# every probe verdict replicated.  The serial ResidentReducer stays verbatim
# as the bit-identity oracle (asserted in tests/test_mesh_plane.py).
# --------------------------------------------------------------------------


def _select_cuts_dev(cw: jax.Array, true_n: jax.Array, mn: int, mx: int,
                     cap: int) -> tuple[jax.Array, jax.Array]:
    """Device-side greedy CDC cut selection over the packed candidate
    bitmap — bit-identical to native.cdc_select (cdc.cpp:73-88): per chunk
    the cut is the first candidate in [prev+min, min(prev+max, n)], else
    the upper bound; the final cut is always ``n``.

    A ``lax.scan`` walks the 32-position bitmap words carrying (prev cut,
    emitted count, cut table); an inner static loop of C iterations emits
    every cut that can land inside one word (cuts advance >= min_chunk
    apart, plus one short final chunk, so C = 32//min + 2 bounds it).  The
    zero-pad tail past ``true_n`` is a dense candidate region (the gear
    hash of zeros is zero) but can never be selected: candidates must sit
    <= hi <= true_n.  Returns (cuts i32[cap] ascending, count i32)."""
    nw = cw.shape[0]
    C = max(1, min(32, 32 // max(mn, 1) + 2))
    tn = true_n.astype(jnp.int32)

    def step(carry, xw):
        prev, cnt, tbl = carry
        widx, w = xw
        base = widx * 32
        word_end = base + 32
        for _ in range(C):
            active = prev < tn
            lo = prev + mn
            hi = jnp.minimum(prev + mx, tn)
            sh = jnp.clip(lo - base - 1, 0, 32)
            keep = jnp.where(
                sh >= 32, jnp.uint32(0),
                jnp.uint32(0xFFFFFFFF)
                << jnp.minimum(sh, 31).astype(jnp.uint32))
            wm = w & keep
            low = wm & (~wm + jnp.uint32(1))     # lowest set bit
            bitpos = jnp.int32(31) - jax.lax.clz(low).astype(jnp.int32)
            cand_pos = base + bitpos + 1         # bit k <-> pos1 = base+k+1
            has_cand = (wm != jnp.uint32(0)) & (cand_pos <= hi)
            # Forced cut at hi fires only in hi's own word: an earlier word
            # cannot rule out candidates it does not cover.  No lo <= hi
            # guard — a tail shorter than min_chunk still cuts at n.
            forced = active & ~has_cand & (hi <= word_end)
            emit = active & (has_cand | forced)
            cut = jnp.where(has_cand, cand_pos, hi)
            tbl = tbl.at[jnp.where(emit, cnt, cap)].set(cut, mode="drop")
            cnt = cnt + emit.astype(jnp.int32)
            prev = jnp.where(emit, cut, prev)
        return (prev, cnt, tbl), None

    init = (jnp.int32(0), jnp.int32(0), jnp.zeros((cap,), jnp.int32))
    # Modest unroll amortizes XLA:CPU's per-iteration scan overhead (the
    # dominant cost for small blocks); full unroll risks the compile
    # blowups PERF_NOTES warns about, 8 stays well clear.
    (_, cnt, tbl), _ = jax.lax.scan(
        step, init, (jnp.arange(nw, dtype=jnp.int32), cw),
        unroll=min(nw, 8))
    return tbl, cnt


def _fp_hi_lo(fp_u8: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """First 8 digest bytes as two big-endian u32 keys — the numpy mirror
    of the on-mesh probe's key math (MUST stay bit-identical to the step
    fn and the ShardedBucketTable refresh)."""
    u = fp_u8.astype(np.uint32)
    hi = (u[:, 0] << 24) | (u[:, 1] << 16) | (u[:, 2] << 8) | u[:, 3]
    lo = (u[:, 4] << 24) | (u[:, 5] << 16) | (u[:, 6] << 8) | u[:, 7]
    return hi, lo


_PROBE_MULT = 2654435761  # Knuth multiplicative hash, u32 wraparound


_mesh_step_fns = _LruJitCache()


def _mesh_step(mesh: Mesh, Kl: int, n_pad: int, mn: int, mx: int,
               b_small: int, b_big: int, Ls: int, Lb: int, cap: int,
               S: int):
    """Compiled mesh-step fn: ``fn(blocks u8[K, n_pad] P('data', None),
    true_ns i32[K] P('data'), mask u32 P(), table u32[ndata, S, 2]
    P('data')) -> (cuts i32[K, cap], counts i32[K], digs u8[K*(Ls+Lb), 32],
    hits i32[K*(Ls+Lb)] replicated)``.

    One dispatch runs, per device: candidate bitmap -> cut-select scan ->
    two-bucket lane binning -> SHA-256 -> all_gather(digests) -> local
    bucket-partition probe -> psum(hit votes).  ``donate_argnums=(0,)``
    recycles the group's HBM block buffer so memory stays flat across
    steps.  The host reconstructs chunk order from the SAME binning rule
    (small = padded SHA block count <= b_small, rank by running count).

    ``Lb == 0`` means the geometry proves every chunk small
    ((max_chunk+72)//64 <= b_small): the big SHA leg is elided at trace
    time — for small-block geometries that leg is pure 128-lane-floor
    padding and dominates the per-device compute."""
    key = (mesh, Kl, n_pad, mn, mx, b_small, b_big, Ls, Lb, cap, S)
    fn = _mesh_step_fns.get(key)
    if fn is not None:
        return fn
    from hdrf_tpu.ops.resident import be_word_image, sha_pad_messages
    from hdrf_tpu.ops.sha256 import sha256_words

    ndata = mesh.shape["data"]
    pw = -(-(b_big * 16 + 16) // 128) * 128   # gather window never clamps
    stride_b = (n_pad // 4 + pw) * 4
    # sha256_words hashes lanes on a 128-lane grid; round the per-DEVICE
    # lane totals up to it (not each block's stride — that multiplied the
    # padding by Kl).  Grid-pad lanes hash zero-length messages and their
    # digest rows are sliced off before the all_gather.
    Lst = -(-(Kl * Ls) // 128) * 128
    Lbt = -(-(Kl * Lb) // 128) * 128 if Lb else 0

    def sha_words(words, ol, bucket):
        msgs, nb = sha_pad_messages(words, ol, bucket)
        if jax.default_backend() == "cpu":
            return sha256_words(msgs, nb.astype(jnp.int32))
        from hdrf_tpu.ops.sha256_pallas import sha256_words_pallas

        return sha256_words_pallas(msgs, nb.astype(jnp.int32))

    # Static skip-ahead word mask (gear.skip_ahead_threshold): bitmap words
    # wholly below max(WINDOW, min_chunk) carry only dead candidates (every
    # select window opens at prev+min), so ANDing them out is provably
    # cut-identical and lets the select scan's first windows skip over
    # guaranteed-empty words.  Static per geometry — part of this fn's
    # cache key already (``mn``).
    _thr = gear.skip_ahead_threshold(mn)
    _wt, _rem = divmod(_thr - 1, 32)
    _wmask = np.full(n_pad // 32, 0xFFFFFFFF, np.uint32)
    _wmask[:min(_wt, _wmask.size)] = 0
    if _rem and _wt < _wmask.size:
        _wmask[_wt] = (0xFFFFFFFF << _rem) & 0xFFFFFFFF

    def step(blocks, tns, mask, table):
        cw = jax.vmap(lambda b: gear.candidate_bitmap_words(b, mask))(blocks)
        cw = cw & jnp.asarray(_wmask)[None, :]
        cuts, counts = jax.vmap(
            lambda w, t: _select_cuts_dev(w, t, mn, mx, cap))(cw, tns)
        starts = jnp.concatenate(
            [jnp.zeros((Kl, 1), jnp.int32), cuts[:, :-1]], axis=1)
        j = jnp.arange(cap, dtype=jnp.int32)[None, :]
        valid = j < counts[:, None]
        lens = jnp.where(valid, cuts - starts, 0)
        starts = jnp.where(valid, starts, 0)
        nb = (lens + 9 + 63) // 64
        small = valid & (nb <= b_small)
        big = valid & ~small
        r_s = jnp.cumsum(small.astype(jnp.int32), axis=1) - 1
        r_b = jnp.cumsum(big.astype(jnp.int32), axis=1) - 1
        karr = jnp.arange(Kl, dtype=jnp.int32)[:, None]
        flat = (karr * stride_b + starts).reshape(-1)
        lens_f = lens.reshape(-1)
        rows_s = jnp.where(small, karr * Ls + r_s, Lst).reshape(-1)
        ol_s = jnp.zeros((2, Lst), jnp.int32)
        ol_s = ol_s.at[0, rows_s].set(flat, mode="drop")
        ol_s = ol_s.at[1, rows_s].set(lens_f, mode="drop")
        imgs = jnp.pad(jax.vmap(be_word_image)(blocks), ((0, 0), (0, pw)))
        words = imgs.reshape(-1)
        if Lb:
            rows_b = jnp.where(big, karr * Lb + r_b, Lbt).reshape(-1)
            ol_b = jnp.zeros((2, Lbt), jnp.int32)
            ol_b = ol_b.at[0, rows_b].set(flat, mode="drop")
            ol_b = ol_b.at[1, rows_b].set(lens_f, mode="drop")
            digs = jnp.concatenate(
                [sha_words(words, ol_s, b_small)[:Kl * Ls],
                 sha_words(words, ol_b, b_big)[:Kl * Lb]], axis=0)
        else:
            digs = sha_words(words, ol_s, b_small)[:Kl * Ls]
        # On-mesh dedup probe: every device sees every fingerprint (the
        # all_gather), answers only for its own partition of fingerprint
        # space (hi % ndata), and the psum replicates the verdicts.  Only
        # the two probe-key words (digest bytes 0-7) cross the mesh — a
        # 4x smaller gather than shipping full 32-byte digest rows.
        d8 = digs[:, :8].astype(jnp.uint32)
        keys = jnp.stack(
            [(d8[:, 0] << 24) | (d8[:, 1] << 16) | (d8[:, 2] << 8) | d8[:, 3],
             (d8[:, 4] << 24) | (d8[:, 5] << 16) | (d8[:, 6] << 8) | d8[:, 7]],
            axis=1)
        gath = jax.lax.all_gather(keys, "data", tiled=True)
        hi = gath[:, 0]
        lo = gath[:, 1]
        mine = hi % jnp.uint32(ndata) == \
            jax.lax.axis_index("data").astype(jnp.uint32)
        slot = ((lo * jnp.uint32(_PROBE_MULT)) ^ hi) % jnp.uint32(S)
        ent = table[0, slot]
        hit = mine & (ent[:, 0] == hi) & (ent[:, 1] == lo)
        hits = jax.lax.psum(hit.astype(jnp.int32), "data")
        return cuts, counts, digs, hits

    fn = jax.jit(_shard_map(
        step, mesh=mesh,
        in_specs=(P("data", None), P("data"), P(), P("data", None, None)),
        out_specs=(P("data", None), P("data"), P("data", None), P()),
        check_rep=False), donate_argnums=(0,))
    _mesh_step_fns.put(key, fn)
    return fn


_bucket_upd_fns = _LruJitCache()


def _bucket_upd_fn(mesh: Mesh, R: int, S: int):
    """Incremental sharded bucket-table refresh: rows u32[R, 4] of
    (owner, slot, hi, lo) arrive replicated; each device scatters only its
    own rows (others drop out of bounds).  The table buffer is donated so
    the refresh recycles HBM in place."""
    key = (mesh, R, S)
    fn = _bucket_upd_fns.get(key)
    if fn is not None:
        return fn

    def upd(tbl, rows):
        mine = rows[:, 0].astype(jnp.int32) == jax.lax.axis_index("data")
        slot = jnp.where(mine, rows[:, 1].astype(jnp.int32), S)
        tbl = tbl.at[0, slot, 0].set(rows[:, 2], mode="drop")
        tbl = tbl.at[0, slot, 1].set(rows[:, 3], mode="drop")
        return tbl

    fn = jax.jit(_shard_map(
        upd, mesh=mesh,
        in_specs=(P("data", None, None), P()),
        out_specs=P("data", None, None), check_rep=False),
        donate_argnums=(0,))
    _bucket_upd_fns.put(key, fn)
    return fn


class ShardedBucketTable:
    """Device-resident dedup fingerprint buckets, fingerprint space
    partitioned over the 'data' axis (owner = hi % ndata, slot = Knuth
    multiplicative hash of the 64-bit digest prefix).

    The table is a PROBE ACCELERATOR, not an authority: the ChunkIndex
    commit listener feeds new fingerprints through :meth:`note_new`, and a
    pending batch flushes to the device right before each mesh step.  A
    stale or collided entry can only produce a false positive (resolved by
    the host's authoritative index re-check) or a false negative (the
    chunk is appended again; ChunkIndex.commit_block keeps the first
    commit and the orphan bytes are reclaimed by compaction) — never
    corruption.  A failed refresh (fault point ``sharded.bucket_refresh``)
    re-queues the pending rows and the step runs with the stale table."""

    def __init__(self, mesh: Mesh, slots: int = 1 << 15):
        self.mesh = mesh
        self.ndata = mesh.shape["data"]
        self.slots = int(slots)
        self._sharding = NamedSharding(mesh, P("data"))
        self._np = np.full((self.ndata, self.slots, 2), 0xFFFFFFFF,
                           np.uint32)
        self._dev: jax.Array | None = None
        self._pending: list[bytes] = []
        self._lock = threading.Lock()

    def note_new(self, fingerprints) -> None:
        """Buffer newly committed chunk fingerprints (>= 8 bytes each) for
        the next refresh.  Called from the ChunkIndex commit listener."""
        with self._lock:
            self._pending.extend(bytes(f) for f in fingerprints)

    def _keys(self, fp_rows: np.ndarray):
        hi, lo = _fp_hi_lo(fp_rows)
        owner = hi % np.uint32(self.ndata)
        slot = ((lo * np.uint32(_PROBE_MULT)) ^ hi) % np.uint32(self.slots)
        return owner, slot, hi, lo

    def host_probe(self, digests: np.ndarray) -> np.ndarray:
        """Numpy mirror of the on-mesh probe (tests pin the two agree)."""
        owner, slot, hi, lo = self._keys(digests)
        ent = self._np[owner, slot]
        return (ent[:, 0] == hi) & (ent[:, 1] == lo)

    def flush(self) -> None:
        with self._lock:
            pend, self._pending = self._pending, []
        if not pend:
            return
        try:
            fault_injection.point("sharded.bucket_refresh", rows=len(pend))
        except Exception:
            with self._lock:
                self._pending = pend + self._pending
            _MP.incr("bucket_refresh_failures")
            return
        fps = np.frombuffer(b"".join(p[:8] for p in pend),
                            np.uint8).reshape(-1, 8)
        owner, slot, hi, lo = self._keys(fps)
        self._np[owner, slot, 0] = hi
        self._np[owner, slot, 1] = lo
        _MP.incr("bucket_refresh_rows", len(pend))
        if self._dev is None:
            return
        R = max(8, 1 << (len(pend) - 1).bit_length())  # stable jit keys
        rows = np.full((R, 4), self.ndata, np.uint32)  # pad rows drop
        rows[:len(pend), 0] = owner
        rows[:len(pend), 1] = slot
        rows[:len(pend), 2] = hi
        rows[:len(pend), 3] = lo
        _ledger.dispatch("sharded.bucket_refresh", batch=len(pend),
                         h2d_bytes=rows.nbytes, key=(R, self.slots))
        self._dev = _bucket_upd_fn(self.mesh, R, self.slots)(
            self._dev, _put_global(rows, NamedSharding(self.mesh, P())))

    def device_table(self) -> jax.Array:
        self.flush()
        if self._dev is None:
            self._dev = _put_global(self._np, self._sharding)
        return self._dev


@dataclasses.dataclass
class MeshJob:
    """One in-flight mesh step (K blocks, one dispatch)."""
    k0: int                    # real blocks (the rest pad the mesh width)
    cap: int
    Ls: int
    Lb: int
    b_small: int
    true_ns: list[int]
    cuts: jax.Array | None
    counts: jax.Array | None
    digs: jax.Array | None
    hits: jax.Array | None
    _ev: object = None


class MeshReducer:
    """Mesh-sharded group-reduction front end: the multi-chip counterpart
    of ops.resident.ResidentReducer's batched pipeline (same submit /
    start / finish shape, so server/write_pipeline.py drives either).

    ``finish_many`` returns per block ``(cuts u64, digests u8[nc, 32],
    probe frozenset)`` — the extra third element is the set of chunk
    fingerprints whose on-mesh bucket probe voted HIT; reduction/dedup.py
    skips the host index walk for everything outside it and re-checks the
    members authoritatively."""

    def __init__(self, cdc=None, mesh: Mesh | None = None,
                 lanes_per_device: int = 2, bucket_slots: int = 1 << 15,
                 mask: int | None = None):
        from hdrf_tpu.config import CdcConfig
        from hdrf_tpu.ops.dispatch import gear_mask

        self.cdc = cdc or CdcConfig()
        self.mesh = mesh if mesh is not None else \
            make_mesh(n_data=len(jax.devices()), n_seq=1)
        assert self.mesh.shape["seq"] == 1, \
            "the mesh plane shards blocks over 'data' only"
        self.ndata = self.mesh.shape["data"]
        self.mask = gear_mask(self.cdc) if mask is None else mask
        self.lanes_per_device = max(1, int(lanes_per_device))
        self.table = ShardedBucketTable(self.mesh, slots=bucket_slots)
        self._b_big = (self.cdc.max_chunk + 9 + 63) // 64
        self._b_small = max(1, min((2 << self.cdc.mask_bits) // 64,
                                   self._b_big))

    def max_group(self, n: int = 0) -> int:
        """Mesh width x per-device lane capacity — the coalescer's group
        target (ISSUE 9 tentpole c)."""
        return self.ndata * self.lanes_per_device

    def submit_many(self, datas) -> MeshJob:
        arrs = [np.frombuffer(d, dtype=np.uint8)
                if not isinstance(d, np.ndarray) else d for d in datas]
        true_ns = [int(a.size) for a in arrs]
        k0 = len(arrs)
        assert k0 > 0 and max(true_ns) > 0
        n_pad = max(true_ns) + (-max(true_ns)) % 512
        k = k0 + (-k0) % self.ndata   # dummy zero blocks (true_n 0) pad
        Kl = k // self.ndata
        buf = np.zeros((k, n_pad), dtype=np.uint8)
        for i, a in enumerate(arrs):
            buf[i, :a.size] = a
        mn, mx = self.cdc.min_chunk, self.cdc.max_chunk
        # Provable capacities — no overflow/fallback path exists or is
        # needed: cuts advance >= min_chunk (+1 final short chunk), big
        # chunks are > b_small*64-9 bytes by the binning rule.
        cap = n_pad // max(mn, 1) + 2
        # Per-block lane strides (the 128-lane SHA grid is applied to the
        # per-device TOTALS inside _mesh_step).  Lb == 0 when the binning
        # rule proves every chunk small — the big leg is elided entirely.
        Ls = cap
        Lb = (0 if self._b_small >= self._b_big
              else n_pad // max(self._b_small * 64 - 8, mn, 1) + 2)
        fn = _mesh_step(self.mesh, Kl, n_pad, mn, mx, self._b_small,
                        self._b_big, Ls, Lb, cap, self.table.slots)
        table_dev = self.table.device_table()   # flushes pending commits
        blocks = _put_global(buf,
                             NamedSharding(self.mesh, P("data", None)))
        tns = _put_global(np.array(true_ns + [0] * (k - k0), np.int32),
                          NamedSharding(self.mesh, P("data")))
        ev = _ledger.dispatch("sharded.step", batch=k0,
                              h2d_bytes=buf.nbytes,
                              key=(Kl, n_pad, cap, self.ndata))
        cuts, counts, digs, hits = fn(
            blocks, tns, jnp.uint32(self.mask & 0xFFFFFFFF), table_dev)
        for out in (cuts, counts, digs, hits):
            out.copy_to_host_async()
        _MP.incr("steps")
        _MP.observe("step_blocks", k0)
        _MP.incr("step_bytes", int(sum(true_ns)))
        return MeshJob(k0=k0, cap=cap, Ls=Ls, Lb=Lb,
                       b_small=self._b_small, true_ns=true_ns, cuts=cuts,
                       counts=counts, digs=digs, hits=hits, _ev=ev)

    def start_sha_many(self, job: MeshJob) -> None:
        """API parity with ResidentReducer — the mesh step already
        enqueued everything; nothing is awaited until finish_many."""

    def finish_many(self, job: MeshJob) -> list[tuple]:
        cuts = _fetch_global(job.cuts)
        counts = _fetch_global(job.counts)
        digs = _fetch_global(job.digs)
        hits = _fetch_global(job.hits)
        _ledger.readback(job._ev,
                         d2h_bytes=cuts.nbytes + counts.nbytes
                         + digs.nbytes + hits.nbytes)
        job._ev = None
        job.cuts = job.counts = job.digs = job.hits = None
        Kl = counts.shape[0] // self.ndata
        out = []
        hit_lanes = 0
        for b in range(job.k0):
            if job.true_ns[b] == 0:
                out.append((np.empty(0, np.uint64),
                            np.empty((0, 32), np.uint8), frozenset()))
                continue
            nc = int(counts[b])
            assert nc <= job.cap, "cut capacity proof violated"
            c = cuts[b, :nc].astype(np.int64)
            assert nc > 0 and c[-1] == job.true_ns[b], \
                "device cut select lost the final cut"
            starts = np.concatenate([[0], c[:-1]])
            lens = c - starts
            small = (lens + 9 + 63) // 64 <= job.b_small
            rank = np.where(small, np.cumsum(small) - 1,
                            np.cumsum(~small) - 1)
            d, kl = b // Kl, b % Kl
            base = d * Kl * (job.Ls + job.Lb)
            rows = np.where(small, base + kl * job.Ls + rank,
                            base + Kl * job.Ls + kl * job.Lb + rank)
            dg = digs[rows]
            hit = hits[rows] > 0
            hit_lanes += int(hit.sum())
            probe = frozenset(dg[i].tobytes()
                              for i in np.nonzero(hit)[0])
            out.append((c.astype(np.uint64), dg, probe))
        if hit_lanes:
            _MP.incr("probe_hit_lanes", hit_lanes)
        return out

    def reduce_many(self, datas: list) -> list[tuple]:
        """Convenience serial driver (benchmarks, tests): groups of up to
        max_group blocks, one mesh step each."""
        out = []
        g = self.max_group()
        for at in range(0, len(datas), g):
            out.extend(self.finish_many(self.submit_many(datas[at:at + g])))
        return out


# --------------------------------------------------------------------------
# Sharded LZ4 match scan: the compress leg of the mesh plane.  Per-block
# match scans are embarrassingly parallel, so the group spreads over 'data'
# and the packed record rows come back in one readback — the same
# (jobs, recs, ev) contract as TpuLz4.submit_many's batched branch, so
# TpuLz4.finish_many assembles (and rescans/falls back) unchanged.
# --------------------------------------------------------------------------

_lz4_mesh_fns = _LruJitCache()


def _lz4_scan_fn(mesh: Mesh, Kl: int, n_pad: int, stride: int,
                 min_len: int, p1: int, p2: int, p3: int):
    from hdrf_tpu.ops.lz4_tpu import _match_scan_impl

    key = (mesh, Kl, n_pad, stride, min_len, p1, p2, p3)
    fn = _lz4_mesh_fns.get(key)
    if fn is not None:
        return fn

    def scan(blocks):
        return jnp.stack([_match_scan_impl(blocks[i], stride, min_len,
                                           p1, p2, p3)
                          for i in range(Kl)])

    fn = jax.jit(_shard_map(
        scan, mesh=mesh, in_specs=(P("data", None),),
        out_specs=P("data", None), check_rep=False), donate_argnums=(0,))
    _lz4_mesh_fns.put(key, fn)
    return fn


def lz4_submit_many_sharded(lz, datas: list, mesh: Mesh):
    """Submit a container group's LZ4 match scans as ONE mesh dispatch.

    Blocks pad to one shape (the pad region's records are masked by the
    emit's MFLIMIT cut, same as TpuLz4's device_images branch) and the
    group pads to mesh width with dummy zero blocks.  Returns the
    ``(jobs, recs, ev)`` triple ``lz.finish_many`` expects, or None when
    the group doesn't fit the mesh (caller falls back to the single-device
    path).  Each job keeps its padded HOST block so the overflow rescan
    path still works."""
    from hdrf_tpu.ops.lz4_tpu import _S, Lz4Job

    if mesh.shape["seq"] != 1:
        return None
    ndata = mesh.shape["data"]
    arrs = [np.frombuffer(d, dtype=np.uint8)
            if not isinstance(d, np.ndarray) else d for d in datas]
    if len(arrs) < 2 or min(a.size for a in arrs) < lz.min_device:
        return None
    n_max = max(a.size for a in arrs)
    n_pad = n_max + (-n_max) % _S
    k0 = len(arrs)
    k = k0 + (-k0) % ndata
    Kl = k // ndata
    buf = np.zeros((k, n_pad), dtype=np.uint8)
    for i, a in enumerate(arrs):
        buf[i, :a.size] = a
    p1, p2, p3 = lz._shapes(n_pad)
    fn = _lz4_scan_fn(mesh, Kl, n_pad, lz.stride, lz.min_len, p1, p2, p3)
    blocks = _put_global(buf, NamedSharding(mesh, P("data", None)))
    ev = _ledger.dispatch("sharded.lz4", batch=k0, h2d_bytes=buf.nbytes,
                          key=(Kl, n_pad, p1, p2, p3))
    recs = fn(blocks)
    recs.copy_to_host_async()
    _MP.incr("lz4_steps")
    jobs = [Lz4Job(n=a.size, host=a, block=buf[i], recs=None,
                   p1=p1, p2=p2, p3=p3)
            for i, a in enumerate(arrs)]
    return jobs, recs, ev


def lz4_compress_many_sharded(lz, datas: list, mesh: Mesh) -> list[bytes]:
    sub = lz4_submit_many_sharded(lz, datas, mesh)
    if sub is None:
        return lz.compress_many(datas)
    return lz.finish_many(sub)
