"""Multi-chip sharded reduction over a ``jax.sharding.Mesh``.

The reference scales one logical object across nodes only via EC striping
(client DFSStripedOutputStream.java:81; DN-side StripedBlockReconstructor) and
scales the per-block hot loops across 2-3 CPU threads with hand-rolled
recursive thread spawns (DataDeduplicator.threadedHasher :536-650,
threadedStorer :652-845, DataConstructor.threadedConstructor :430-567).

Here the analogous capability is expressed TPU-natively with two mesh axes:

- ``seq`` — *sequence parallelism* over one block's byte axis: the Gear
  rolling-hash candidate scan (ops/gear.py) shards its positions across
  devices; each device needs the previous device's last ``WINDOW-1`` bytes, a
  halo that travels over ICI via ``lax.ppermute`` (the ring-attention-style
  neighbor exchange).  Because ``G[0] == 0`` (fmix32 preserves zero), the first
  shard's zero halo reproduces exactly the partial-window hashes of the
  single-device scan, so sharded output is bit-identical to ops.gear.
- ``data`` — *data parallelism* over independent blocks (and over SHA-256 lane
  tiles): no communication; the embarrassingly parallel axis.

Cross-device reductions (candidate counts, byte stats) ride ``psum``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.6 exports shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map

from hdrf_tpu.ops import gear
from hdrf_tpu.utils import device_ledger as _ledger

WINDOW = gear.WINDOW
_HALO = WINDOW - 1


def _put_global(arr: np.ndarray, sharding) -> jax.Array:
    """Host array -> sharded jax.Array; in multi-process mode each rank
    feeds only its addressable shards (parallel/launch.py runs the host
    stages replicated, so every rank holds the same logical array).  The
    single H2D chokepoint of the sharded pipeline — ledger transfer
    accounting lives here so callers never double-count."""
    _ledger.transfer("h2d", "sharded.put", arr.nbytes)
    if jax.process_count() == 1:
        return jax.device_put(arr, sharding)
    return jax.make_array_from_callback(arr.shape, sharding,
                                        lambda idx: arr[idx])


def _fetch_global(x: jax.Array) -> np.ndarray:
    """Sharded jax.Array -> full numpy on every host (the host-side cut
    selection must see ALL candidate words regardless of process count)."""
    if jax.process_count() == 1:
        return np.asarray(x)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(x, tiled=True))


class _LruJitCache:
    """Bounded compiled-fn cache: (mesh, shape-key) tuples accumulate one
    entry per distinct mesh/bucket/pad combination, and a long-lived
    worker crossing many mesh shapes must not grow it without bound (r4
    verdict weak #3)."""

    def __init__(self, cap: int = 8):
        from collections import OrderedDict
        self._d = OrderedDict()
        self._cap = cap

    def get(self, key):
        fn = self._d.get(key)
        if fn is not None:
            self._d.move_to_end(key)
        return fn

    def put(self, key, fn) -> None:
        self._d[key] = fn
        self._d.move_to_end(key)
        while len(self._d) > self._cap:
            self._d.popitem(last=False)


def make_mesh(n_data: int = 1, n_seq: int | None = None,
              devices=None) -> Mesh:
    """A 2D ('data', 'seq') mesh over ``devices`` (default: all devices)."""
    devices = list(devices if devices is not None else jax.devices())
    if n_seq is None:
        n_seq = len(devices) // n_data
    if n_data * n_seq != len(devices):
        raise ValueError(f"mesh {n_data}x{n_seq} != {len(devices)} devices")
    arr = np.array(devices).reshape(n_data, n_seq)
    return Mesh(arr, ("data", "seq"))


def _local_candidate_words(local: jax.Array, mask: jax.Array,
                           n_seq: int) -> tuple[jax.Array, jax.Array]:
    """Per-shard candidate bitmap words for a seq-sharded block.

    local: u8[m] — this device's byte range (m % 256 == 0).
    Returns (u32[m/32] packed candidate words, i32[] local candidate count).
    """
    m = local.shape[0]
    idx = jax.lax.axis_index("seq")
    # Halo: last WINDOW-1 bytes of the previous shard (zeros for shard 0 —
    # ppermute leaves unaddressed targets zero-filled, which is exactly the
    # zero-pad the single-device scan uses).  The halo-prefixed scan yields
    # full-window hashes for every local position; the first _HALO outputs
    # belong to the previous shard and are dropped by scanning the
    # concatenation and packing only the local tail.
    halo = jax.lax.ppermute(local[-_HALO:], "seq",
                            [(i, i + 1) for i in range(n_seq - 1)])
    ext = jnp.concatenate([halo, local])
    t = gear._gear_map(ext)
    h = gear._doubling_hashes(t)[_HALO:]  # full-window hash per local position
    base = (idx * m).astype(jnp.uint32)
    pos1 = base + jnp.arange(1, m + 1, dtype=jnp.uint32)
    is_cand = ((h & mask) == 0) & (pos1 >= WINDOW)
    words = gear.pack_bitmap_words(is_cand)
    return words, jnp.sum(is_cand.astype(jnp.int32))


def candidate_words_sharded(mesh: Mesh, fused: str | None = None):
    """Jitted all-position Gear candidate scan, byte axis sharded over 'seq'.

    Returns ``fn(block u8[N], mask u32) -> (words u32[N/32], count i32)`` with
    the block sharded P('seq'); words come back with the same layout.  Output
    is bit-identical to the single-device ops.gear._candidate_words bitmap.

    ``fused`` routes the per-shard scan through the fused Pallas kernel
    (ops/cdc_pallas.py) instead of the XLA doubling scan — same halo, same
    packed-bitmap contract, asserted bit-identical in tests/test_cdc_pallas.py.
    None resolves via cdc_pallas.cdc_pallas_mode() ('off' on the CPU mesh).
    """
    from hdrf_tpu.ops import cdc_pallas

    n_seq = mesh.shape["seq"]
    if fused is None:
        fused = cdc_pallas.cdc_pallas_mode()

    kw = {}
    if fused != "off":
        interp = fused == "interpret"
        # shard_map has no replication rule for pallas_call; the psum below
        # makes the count output replicated by construction, so the check
        # is safely skipped on the fused route.
        kw["check_rep"] = False

        def scan(block: jax.Array, mask: jax.Array):
            words, cnt = cdc_pallas.local_candidate_words_pallas(
                block, mask, n_seq, interpret=interp)
            return words, jax.lax.psum(cnt, "seq")
    else:
        def scan(block: jax.Array, mask: jax.Array):
            words, cnt = _local_candidate_words(block, mask, n_seq)
            return words, jax.lax.psum(cnt, "seq")

    fn = _shard_map(scan, mesh=mesh, in_specs=(P("seq"), P()),
                    out_specs=(P("seq"), P()), **kw)
    return jax.jit(fn)


def sha256_lanes_sharded(mesh: Mesh):
    """SHA-256 lane hashing with lanes sharded over the 'data' axis.

    Pure data parallelism: ``fn(blocks u8[L, B*64], nblocks i32[L]) ->
    u8[L, 32]``; L must be a multiple of 128 * mesh.shape['data'].
    """
    from hdrf_tpu.ops import sha256 as sha

    def hash_local(blocks_u8: jax.Array, nblocks: jax.Array) -> jax.Array:
        return sha.sha256_lanes(blocks_u8, nblocks)

    fn = _shard_map(hash_local, mesh=mesh,
                    in_specs=(P("data"), P("data")), out_specs=P("data"))
    return jax.jit(fn)


# --------------------------------------------------------------------------
# Full sharded reduction step (what the driver's dryrun compiles + runs)
# --------------------------------------------------------------------------

def _segment_sha_pad(seg: int) -> np.ndarray:
    """The constant SHA-256 terminal block for a fixed ``seg``-byte message
    (seg % 64 == 0): 0x80 marker + big-endian bit length."""
    pad = np.zeros(64, dtype=np.uint8)
    pad[0] = 0x80
    pad[56:64] = np.frombuffer(np.uint64(seg * 8).byteswap().tobytes(),
                               dtype=np.uint8)
    return pad


def reduction_step(mesh: Mesh, seg: int = 512):
    """The full per-batch reduction forward, sharded over ('data', 'seq').

    Input ``blocks u8[B, N]``: B blocks data-parallel over 'data', each
    block's N bytes sequence-parallel over 'seq'.  Per block the step runs

    1. the Gear CDC candidate scan with ICI halo exchange (``ppermute``),
    2. SHA-256 fingerprints of the block's fixed ``seg``-byte segments
       (the jit-static stand-in for variable CDC chunks, whose SHA padding
       is data-dependent and therefore host-side in the serving path),
    3. global stats via ``psum`` over both axes.

    Returns ``fn(blocks) -> dict(words, digests, candidates)``; everything
    stays device-resident, sharded P('data','seq').
    """
    from hdrf_tpu.ops import sha256 as sha

    n_seq = mesh.shape["seq"]
    pad_const = _segment_sha_pad(seg)

    def step(blocks: jax.Array, mask: jax.Array):
        b_local, m = blocks.shape
        words, counts = jax.vmap(
            lambda blk: _local_candidate_words(blk, mask, n_seq))(blocks)
        # Fixed-size segment fingerprints: (lanes, seg) + constant pad block.
        lanes = blocks.reshape(-1, seg)
        n_lanes = lanes.shape[0]
        lane_pad = (-n_lanes) % 128
        lanes = jnp.pad(lanes, ((0, lane_pad), (0, 0)))
        msgs = jnp.concatenate(
            [lanes, jnp.broadcast_to(jnp.asarray(pad_const),
                                     (lanes.shape[0], 64))], axis=1)
        nblocks = jnp.where(jnp.arange(lanes.shape[0]) < n_lanes,
                            seg // 64 + 1, 0).astype(jnp.int32)
        digests = sha.sha256_lanes(msgs, nblocks)[:n_lanes]
        digests = digests.reshape(b_local, m // seg, 32)
        total = jax.lax.psum(jax.lax.psum(jnp.sum(counts), "seq"), "data")
        return {"words": words, "digests": digests, "candidates": total}

    fn = _shard_map(step, mesh=mesh,
                    in_specs=(P("data", "seq"), P()),
                    out_specs={"words": P("data", "seq"),
                               "digests": P("data", "seq"),
                               "candidates": P()})
    return jax.jit(fn)


# --------------------------------------------------------------------------
# The REAL variable-chunk pipeline, sharded (the serving path's multi-chip
# form: seq-parallel candidate scan -> host cut select -> chunk-parallel
# SHA over the actual CDC chunks, lanes spread across every device)
# --------------------------------------------------------------------------

_sha_fns = _LruJitCache()


def _sha_chunks_sharded(mesh: Mesh, bucket: int, pad_words: int):
    """Variable-chunk SHA with lanes sharded over the FLATTENED mesh.  The
    block arrives SEQ-SHARDED (the same resident shards the candidate scan
    used — one H2D total); each device all-gathers the full byte image
    over ICI, word-images it, and DMA/gathers + hashes its own lane
    subset.  Chunk fingerprints are embarrassingly parallel once cuts are
    known; the all_gather is the only collective."""
    from hdrf_tpu.ops.resident import _bucket_sha, be_word_image

    key = (mesh, bucket, pad_words)  # Mesh hashes by devices+axis names
    fn = _sha_fns.get(key)
    if fn is not None:
        return fn
    axes = tuple(mesh.axis_names)

    def local(block_shard: jax.Array, ol: jax.Array) -> jax.Array:
        full = jax.lax.all_gather(block_shard, "seq", tiled=True)
        words = jnp.concatenate([be_word_image(full),
                                 jnp.zeros(pad_words, jnp.uint32)])
        return _bucket_sha(words, ol, bucket)

    fn = jax.jit(_shard_map(
        local, mesh=mesh,
        in_specs=(P("seq"), P(None, axes)), out_specs=P(axes)))
    _sha_fns.put(key, fn)
    return fn


_sha_halo_fns = _LruJitCache()


def _sha_chunks_halo(mesh: Mesh, bucket: int, pad_words: int,
                     halo_shards: int):
    """Data-LOCAL sharded SHA: each chunk is hashed by a device at its
    OWNING seq position, whose image is its own shard plus ``halo_shards``
    neighbor shards fetched with a ppermute ring walk — ICI traffic is
    halo_shards x (block/n_seq) per device instead of the full-image
    all_gather's (n_seq-1) x (block/n_seq) (the r3 verdict's economics
    note; the halo pattern is the scaling-book neighbor-exchange recipe,
    same as the candidate scan's WINDOW halo).  Over-read bytes past a
    chunk (next chunks' data, or ring-wrapped bytes on the last shard)
    are masked by _bucket_sha's SHA-padding splice, so output stays
    bit-identical.  Lanes land as (n_data, n_seq, Lmax) blocks; the host
    unpermutes digests by its own owner assignment."""
    from hdrf_tpu.ops.resident import _bucket_sha, be_word_image

    key = (mesh, bucket, pad_words, halo_shards)
    fn = _sha_halo_fns.get(key)
    if fn is not None:
        return fn
    n_seq = mesh.shape["seq"]
    perm = [(i, (i - 1) % n_seq) for i in range(n_seq)]  # fetch NEXT shard

    def local(block_shard: jax.Array, ol: jax.Array) -> jax.Array:
        parts = [block_shard]
        cur = block_shard
        for _ in range(halo_shards):
            cur = jax.lax.ppermute(cur, "seq", perm)
            parts.append(cur)
        img = jnp.concatenate(parts)
        words = jnp.concatenate([be_word_image(img),
                                 jnp.zeros(pad_words, jnp.uint32)])
        return _bucket_sha(words, ol[0, 0], bucket)

    fn = jax.jit(_shard_map(
        local, mesh=mesh,
        in_specs=(P("seq"), P("data", "seq")),
        out_specs=P(("data", "seq"))))
    _sha_halo_fns.put(key, fn)
    return fn


def reduce_sharded(data: bytes | np.ndarray, cdc, mesh: Mesh):
    """(cuts, digests) for ONE block with every stage on the mesh — the
    multi-chip form of ops.dispatch.chunk_and_fingerprint, bit-identical
    to the native oracle (asserted in tests/test_sharding.py and the
    driver's dryrun):

    1. all-position Gear candidate scan, byte axis sharded over 'seq' with
       the ppermute halo exchange (ICI neighbor traffic);
    2. host cut selection over the sparse candidates (O(chunks) control
       flow — data-dependent, so host-side, same as single-device);
    3. SHA-256 of the actual VARIABLE chunks, lanes sharded across every
       device; the byte image reaches each chip via an ICI all_gather of
       the SAME seq-sharded resident bytes stage 1 used — the block
       crosses the host->device boundary exactly once.
    """
    from hdrf_tpu import native
    from hdrf_tpu.ops.dispatch import gear_mask
    from hdrf_tpu.ops.resident import _bucket_of

    a = (np.frombuffer(data, dtype=np.uint8)
         if not isinstance(data, np.ndarray) else data)
    n = a.size
    if n == 0:  # same contract as ResidentReducer's n==0 special case
        return np.empty(0, dtype=np.uint64), np.empty((0, 32), np.uint8)
    assert n < (1 << 31), "i32 lane offsets: shard blocks beyond 2 GiB"
    mask = gear_mask(cdc)
    n_seq = mesh.shape["seq"]
    # one padded image serves BOTH stages: shard-size granularity for the
    # scan (each seq shard % 256) and the word-image grid (% 512)
    grid = 512 * n_seq
    buf = np.zeros(n + ((-n) % grid), dtype=np.uint8)
    buf[:n] = a
    block_sh = _put_global(buf, NamedSharding(mesh, P("seq")))
    from hdrf_tpu.ops.cdc_pallas import cdc_pallas_mode
    scan_mode = cdc_pallas_mode()
    ev = _ledger.dispatch("sharded.scan", key=(buf.size, n_seq, scan_mode))
    words, _ = candidate_words_sharded(mesh, fused=scan_mode)(
        block_sh, jnp.uint32(mask & 0xFFFFFFFF))
    wv = _fetch_global(words)
    _ledger.readback(ev, d2h_bytes=wv.nbytes)
    (idx,) = np.nonzero(wv)
    pos = gear._words_to_positions(idx.astype(np.uint32), wv[idx], n)
    cuts = native.cdc_select(pos, n, cdc.min_chunk, cdc.max_chunk)
    starts = np.concatenate([[0], cuts[:-1]]).astype(np.int64)
    lens = (cuts - starts).astype(np.int64)
    nchunks = len(cuts)
    ndev = int(np.prod([mesh.shape[ax] for ax in mesh.axis_names]))
    # one bucket sized for max_chunk: a stable jit key across blocks (the
    # single-device path's finer bucketing is a padded-FLOPs optimization,
    # not a correctness requirement)
    bucket = _bucket_of((cdc.max_chunk + 9 + 63) // 64)
    pad_words = -(-(bucket * 16 + 16) // 128) * 128
    n_data, n_seq = mesh.shape["data"], mesh.shape["seq"]
    shard_bytes = buf.size // n_seq
    # halo shards covering one full gather window past a shard boundary
    halo = -(-(bucket * 64 + 64) // shard_bytes)
    if halo < n_seq - 1:
        # DATA-LOCAL SHA: each chunk hashed at its owning seq position
        # (+round-robin over 'data'), image = own shard + ppermute halo —
        # ICI bytes per device drop from (n_seq-1) to `halo` shards
        # vectorized owner assignment (a 1 GiB block has ~131k chunks;
        # python-loop assignment would stall the pipeline between
        # dispatches): rank chunks within their seq shard, round-robin
        # the rank across 'data', lane index = rank // n_data
        owner_seq = np.minimum(starts // shard_bytes,
                               n_seq - 1).astype(np.int64)
        counts = np.bincount(owner_seq, minlength=n_seq)
        order = np.argsort(owner_seq, kind="stable")
        group_base = np.cumsum(counts) - counts
        rank = np.empty(nchunks, dtype=np.int64)
        rank[order] = (np.arange(nchunks)
                       - np.repeat(group_base, counts))
        d_arr = rank % n_data
        j_arr = rank // n_data
        # jit shape key: quantize the per-cell lane count to power-of-two
        # 128-lane steps — a data-dependent exact lmax would retrace per
        # block (the stable-key property the bucket choice exists for)
        max_cell = max(int(j_arr.max()) + 1 if nchunks else 1, 1)
        lmax = 128 << max(0, (max_cell - 1).bit_length() - 7) \
            if max_cell > 128 else 128
        ol_all = np.zeros((n_data, n_seq, 2, lmax), dtype=np.int32)
        ol_all[d_arr, owner_seq, 0, j_arr] = starts - owner_seq * shard_bytes
        ol_all[d_arr, owner_seq, 1, j_arr] = lens
        fn = _sha_chunks_halo(mesh, bucket, pad_words, halo)
        ol_dev = _put_global(
            ol_all, NamedSharding(mesh, P("data", "seq")))
        ev = _ledger.dispatch("sharded.sha", batch=nchunks,
                              key=(bucket, lmax, halo))
        out = _fetch_global(fn(block_sh, ol_dev))
        _ledger.readback(ev, d2h_bytes=out.nbytes)
        digests = out[(d_arr * n_seq + owner_seq) * lmax + j_arr]
        return cuts, digests
    # tiny blocks / shards smaller than the gather window: the halo walk
    # would re-build the full image anyway — all_gather is the right tool
    lane_grid = 128 * ndev
    L = max(-(-nchunks // lane_grid) * lane_grid, lane_grid)
    ol = np.zeros((2, L), dtype=np.int32)
    ol[0, :nchunks] = starts
    ol[1, :nchunks] = lens
    fn = _sha_chunks_sharded(mesh, bucket, pad_words)
    ol_dev = _put_global(
        ol, NamedSharding(mesh, P(None, tuple(mesh.axis_names))))
    ev = _ledger.dispatch("sharded.sha", batch=nchunks, key=(bucket, L))
    digests = _fetch_global(fn(block_sh, ol_dev))
    _ledger.readback(ev, d2h_bytes=digests.nbytes)
    digests = digests[:nchunks]
    return cuts, digests


def gear_candidates_sharded(data: bytes | np.ndarray, mask: int,
                            mesh: Mesh) -> np.ndarray:
    """Host-facing sharded candidate scan; same contract (and bit-identical
    output) as ops.gear.gear_candidates_jax, bytes spread over mesh['seq']."""
    a = np.frombuffer(data, dtype=np.uint8) if not isinstance(data, np.ndarray) else data
    n = a.size
    n_seq = mesh.shape["seq"]
    chunk = 256 * n_seq
    padded = n + ((-n) % chunk)
    buf = np.zeros(padded, dtype=np.uint8)
    buf[:n] = a
    sharding = NamedSharding(mesh, P("seq"))
    block = _put_global(buf, sharding)
    fn = candidate_words_sharded(mesh)
    words, _ = fn(block, jnp.uint32(mask & 0xFFFFFFFF))
    wv = _fetch_global(words)
    (idx,) = np.nonzero(wv)
    pos = gear._words_to_positions(idx.astype(np.uint32), wv[idx], n)
    return pos
