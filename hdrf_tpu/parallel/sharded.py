"""Multi-chip sharded reduction over a ``jax.sharding.Mesh``.

The reference scales one logical object across nodes only via EC striping
(client DFSStripedOutputStream.java:81; DN-side StripedBlockReconstructor) and
scales the per-block hot loops across 2-3 CPU threads with hand-rolled
recursive thread spawns (DataDeduplicator.threadedHasher :536-650,
threadedStorer :652-845, DataConstructor.threadedConstructor :430-567).

Here the analogous capability is expressed TPU-natively with two mesh axes:

- ``seq`` — *sequence parallelism* over one block's byte axis: the Gear
  rolling-hash candidate scan (ops/gear.py) shards its positions across
  devices; each device needs the previous device's last ``WINDOW-1`` bytes, a
  halo that travels over ICI via ``lax.ppermute`` (the ring-attention-style
  neighbor exchange).  Because ``G[0] == 0`` (fmix32 preserves zero), the first
  shard's zero halo reproduces exactly the partial-window hashes of the
  single-device scan, so sharded output is bit-identical to ops.gear.
- ``data`` — *data parallelism* over independent blocks (and over SHA-256 lane
  tiles): no communication; the embarrassingly parallel axis.

Cross-device reductions (candidate counts, byte stats) ride ``psum``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.6 exports shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map

from hdrf_tpu.ops import gear

WINDOW = gear.WINDOW
_HALO = WINDOW - 1


def make_mesh(n_data: int = 1, n_seq: int | None = None,
              devices=None) -> Mesh:
    """A 2D ('data', 'seq') mesh over ``devices`` (default: all devices)."""
    devices = list(devices if devices is not None else jax.devices())
    if n_seq is None:
        n_seq = len(devices) // n_data
    if n_data * n_seq != len(devices):
        raise ValueError(f"mesh {n_data}x{n_seq} != {len(devices)} devices")
    arr = np.array(devices).reshape(n_data, n_seq)
    return Mesh(arr, ("data", "seq"))


def _local_candidate_words(local: jax.Array, mask: jax.Array,
                           n_seq: int) -> tuple[jax.Array, jax.Array]:
    """Per-shard candidate bitmap words for a seq-sharded block.

    local: u8[m] — this device's byte range (m % 256 == 0).
    Returns (u32[m/32] packed candidate words, i32[] local candidate count).
    """
    m = local.shape[0]
    idx = jax.lax.axis_index("seq")
    # Halo: last WINDOW-1 bytes of the previous shard (zeros for shard 0 —
    # ppermute leaves unaddressed targets zero-filled, which is exactly the
    # zero-pad the single-device scan uses).  The halo-prefixed scan yields
    # full-window hashes for every local position; the first _HALO outputs
    # belong to the previous shard and are dropped by scanning the
    # concatenation and packing only the local tail.
    halo = jax.lax.ppermute(local[-_HALO:], "seq",
                            [(i, i + 1) for i in range(n_seq - 1)])
    ext = jnp.concatenate([halo, local])
    t = gear._gear_map(ext)
    h = gear._doubling_hashes(t)[_HALO:]  # full-window hash per local position
    base = (idx * m).astype(jnp.uint32)
    pos1 = base + jnp.arange(1, m + 1, dtype=jnp.uint32)
    is_cand = ((h & mask) == 0) & (pos1 >= WINDOW)
    words = gear.pack_bitmap_words(is_cand)
    return words, jnp.sum(is_cand.astype(jnp.int32))


def candidate_words_sharded(mesh: Mesh):
    """Jitted all-position Gear candidate scan, byte axis sharded over 'seq'.

    Returns ``fn(block u8[N], mask u32) -> (words u32[N/32], count i32)`` with
    the block sharded P('seq'); words come back with the same layout.  Output
    is bit-identical to the single-device ops.gear._candidate_words bitmap.
    """
    n_seq = mesh.shape["seq"]

    def scan(block: jax.Array, mask: jax.Array):
        words, cnt = _local_candidate_words(block, mask, n_seq)
        return words, jax.lax.psum(cnt, "seq")

    fn = _shard_map(scan, mesh=mesh, in_specs=(P("seq"), P()),
                    out_specs=(P("seq"), P()))
    return jax.jit(fn)


def sha256_lanes_sharded(mesh: Mesh):
    """SHA-256 lane hashing with lanes sharded over the 'data' axis.

    Pure data parallelism: ``fn(blocks u8[L, B*64], nblocks i32[L]) ->
    u8[L, 32]``; L must be a multiple of 128 * mesh.shape['data'].
    """
    from hdrf_tpu.ops import sha256 as sha

    def hash_local(blocks_u8: jax.Array, nblocks: jax.Array) -> jax.Array:
        return sha.sha256_lanes(blocks_u8, nblocks)

    fn = _shard_map(hash_local, mesh=mesh,
                    in_specs=(P("data"), P("data")), out_specs=P("data"))
    return jax.jit(fn)


# --------------------------------------------------------------------------
# Full sharded reduction step (what the driver's dryrun compiles + runs)
# --------------------------------------------------------------------------

def _segment_sha_pad(seg: int) -> np.ndarray:
    """The constant SHA-256 terminal block for a fixed ``seg``-byte message
    (seg % 64 == 0): 0x80 marker + big-endian bit length."""
    pad = np.zeros(64, dtype=np.uint8)
    pad[0] = 0x80
    pad[56:64] = np.frombuffer(np.uint64(seg * 8).byteswap().tobytes(),
                               dtype=np.uint8)
    return pad


def reduction_step(mesh: Mesh, seg: int = 512):
    """The full per-batch reduction forward, sharded over ('data', 'seq').

    Input ``blocks u8[B, N]``: B blocks data-parallel over 'data', each
    block's N bytes sequence-parallel over 'seq'.  Per block the step runs

    1. the Gear CDC candidate scan with ICI halo exchange (``ppermute``),
    2. SHA-256 fingerprints of the block's fixed ``seg``-byte segments
       (the jit-static stand-in for variable CDC chunks, whose SHA padding
       is data-dependent and therefore host-side in the serving path),
    3. global stats via ``psum`` over both axes.

    Returns ``fn(blocks) -> dict(words, digests, candidates)``; everything
    stays device-resident, sharded P('data','seq').
    """
    from hdrf_tpu.ops import sha256 as sha

    n_seq = mesh.shape["seq"]
    pad_const = _segment_sha_pad(seg)

    def step(blocks: jax.Array, mask: jax.Array):
        b_local, m = blocks.shape
        words, counts = jax.vmap(
            lambda blk: _local_candidate_words(blk, mask, n_seq))(blocks)
        # Fixed-size segment fingerprints: (lanes, seg) + constant pad block.
        lanes = blocks.reshape(-1, seg)
        n_lanes = lanes.shape[0]
        lane_pad = (-n_lanes) % 128
        lanes = jnp.pad(lanes, ((0, lane_pad), (0, 0)))
        msgs = jnp.concatenate(
            [lanes, jnp.broadcast_to(jnp.asarray(pad_const),
                                     (lanes.shape[0], 64))], axis=1)
        nblocks = jnp.where(jnp.arange(lanes.shape[0]) < n_lanes,
                            seg // 64 + 1, 0).astype(jnp.int32)
        digests = sha.sha256_lanes(msgs, nblocks)[:n_lanes]
        digests = digests.reshape(b_local, m // seg, 32)
        total = jax.lax.psum(jax.lax.psum(jnp.sum(counts), "seq"), "data")
        return {"words": words, "digests": digests, "candidates": total}

    fn = _shard_map(step, mesh=mesh,
                    in_specs=(P("data", "seq"), P()),
                    out_specs={"words": P("data", "seq"),
                               "digests": P("data", "seq"),
                               "candidates": P()})
    return jax.jit(fn)


def gear_candidates_sharded(data: bytes | np.ndarray, mask: int,
                            mesh: Mesh) -> np.ndarray:
    """Host-facing sharded candidate scan; same contract (and bit-identical
    output) as ops.gear.gear_candidates_jax, bytes spread over mesh['seq']."""
    a = np.frombuffer(data, dtype=np.uint8) if not isinstance(data, np.ndarray) else data
    n = a.size
    n_seq = mesh.shape["seq"]
    chunk = 256 * n_seq
    padded = n + ((-n) % chunk)
    buf = np.zeros(padded, dtype=np.uint8)
    buf[:n] = a
    sharding = NamedSharding(mesh, P("seq"))
    block = jax.device_put(buf, sharding)
    fn = candidate_words_sharded(mesh)
    words, _ = fn(block, jnp.uint32(mask & 0xFFFFFFFF))
    wv = np.asarray(words)
    (idx,) = np.nonzero(wv)
    pos = gear._words_to_positions(idx.astype(np.uint32), wv[idx], n)
    return pos
