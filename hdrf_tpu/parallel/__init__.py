"""Multi-chip parallelism: mesh construction, sequence-parallel CDC scan with
ICI halo exchange, data-parallel SHA lanes, the combined sharded reduction
step, and the REAL variable-chunk sharded pipeline (reduce_sharded)."""

from hdrf_tpu.parallel.sharded import (  # noqa: F401
    candidate_words_sharded,
    gear_candidates_sharded,
    make_mesh,
    reduce_sharded,
    reduction_step,
    sha256_lanes_sharded,
)
