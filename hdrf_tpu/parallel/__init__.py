"""Multi-chip parallelism: mesh construction, sequence-parallel CDC scan with
ICI halo exchange, data-parallel SHA lanes, and the combined sharded
reduction step (see sharded.py)."""

from hdrf_tpu.parallel.sharded import (  # noqa: F401
    candidate_words_sharded,
    gear_candidates_sharded,
    make_mesh,
    reduction_step,
    sha256_lanes_sharded,
)
