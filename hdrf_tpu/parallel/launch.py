"""Multi-host launcher for the sharded reduction pipeline.

Re-expresses the reference's multi-host bring-up: per-host daemon entry
points (``DataNode.java:3561`` main -> instantiateDataNode; the
``hdfs --daemon`` scripts under ``hadoop-hdfs/src/main/bin/hdfs``) plus the
in-node thread-group scaling of the hot loops
(``DataDeduplicator.java:536-650`` threadedHasher's hand-rolled recursive
spawns).  The TPU-native form is ``jax.distributed``: every host runs THIS
module, rank 0 doubles as coordinator, and the per-host chips merge into
one global device set that `parallel/sharded.py`'s ('data','seq') mesh
spans — XLA then lays the ppermute/all_gather collectives onto ICI within
a slice and DCN across slices (SURVEY §2.4's "intra-pod data movement over
jax collectives").

``reduce_sharded`` itself is host-count agnostic; what this module adds is
the bring-up (coordinator handshake, global mesh construction) and the two
multi-process array plumbing helpers it needs:

- ``put_global``  — host numpy -> globally-sharded jax.Array (each process
  feeds only its addressable shards);
- ``fetch_global`` — globally-sharded jax.Array -> identical full numpy on
  every host (process_allgather), so the host-side cut selection stays a
  deterministic pure function replicated on all ranks, exactly like the
  single-process path.

Ops entry point::

    python -m hdrf_tpu.parallel.launch --coordinator HOST:PORT \
        --nprocs N --rank R [--n-data D] [--selftest MB]

On TPU pods where the runtime provides topology env vars,
``--coordinator``/``--rank`` may be omitted (jax.distributed auto-detects).
"""

from __future__ import annotations

import argparse
import sys

import jax
import numpy as np


def initialize(coordinator: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None) -> None:
    """Join (or form) the multi-host system.  All three None = the TPU-pod
    auto-detection path; explicit values = the portable/CPU path."""
    if coordinator is None and num_processes is None and process_id is None:
        jax.distributed.initialize()
    else:
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=num_processes,
                                   process_id=process_id)


def global_mesh(n_data: int = 1):
    """('data','seq') mesh over ALL global devices (every host's chips)."""
    from hdrf_tpu.parallel.sharded import make_mesh

    return make_mesh(n_data=n_data, devices=jax.devices())


def put_global(arr: np.ndarray, sharding) -> jax.Array:
    """Host array -> global sharded jax.Array (sharded._put_global)."""
    from hdrf_tpu.parallel.sharded import _put_global

    return _put_global(arr, sharding)


def fetch_global(x: jax.Array) -> np.ndarray:
    """Global sharded jax.Array -> full numpy on EVERY host
    (sharded._fetch_global)."""
    from hdrf_tpu.parallel.sharded import _fetch_global

    return _fetch_global(x)


def run_reduce(data, cdc=None, n_data: int = 1):
    """Multi-host entry for one block's (cuts, digests)."""
    from hdrf_tpu.config import CdcConfig
    from hdrf_tpu.parallel.sharded import reduce_sharded

    return reduce_sharded(data, cdc or CdcConfig(), global_mesh(n_data))


def _selftest(mb: int, n_data: int) -> bool:
    """Every rank reduces the same seeded block on the global mesh and
    checks bit-identity against the native oracle."""
    from hdrf_tpu import native
    from hdrf_tpu.config import CdcConfig
    from hdrf_tpu.ops.dispatch import gear_mask

    cdc = CdcConfig()
    rng = np.random.default_rng(7)
    data = rng.integers(0, 256, mb << 20, dtype=np.uint8)
    # make it compressible/structured so cuts are non-trivial
    data[::3] = 0
    cuts, digests = run_reduce(data, cdc, n_data=n_data)
    pos = native.gear_candidates(data.tobytes(), gear_mask(cdc))
    want_cuts = native.cdc_select(pos, data.size, cdc.min_chunk,
                                  cdc.max_chunk)
    ok = np.array_equal(cuts, np.asarray(want_cuts))
    starts = np.concatenate([[0], want_cuts[:-1]]).astype(np.int64)
    want_digs = native.sha256_batch(data, starts, (want_cuts - starts))
    ok = ok and np.array_equal(digests, want_digs)
    from hdrf_tpu.utils import log

    log.get_logger("launch", stream=sys.stdout).info(
        f"rank {jax.process_index()}/{jax.process_count()}: "
        f"devices={jax.device_count()} chunks={len(cuts)} "
        f"oracle_match={ok}",
        rank=jax.process_index(), oracle_match=bool(ok))
    return ok


def supervised_worker(backend: str = "auto"):
    """Spawn a supervised co-located reduction worker on this host (the
    per-host daemon bring-up role of the reference's ``hdfs --daemon``
    scripts, with the supervision the reference leaves to init systems):
    returns the started WorkerSupervisor — the worker is respawned with
    capped backoff if it dies, and ``supervisor.addr`` always names the
    live incarnation."""
    from hdrf_tpu.server.reduction_worker import WorkerSupervisor

    sup = WorkerSupervisor(backend=backend)
    sup.start()
    return sup


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="hdrf-launch")
    ap.add_argument("--coordinator", default=None,
                    help="host:port of rank 0 (omit on TPU pods)")
    ap.add_argument("--nprocs", type=int, default=None)
    ap.add_argument("--rank", type=int, default=None)
    ap.add_argument("--n-data", type=int, default=1,
                    help="'data' axis size of the mesh")
    ap.add_argument("--selftest", type=int, default=0, metavar="MB",
                    help="reduce a seeded MB-sized block and verify "
                         "against the native oracle")
    ap.add_argument("--with-worker", action="store_true",
                    help="also spawn a SUPERVISED co-located reduction "
                         "worker on this host (auto-respawn on death)")
    args = ap.parse_args(argv)
    initialize(args.coordinator, args.nprocs, args.rank)
    from hdrf_tpu.utils import log

    logger = log.get_logger("launch", stream=sys.stdout)
    sup = None
    if args.with_worker:
        sup = supervised_worker()
        logger.info(
            f"supervised reduction worker listening on "
            f"{sup.addr[0]}:{sup.addr[1]}", rank=jax.process_index())
    try:
        if args.selftest:
            return 0 if _selftest(args.selftest, args.n_data) else 1
        logger.info(
            f"rank {jax.process_index()}/{jax.process_count()} up; "
            f"{jax.local_device_count()} local / {jax.device_count()} "
            f"global devices", rank=jax.process_index())
        return 0
    finally:
        if sup is not None:
            sup.stop()


if __name__ == "__main__":
    raise SystemExit(main())
