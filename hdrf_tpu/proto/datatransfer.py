"""Bulk data-transfer protocol: framed packet streaming on a raw TCP socket.

Modeled on the reference's ``DataTransferProtocol``
(hadoop-hdfs-client/.../datatransfer/DataTransferProtocol.java:42): a
connection carries one op — an op header, then for WRITE/READ a run of framed
packets with per-packet checksums, with acks flowing back on the same socket
(BlockReceiver's PacketResponder, BlockReceiver.java:1509).

Wire layout:

- Op header: one msgpack frame ``[op_name, fields_dict]`` (length-prefixed via
  proto.rpc.send_frame).  ``fields["_trace"]`` resumes a client span
  server-side (Receiver.java:94-98 continueTraceSpan).
- Packet:    ``[u32 data_len][u64 seqno][u8 flags][u32 crc32c(data)]`` + data
  (the reference's PacketHeader: 64 KB default payload, crc per checksum chunk;
  here one crc32c per packet — checksum chunking for range reads lives in
  BlockMeta.checksums).
- Ack:       ``[u64 seqno][u8 status]`` per packet, status 0 = SUCCESS; for
  pipelines the ack aggregates downstream status (worst wins), the analog of
  PipelineAck.

Ops (Receiver.java:101-135 op dispatch analog): WRITE_BLOCK, READ_BLOCK,
TRANSFER_BLOCK, COPY_BLOCK, BLOCK_CHECKSUM — dispatched by the DataNode's
xceiver loop.
"""

from __future__ import annotations

import socket
import struct
from typing import Any, Iterator

from hdrf_tpu import native
from hdrf_tpu.proto.rpc import recv_exact, recv_frame, send_frame
from hdrf_tpu.utils import retry, tracing

PKT_HDR = struct.Struct("<IQBI")
FLAG_LAST = 0x1
# hflush/hsync markers (DFSOutputStream.java:573 hflush / :580 hsync; the
# reference rides syncBlock on the packet header, PacketHeader.java): a
# FLUSH-flagged packet makes the receiver expose the prefix to readers
# (visible length) before acking; SYNC additionally fsyncs the replica.
FLAG_FLUSH = 0x2
FLAG_SYNC = 0x4

ACK = struct.Struct("<QB")
ACK_SUCCESS = 0
ACK_ERROR = 1
# Admission shed (utils/qos.py ShedError crossing the write wire): the DN
# refused the block AT ADMISSION — retryable, nothing was stored.  Shed
# acks repurpose the seqno field to carry the retry-after hint in
# MILLISECONDS (the 8-byte slot is wasted on a refusal; the reference's
# PipelineAck rides ECN/restart hints in spare header fields the same way).
ACK_SHED = 2

DEFAULT_PACKET = 64 * 1024

# Op names (DataTransferProtocol.java op codes)
WRITE_BLOCK = "write_block"
READ_BLOCK = "read_block"
TRANSFER_BLOCK = "transfer_block"
COPY_BLOCK = "copy_block"
BLOCK_CHECKSUM = "block_checksum"
# EC cold-tier stripe ops (server/ec_tier.py; DN-protocol trust — stripe
# ops never carry client bytes).  STRIPE_CODED_READ is the coded-exchange
# sibling of STRIPE_READ: the request carries a per-DN chain plan plus
# negotiation fields (``accept_enc`` — may the response ship LZ4'd payloads
# with per-item ``enc`` flags?), so a peer that predates the op simply
# books unknown_ops and answers nothing — the caller's recv fails and it
# falls back to plain STRIPE_READ legs, byte-identical results either way.
STRIPE_READ = "stripe_read"
STRIPE_WRITE = "stripe_write"
STRIPE_CODED_READ = "stripe_coded_read"


def secure_socket(sock: socket.socket, token: dict | None, encrypt: bool):
    """Wrap a freshly connected data socket with the AEAD record layer when
    encryption is on (security.client_handshake, keyed by the block token —
    the datatransfer/sasl analog).  Returns the socket to use for the op."""
    if not encrypt:
        return sock
    from hdrf_tpu import security

    if not token or not token.get("sig"):
        raise PermissionError("data-transfer encryption requires block "
                              "tokens (dfs.block.access.token.enable)")
    return security.client_handshake(sock, token)


def send_op(sock: socket.socket, op: str, **fields: Any) -> None:
    tr = tracing.current_context()
    if tr is not None:
        fields["_trace"] = list(tr)
    # remaining deadline budget rides the op header beside _trace (the
    # receiving DN rebinds it around its handler — datanode._xceive)
    hdr = retry.remaining_header()
    if hdr is not None:
        fields[retry.DEADLINE_KEY] = hdr
    send_frame(sock, [op, fields])


def recv_op(sock: socket.socket) -> tuple[str, dict]:
    op, fields = recv_frame(sock)
    return op, fields


def write_packet(sock: socket.socket, seqno: int, data: bytes,
                 last: bool = False, flags: int = 0) -> None:
    flags |= FLAG_LAST if last else 0
    sock.sendall(PKT_HDR.pack(len(data), seqno, flags, native.crc32c(data)))
    if data:
        sock.sendall(data)


def read_packet_ex(sock: socket.socket) -> tuple[int, bytes, int]:
    """Returns (seqno, data, flags); raises IOError on checksum mismatch —
    the receiver-side verify the reference does per checksum chunk."""
    ln, seqno, flags, crc = PKT_HDR.unpack(recv_exact(sock, PKT_HDR.size))
    data = recv_exact(sock, ln) if ln else b""
    if native.crc32c(data) != crc:
        raise IOError(f"packet {seqno}: checksum mismatch")
    return seqno, data, flags


def read_packet(sock: socket.socket) -> tuple[int, bytes, bool]:
    seqno, data, flags = read_packet_ex(sock)
    return seqno, data, bool(flags & FLAG_LAST)


def iter_packets(sock: socket.socket) -> Iterator[tuple[int, bytes, bool]]:
    while True:
        seqno, data, last = read_packet(sock)
        yield seqno, data, last
        if last:
            return


def iter_packets_ex(sock: socket.socket) -> Iterator[tuple[int, bytes, int]]:
    """Flag-preserving packet run iterator (the write path needs FLUSH/SYNC
    markers; readers of whole runs use iter_packets)."""
    while True:
        seqno, data, flags = read_packet_ex(sock)
        yield seqno, data, flags
        if flags & FLAG_LAST:
            return


def send_ack(sock: socket.socket, seqno: int, status: int = ACK_SUCCESS) -> None:
    sock.sendall(ACK.pack(seqno, status))


def read_ack(sock: socket.socket) -> tuple[int, int]:
    seqno, status = ACK.unpack(recv_exact(sock, ACK.size))
    return seqno, status


def stream_bytes(sock: socket.socket, data: bytes,
                 packet_size: int = DEFAULT_PACKET, base_seqno: int = 0,
                 throttle=None) -> int:
    """Packetize ``data`` onto the socket, ending with an empty LAST packet
    (the reference's zero-payload trailer that carries lastPacketInBlock).
    Returns the number of packets sent.  ``throttle(nbytes)`` is invoked
    before each packet when given (DataTransferThrottler's per-packet
    gating in BlockSender.sendPacket)."""
    seqno = base_seqno
    for off in range(0, len(data), packet_size):
        pkt = data[off:off + packet_size]
        if throttle is not None:
            throttle(len(pkt))
        write_packet(sock, seqno, pkt)
        seqno += 1
    write_packet(sock, seqno, b"", last=True)
    return seqno - base_seqno + 1


def fetch_block(addr: tuple, block_id: int, offset: int = 0,
                length: int = -1, timeout: float = 60,
                token: dict | None = None, encrypt: bool = False) -> bytes:
    """One-shot READ_BLOCK: connect, request [offset, offset+length), collect
    the packet run, length-check.  Shared by the EC degraded-read path
    (client/striped.py) and DN reconstruction fan-in (server/datanode.py)."""
    from hdrf_tpu.proto.rpc import recv_frame

    sock = socket.create_connection(addr, timeout=timeout)
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock = secure_socket(sock, token, encrypt)
        send_op(sock, READ_BLOCK, block_id=block_id, offset=offset,
                length=length, token=token)
        hdr = recv_frame(sock)
        if hdr["status"] != 0:
            if hdr.get("error") == "ShedError":
                from hdrf_tpu.utils import qos

                raise qos.ShedError(
                    f"datanode shed: {hdr.get('message', '')}",
                    retry_after_s=float(hdr.get("retry_after_s") or 0.0))
            raise IOError(f"datanode error: {hdr['error']}: "
                          f"{hdr.get('message', '')}")
        data = collect_packets(sock)
        if len(data) != hdr["length"]:
            raise IOError(f"short read: {len(data)} != {hdr['length']}")
        return data
    finally:
        sock.close()


def collect_packets(sock: socket.socket, ack_sock: socket.socket | None = None,
                    on_packet=None) -> bytes:
    """Receive a full packet run; optionally ack each packet on ``ack_sock``
    and/or forward via ``on_packet(seqno, data, last)`` (mirroring hook)."""
    parts: list[bytes] = []
    for seqno, data, last in iter_packets(sock):
        parts.append(data)
        if on_packet is not None:
            on_packet(seqno, data, last)
        if ack_sock is not None:
            send_ack(ack_sock, seqno)
    return b"".join(parts)
