"""Control-plane RPC: length-prefixed msgpack frames over TCP.

Plays the role of Hadoop IPC (protobuf-over-IPC services + the ``protocolPB``
translator layers, ~12 kLoC in the reference) for all NN<->client and NN<->DN
control traffic.  One frame = [u32 len][msgpack body].

Request body:  ``[req_id, method, kwargs]``; kwargs may carry ``_trace``, a
``(trace_id, span_id)`` pair resumed server-side (the reference's
``continueTraceSpan``, Receiver.java:94-98).
Response body: ``[req_id, 0, result]`` or ``[req_id, 1, {"error", "message"}]``
— errors round-trip as :class:`RpcError` (the IPC RemoteException analog).

State-id protocol (ISSUE 20): a service exposing ``_rpc_state_id()`` (the
NameNode) gets that dict appended as a FOURTH reply element on every wire
response — ``[req_id, status, payload, {"txid", "role", "lag_s"}]`` — and
clients piggyback their high-water ``last_seen_txid`` back as the ``_sid``
side-channel kwarg, which an observer's ``_rpc_observer_gate`` hook enforces
before dispatch.  This re-expresses the reference's RpcRequestHeaderProto
``stateId`` / GlobalStateIdContext.java:40 + ObserverReadProxyProvider.java:60
read-your-writes plumbing on the msgpack channel; clients unpacking with
``*extra`` stay compatible with 3-element replies from stateless services.

Server threading model is thread-per-connection, mirroring the reference's
thread-per-DataXceiver design (DataXceiverServer.java:44) — but bounded:
``max_handlers`` caps live handler threads the way ``dfs.datanode.max.transfer
.threads`` caps xceivers (the accept loop parks past the cap, so overload
backs up into the TCP listen queue instead of an unbounded thread spawn).

NameNode service-time decomposition (ISSUE 18): every wire request's wall
clock is partitioned into ``frame_read`` / ``dispatch_queue`` / ``lock_wait``
/ ``locked`` / ``serialize`` / ``reply`` phases via the write-path profiler's
exclusive-class boundary sweep (utils/profiler.py profile_spans) — the
decomposition the reference never had for its RPC layer (RpcMetrics.java:118
keeps one queue-time + one processing-time average per server, never
per-method, never lock-attributed).  Lock phases ride the ambient
request context (utils/lockprof.py bind_request).
"""

from __future__ import annotations

import contextlib
import socket
import socketserver
import struct
import threading
import time
from typing import Any

import msgpack

from hdrf_tpu.utils import (fault_injection, lockprof, metrics, profiler,
                            retry, rollwin, tenants, tracing)

_LEN = struct.Struct("<I")
MAX_FRAME = 64 * 1024 * 1024


class RpcError(Exception):
    """Server-side exception re-raised at the caller (RemoteException analog)."""

    def __init__(self, error: str, message: str):
        super().__init__(f"{error}: {message}")
        self.error = error
        self.message = message


def recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ConnectionError("peer closed connection")
        got += r
    return bytes(buf)


def send_frame(sock: socket.socket, body: Any) -> None:
    payload = msgpack.packb(body)
    if len(payload) > MAX_FRAME:
        raise ValueError(f"frame too large: {len(payload)}")
    sock.sendall(_LEN.pack(len(payload)) + payload)


def recv_frame(sock: socket.socket) -> Any:
    (n,) = _LEN.unpack(recv_exact(sock, _LEN.size))
    if n > MAX_FRAME:
        raise ConnectionError(f"oversized frame: {n}")
    return msgpack.unpackb(recv_exact(sock, n), raw=False, use_list=True,
                           strict_map_key=False)


@contextlib.contextmanager
def _null_ctx():
    yield


class RpcServer:
    """Serves ``rpc_*`` methods of a service object.

    >>> class Svc:
    ...     def rpc_add(self, a, b): return a + b
    >>> srv = RpcServer("127.0.0.1", 0, Svc(), "test"); srv.start()
    """

    def __init__(self, host: str, port: int, service: Any, name: str,
                 watchdog: Any | None = None,
                 max_handlers: int | None = None):
        """``watchdog``: optional utils.watchdog.StallWatchdog — every
        dispatched method is tracked so handler threads wedged past the
        budget (VM write-burst stalls) surface in stall_total/stacks.
        ``max_handlers``: cap on live handler threads (one per connection);
        past it the accept loop itself parks, so a metadata storm backs up
        into the TCP listen queue instead of spawning without bound."""
        self._service = service
        self._name = name
        self._metrics = metrics.registry(f"rpc.{name}")
        self._tracer = tracing.tracer(f"rpc.{name}")
        self._watchdog = watchdog
        # Metadata-plane latency axis (RpcMetrics#addRpcProcessingTime
        # analog): per-method histograms + one rolling window feeding a
        # p99 gauge into the NN flight record.  NN-only — the DN control
        # plane has no RPC server of its own worth the extra books.
        self._lat_win = (rollwin.RollingWindow(window_s=300.0, maxlen=512)
                        if name == "namenode" else None)
        # Cumulative phase-attribution accountant (NN only): how much of
        # the dispatched wall clock the named phases explain — the >= 95%
        # contention-observatory acceptance bar, cheap enough to keep
        # always-on (two float adds per request).
        self._attr_lock = threading.Lock()
        self._attr_wall_s = 0.0
        self._attr_used_s = 0.0
        self.max_handlers = max_handlers
        self._handler_sem = (threading.BoundedSemaphore(max_handlers)
                             if max_handlers else None)
        self._count_lock = threading.Lock()
        self._handler_threads = 0
        self._inflight = 0
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:  # one thread per connection
                sock = self.request
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                outer._conns.add(sock)
                try:
                    while True:
                        outer._serve_one(sock)
                except (ConnectionError, OSError):
                    return
                finally:
                    outer._conns.discard(sock)

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

            def process_request(self, request, client_address):
                # Accept-loop backpressure: a full handler pool parks the
                # acceptor HERE, before the thread spawn — new connections
                # queue in the kernel listen backlog (the xceiver-cap
                # refusal analog, soft form).
                if outer._handler_sem is not None:
                    outer._handler_sem.acquire()
                super().process_request(request, client_address)

            def process_request_thread(self, request, client_address):
                outer._note_handler(+1)
                try:
                    super().process_request_thread(request, client_address)
                finally:
                    outer._note_handler(-1)
                    if outer._handler_sem is not None:
                        outer._handler_sem.release()

        self._server = Server((host, port), Handler)
        self._conns: set[socket.socket] = set()
        self._thread: threading.Thread | None = None
        self._retry_cache: dict[str, tuple[float, list]] = {}
        self._retry_lock = threading.Lock()

    def _note_handler(self, delta: int) -> None:
        with self._count_lock:
            self._handler_threads += delta
            self._metrics.gauge("rpc_handler_threads",
                                float(self._handler_threads))

    def _note_inflight(self, delta: int) -> None:
        with self._count_lock:
            self._inflight += delta
            self._metrics.gauge("rpc_inflight", float(self._inflight))

    @property
    def addr(self) -> tuple[str, int]:
        return self._server.server_address  # resolved (host, real_port)

    def rpc_p99_ms(self) -> float:
        """Rolling p99 RPC processing latency (ms) over the last window —
        the ``nn_rpc_p99_ms`` gauge the NN flight record samples."""
        if self._lat_win is None:
            return 0.0
        q = self._lat_win.quantiles((99,))
        return (q or {}).get("p99", 0.0) / 1e3

    def _serve_one(self, sock: socket.socket) -> None:
        """One request/response cycle with service-time decomposition.

        The block on the 4-byte length header happens OUTSIDE the profiled
        window — a keep-alive connection parked between calls is idle, not
        service time.  From the header's arrival on, every segment lands as
        a span: body read (``frame_read``), side-channel/auth/cache work
        (``dispatch_queue``), the handler (``handler``, refined by the
        instrumented lock's ``lock_wait``/``locked``), response pack
        (``serialize``) and the write back (``reply``)."""
        hdr = recv_exact(sock, _LEN.size)
        t0 = time.perf_counter()
        (n,) = _LEN.unpack(hdr)
        if n > MAX_FRAME:
            raise ConnectionError(f"oversized frame: {n}")
        body = recv_exact(sock, n)
        spans: list[tuple] = [("frame_read", t0, time.perf_counter())]
        req = msgpack.unpackb(body, raw=False, use_list=True,
                              strict_map_key=False)
        self._note_inflight(+1)
        try:
            resp = self._dispatch(req, spans=spans)
        finally:
            self._note_inflight(-1)
        # State-id stamp: one hook point covers every wire reply — success,
        # error, auth refusal and retry-cache replay alike — so the client's
        # txid high-water mark advances no matter how the call ended.
        state = self._state_stamp()
        if state is not None:
            resp = resp + [state]
        t_ser0 = time.perf_counter()
        payload = msgpack.packb(resp)
        if len(payload) > MAX_FRAME:
            raise ValueError(f"frame too large: {len(payload)}")
        t_ser1 = time.perf_counter()
        spans.append(("serialize", t_ser0, t_ser1))
        sock.sendall(_LEN.pack(len(payload)) + payload)
        t1 = time.perf_counter()
        spans.append(("reply", t_ser1, t1))
        if self._lat_win is not None and isinstance(req, list) and len(req) == 3:
            self._profile_request(str(req[1]), spans, t0, t1)

    def _profile_request(self, method: str, spans: list, t0: float,
                         t1: float) -> None:
        """Exclusive-phase partition of one request's service time
        (profiler.profile_spans — same sweep as the DN block timelines),
        observed as ``nn_rpc_phase_us|method=,phase=`` histograms plus the
        cumulative attributed-fraction accountant."""
        prof = profiler.profile_spans(spans, t0, t1)
        for name, s in prof["phases"].items():
            self._metrics.observe(f"nn_rpc_phase_us|method={method},"
                                  f"phase={name}", s * 1e6)
        with self._attr_lock:
            self._attr_wall_s += prof["wall_s"]
            self._attr_used_s += prof["wall_s"] * prof["attributed_frac"]

    def attributed_frac(self) -> float:
        """Cumulative share of dispatched wall clock explained by named
        phases (1.0 before any wire request — nothing unattributed yet)."""
        with self._attr_lock:
            return (self._attr_used_s / self._attr_wall_s
                    if self._attr_wall_s > 0 else 1.0)

    def contention_summary(self) -> dict:
        """Per-method RPC service table for ``/contention``: calls, errors,
        p99 µs and per-phase mean µs (from the cumulative histograms), plus
        the server-wide attribution and handler-pool gauges."""
        snap = self._metrics.snapshot()
        counters, hists = snap["counters"], snap["histograms"]
        methods: dict[str, dict] = {}
        for key, h in hists.items():
            if not key.startswith("nn_rpc_us|method="):
                continue
            m = key.split("method=", 1)[1]
            methods[m] = {"calls": counters.get(f"{m}_calls", 0),
                          "errors": counters.get(f"{m}_errors", 0),
                          "p99_us": h["p99"], "mean_us": h["mean"],
                          "phase_us": {}}
        for key, h in hists.items():
            if not key.startswith("nn_rpc_phase_us|method="):
                continue
            label = key.split("method=", 1)[1]
            m, _, phase = label.partition(",phase=")
            if m in methods:
                methods[m]["phase_us"][phase] = round(h["mean"], 1)
        return {"rpc_p99_ms": self.rpc_p99_ms(),
                "attributed_frac": self.attributed_frac(),
                "inflight": self._inflight,
                "handler_threads": self._handler_threads,
                "max_handlers": self.max_handlers,
                "methods": methods}

    def _state_stamp(self) -> dict | None:
        """The service's reply-envelope state dict (None for stateless
        services — their replies stay 3 elements, old-wire compatible)."""
        hook = getattr(self._service, "_rpc_state_id", None)
        if hook is None:
            return None
        try:
            return hook()
        except Exception:  # noqa: BLE001 — a stamp must never kill a reply
            return None

    def _dispatch(self, req: list, spans: list | None = None) -> list:
        req_id, method, kwargs = req
        # dispatch_queue starts where frame_read ended: side-channel
        # parsing, auth and the retry cache all land in that phase.
        t_in = spans[0][2] if spans else time.perf_counter()
        trace = kwargs.pop("_trace", None)
        retry_id = kwargs.pop("_retry_id", None)
        dtoken = kwargs.pop("_dtoken", None)
        sid = kwargs.pop("_sid", None)
        # Hop-by-hop deadline budget (remaining seconds, riding beside
        # _trace): a request arriving with a spent budget is refused
        # BEFORE dispatch — the caller already gave up, so running the
        # handler would only waste the server's cycles.
        deadline_hdr = kwargs.pop(retry.DEADLINE_KEY, None)
        if deadline_hdr is not None and float(deadline_hdr) <= 0:
            self._metrics.incr(f"{method}_deadline_rejected")
            return [req_id, 1, {"error": "DeadlineExceeded",
                                "message": f"{method}: deadline budget "
                                           "exhausted before dispatch"}]
        # Caller identity (UGI analog): populated into a per-thread context
        # the service's permission checker reads.  Only set for WIRE calls —
        # in-process invocations act as the superuser, like the reference's
        # own NN threads.
        from hdrf_tpu.server import permissions as _perm

        _perm.set_caller(kwargs.pop("_user", None),
                         kwargs.pop("_groups", None))
        # Tenant id for attribution only (utils/tenants.py) — stripped here
        # like the rest of the side-channel so handlers never see it.
        tenant = kwargs.pop("_client", None)
        fn = getattr(self._service, f"rpc_{method}", None)
        if fn is None:
            return [req_id, 1, {"error": "NoSuchMethod", "message": method}]
        auth = getattr(self._service, "_rpc_auth_hook", None)
        if auth is not None:
            try:
                auth(method, dtoken)
            except Exception as e:  # noqa: BLE001 — refusal crosses the wire
                self._metrics.incr(f"{method}_auth_rejected")
                return [req_id, 1, {"error": type(e).__name__,
                                    "message": str(e)}]
        # Observer read gate (_sid consistency check): on an observer this
        # refuses non-reads, waits out the bounded catch-up window for the
        # caller's state-id and enforces the staleness bound.  Runs before
        # the retry cache — a bounced read was never executed here.
        gate = getattr(self._service, "_rpc_observer_gate", None)
        if gate is not None:
            try:
                gate(method, sid)
            except Exception as e:  # noqa: BLE001 — bounce crosses the wire
                self._metrics.incr("observer_refused")
                return [req_id, 1, {"error": type(e).__name__,
                                    "message": str(e)}]
        if retry_id is not None:
            cached = self._retry_cache_get(retry_id)
            if cached is not None:
                self._metrics.incr("retry_cache_hits")
                return [req_id, *cached]
        track = (self._watchdog.track(f"rpc.{method}")
                 if self._watchdog is not None else _null_ctx())
        # Wire requests bind the ambient request context so the service's
        # instrumented lock attributes its wait/hold to this method and
        # lands lock_wait/locked spans in this request's decomposition;
        # in-process calls (spans is None) skip the stamp.
        req_ctx = (lockprof.bind_request(method, spans)
                   if spans is not None else _null_ctx())
        t_start = time.perf_counter()
        if spans is not None:
            spans.append(("dispatch_queue", t_in, t_start))
        with retry.bind_remaining(deadline_hdr), track, req_ctx, \
                self._tracer.span(method,
                                  parent=tuple(trace) if trace else None):
            try:
                fault_injection.point("rpc.dispatch", server=self._name,
                                      method=method)
                with self._metrics.time(f"{method}_us"):
                    result = fn(**kwargs)
                self._metrics.incr(f"{method}_calls")
                out = [0, result]
            except Exception as e:  # noqa: BLE001 — errors cross the wire
                self._metrics.incr(f"{method}_errors")
                out = [1, {"error": type(e).__name__, "message": str(e)}]
        t_h1 = time.perf_counter()
        if spans is not None:
            spans.append(("handler", t_start, t_h1))
        if self._lat_win is not None:
            dt_us = (time.perf_counter() - t_start) * 1e6
            self._metrics.observe(f"nn_rpc_us|method={method}", dt_us)
            self._lat_win.add(dt_us)
        if tenant is not None:  # wire calls carrying a client id only
            tenants.note_op(tenant, f"rpc.{method}",
                            latency_s=time.perf_counter() - t_start)
        if retry_id is not None:
            self._retry_cache_put(retry_id, out)
        if spans is not None:
            # tail bookkeeping (lat window, tenant note, retry cache) stays
            # attributed — a second dispatch_queue span, same exclusive
            # class, so the sweep folds it in without a dedicated phase
            spans.append(("dispatch_queue", t_h1, time.perf_counter()))
        return [req_id, *out]

    # RetryCache analog: replayed responses for at-least-once HA retries.
    _RETRY_TTL = 120.0

    def _retry_cache_get(self, rid: str):
        import time as _t

        with self._retry_lock:
            ent = self._retry_cache.get(rid)
            if ent and ent[0] > _t.monotonic():
                return ent[1]
            return None

    def _retry_cache_put(self, rid: str, out: list) -> None:
        import time as _t

        now = _t.monotonic()
        with self._retry_lock:
            self._retry_cache[rid] = (now + self._RETRY_TTL, out)
            if len(self._retry_cache) > 50_000:  # expire the stale half
                self._retry_cache = {k: v for k, v in
                                     self._retry_cache.items()
                                     if v[0] > now}

    def start(self) -> "RpcServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, name=f"rpc-{self._name}", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        # Sever live connections too: a stopped server must look DEAD to its
        # peers (handler threads would otherwise keep answering RPCs — clients
        # of a restarted daemon would talk to the zombie forever).
        for s in list(self._conns):
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            s.close()


def normalize_addrs(addr) -> list[tuple[str, int]]:
    """One (host, port) pair or any sequence of pairs -> list of tuples."""
    if (isinstance(addr, (list, tuple)) and addr
            and isinstance(addr[0], (list, tuple))):
        return [(a[0], int(a[1])) for a in addr]
    return [(addr[0], int(addr[1]))]


_HM = metrics.registry("client.ha")
_MISS = object()  # sentinel: no observer could answer; fall back to active


class HaRpcClient:
    """Failover proxy over an ordered NN list (the reference's
    ConfiguredFailoverProxyProvider + RetryProxy analog): on connection
    failure or StandbyError, rotate to the next address; remember the last
    good one.

    Observer routing (ObserverReadProxyProvider.java:60 analog): with
    ``observer_reads`` on, READ_METHODS are offered to every known observer
    first, carrying the proxy's ``last_seen_txid`` as the ``_sid``
    side-channel for read-your-writes.  A stale observer bounces the call
    with a typed ObserverStaleError — counted, retried on the active, never
    silently stale; a dead one trips its per-endpoint circuit breaker
    (utils/retry.py breaker registry) and is skipped until it half-opens.
    Endpoint roles are discovered lazily over ``ha_state`` and refreshed on
    a TTL, so a promotion or observer restart is picked up without
    reconfiguration."""

    RETRIABLE = ("StandbyError",)
    # Client-side mirror of the NN's observer-servable read set: only these
    # are worth offering to a read replica (everything else either mutates
    # or is NN-instance-specific admin plumbing).
    READ_METHODS = frozenset({
        "get_block_locations", "stat", "listing", "ec_status",
        "content_summary", "get_xattrs", "get_acl", "get_storage_policy",
        "list_snapshots", "snapshot_diff", "list_cache_pools",
        "list_cache_directives", "list_encryption_zones", "get_ez",
        "datanode_report", "cluster_status", "decommission_status",
        "slow_nodes_report", "slow_peers", "policy_violations",
        "get_events", "fsck", "check_delegation_token",
    })
    ROLE_TTL_S = 10.0

    def __init__(self, addrs: list[tuple[str, int]], timeout: float = 30.0,
                 observer_reads: bool = True):
        self._clients = [RpcClient(a, timeout) for a in normalize_addrs(addrs)]
        self._cur = 0
        self.observer_reads = observer_reads
        self._roles: list[str | None] = [None] * len(self._clients)
        self._roles_t = float("-inf")  # first use forces a discovery pass
        # High-water journal txid observed across ALL endpoints (the
        # ClientGSIContext the reference keeps per-proxy-provider).
        self.last_seen_txid = 0

    def _breaker(self, c: "RpcClient"):
        return retry.breaker(f"nn:{c._addr[0]}:{c._addr[1]}")

    def _note_state(self, c: "RpcClient") -> None:
        if c.last_seen_txid > self.last_seen_txid:
            self.last_seen_txid = c.last_seen_txid

    def _refresh_roles(self, force: bool = False) -> None:
        now = time.monotonic()
        if not force and now - self._roles_t < self.ROLE_TTL_S:
            return
        self._roles_t = now
        for i, c in enumerate(self._clients):
            br = self._breaker(c)
            if not br.allow():
                self._roles[i] = None
                continue
            try:
                st = c.call("ha_state")
            except (ConnectionError, OSError):
                br.record_failure()
                self._roles[i] = None
                continue
            except RpcError:
                br.record_success()  # endpoint alive, role just unknown
                self._roles[i] = None
                continue
            br.record_success()
            self._note_state(c)
            self._roles[i] = st.get("role")

    def _observer_call(self, method: str, kwargs: dict) -> Any:
        """Offer a read to each known observer; _MISS means none answered
        (no observers configured, all stale/bounced, or breakers open)."""
        self._refresh_roles()
        for i, role in enumerate(self._roles):
            if role != "observer":
                continue
            c = self._clients[i]
            br = self._breaker(c)
            if not br.allow():
                _HM.incr("observer_skipped_open")
                continue
            kw = dict(kwargs)
            kw["_sid"] = self.last_seen_txid
            try:
                out = c.call(method, **kw)
            except retry.DeadlineExceeded:
                raise
            except (ConnectionError, OSError):
                # dead observer: the BREAKER is the demotion — the role map
                # keeps the entry so the strike count accumulates across
                # reads (connect-refused fails fast), and once open,
                # allow() gates this endpoint to half-open probes only
                br.record_failure()
                _HM.incr("observer_demotions")
                continue
            except RpcError as e:
                br.record_success()
                self._note_state(c)
                if e.error == "ObserverStaleError":
                    _HM.incr("observer_bounces")
                    continue  # bounded-staleness bounce: active serves it
                if e.error == "StandbyError":
                    self._roles[i] = None  # role changed under us
                    continue
                raise  # real application error from a consistent read
            br.record_success()
            self._note_state(c)
            _HM.incr("observer_reads")
            return out
        return _MISS

    def msync(self, wait_s: float | None = None) -> dict:
        """Consistency barrier (FileSystem.msync analog): ask every
        reachable observer to catch up to this proxy's ``last_seen_txid``.
        Returns per-endpoint msync replies ({} with no observers — a
        single active is strongly consistent already)."""
        self._refresh_roles(force="observer" not in self._roles)
        out: dict[str, Any] = {}
        for i, role in enumerate(self._roles):
            if role != "observer":
                continue
            c = self._clients[i]
            kw: dict[str, Any] = {"txid": self.last_seen_txid}
            if wait_s is not None:
                kw["wait_s"] = wait_s
            try:
                out[f"{c._addr[0]}:{c._addr[1]}"] = c.call("msync", **kw)
                self._note_state(c)
            except (ConnectionError, OSError, RpcError):
                continue
        return out

    def call(self, method: str, **kwargs: Any) -> Any:
        if (self.observer_reads and method in self.READ_METHODS
                and "_sid" not in kwargs):
            out = self._observer_call(method, kwargs)
            if out is not _MISS:
                return out
        # One retry id per LOGICAL call: a mutation that succeeded just before
        # the connection died must not re-execute when the proxy retries — the
        # server's retry cache replays the original response instead (the
        # NameNode RetryCache that HDFS pairs with its failover proxy).
        import uuid as _uuid

        kwargs["_retry_id"] = _uuid.uuid4().hex
        last: Exception | None = None
        attempts = 2 * len(self._clients)
        # second lap onward: capped full-jitter backoff instead of a fixed
        # beat, so a thundering herd of proxies doesn't re-poll in lockstep
        delays = retry.backoff_delays(attempts, base_s=0.1, cap_s=2.0)
        # Known observers are not failover targets — skip them for free
        # (no attempt consumed) unless they are all we have.
        n_obs = sum(1 for r in self._roles if r == "observer")
        skip_observers = 0 < n_obs < len(self._clients)
        attempt = 0
        while attempt < attempts:
            dl = retry.current()
            if dl is not None:
                dl.check("namenode failover")  # spent budget: stop retrying
            if skip_observers and self._roles[self._cur] == "observer":
                self._cur = (self._cur + 1) % len(self._clients)
                continue
            c = self._clients[self._cur]
            attempt += 1
            try:
                out = c.call(method, **kwargs)
                self._note_state(c)
                return out
            except retry.DeadlineExceeded:
                raise
            except (ConnectionError, OSError) as e:
                last = e
            except RpcError as e:
                self._note_state(c)
                if e.error not in self.RETRIABLE:
                    raise
                last = e
            self._cur = (self._cur + 1) % len(self._clients)
            if attempt > len(self._clients):
                import time as _t

                delay = next(delays)
                if dl is not None:
                    delay = min(delay, dl.remaining())
                if delay > 0:
                    _t.sleep(delay)
        raise ConnectionError(f"all namenodes failed: {last}")

    def close(self) -> None:
        for c in self._clients:
            c.close()

    def __enter__(self) -> "HaRpcClient":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


class RpcClient:
    """Blocking RPC client; one socket, requests serialized by a lock.
    Reconnects on the next call after a connection failure."""

    def __init__(self, addr: tuple[str, int], timeout: float = 30.0):
        self._addr = (addr[0], addr[1])
        self._timeout = timeout
        self._lock = threading.Lock()
        self._sock: socket.socket | None = None
        self._req_id = 0
        # State-id bookkeeping (ClientGSIContext analog): the last reply's
        # state stamp and the high-water journal txid this client has
        # observed — what observer reads present as ``_sid``.
        self.last_state: dict | None = None
        self.last_seen_txid = 0

    def _connect(self) -> socket.socket:
        s = socket.create_connection(
            self._addr, timeout=retry.effective_budget(self._timeout))
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return s

    def call(self, method: str, **kwargs: Any) -> Any:
        tr = tracing.current_context()
        if tr is not None:
            kwargs["_trace"] = list(tr)
        # Ambient deadline: refuse a spent budget before touching the
        # socket, stamp the remaining seconds as the hop-by-hop header,
        # and clamp this call's socket timeout to the remainder.
        dl = retry.current()
        if dl is not None:
            dl.check(f"rpc {method}")
            kwargs[retry.DEADLINE_KEY] = dl.header()
        with self._lock:
            self._req_id += 1
            req_id = self._req_id
            try:
                if self._sock is None:
                    self._sock = self._connect()
                self._sock.settimeout(
                    dl.timeout(self._timeout) if dl is not None
                    else self._timeout)
                send_frame(self._sock, [req_id, method, kwargs])
                resp = recv_frame(self._sock)
            except (ConnectionError, OSError):
                self.close()
                raise
        rid, status, payload, *extra = resp
        if rid != req_id:
            self.close()
            raise ConnectionError(f"rpc response id mismatch: {rid} != {req_id}")
        # Record the state stamp BEFORE raising: an error reply (e.g. an
        # ObserverStaleError bounce) still advances the txid high-water.
        if extra and isinstance(extra[0], dict):
            self.last_state = extra[0]
            txid = extra[0].get("txid")
            if isinstance(txid, int) and txid > self.last_seen_txid:
                self.last_seen_txid = txid
        if status != 0:
            raise RpcError(payload["error"], payload["message"])
        return payload

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def __enter__(self) -> "RpcClient":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()
