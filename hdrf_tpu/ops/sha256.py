"""SHA-256 fingerprinting vectorized across chunk lanes on TPU.

Replaces the reference's per-chunk JNI hashing (utilities.sha1hash,
utilities.java:98-137, libnayuki-native-hashes.so) — which pays a JNI crossing
and a sequential hash per chunk — with one device program that runs the SHA-256
compression function for *all* chunks of a block simultaneously: the 64-round
recurrence is serial per chunk but embarrassingly parallel across the ~16K
chunks of a 128 MB block, mapping onto the VPU's 8x128 uint32 lanes.

Chunks are padded host-side (standard SHA padding) into fixed-shape lane
buffers, bucketed by 64-byte block count to bound wasted lanes, then a single
`lax.scan` over the block axis advances every lane's digest state with
per-lane active masking.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

_K = np.array([
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2], dtype=np.uint32)

_H0 = np.array([0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19], dtype=np.uint32)


def _rotr(x: jax.Array, n: int) -> jax.Array:
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


def _compress(state: list[jax.Array], blk: jax.Array) -> list[jax.Array]:
    """One SHA-256 compression for every lane.

    state: 8 arrays u32[R, 128]; blk: u32[16, R, 128] (big-endian words).
    Lanes live as (R, 128) tiles — the natural VPU layout; a flat (L,) vector
    wastes sublanes and measured ~5x slower.

    Both the message-schedule extension and the 64 rounds are ``lax.scan``s
    with partial unroll, NOT fully unrolled Python loops: a fully unrolled
    compression whose output feeds the Davies-Meyer add (``state + rounds``)
    sends XLA:CPU's LLVM pipeline into a multi-minute compile (the closing
    live range over 64 unrolled rounds; reproduced and bisected 2026-07-30).
    """

    # TPU's Mosaic/LLVM pipeline handles the fully unrolled graph fine (and
    # the scan loop overhead costs real throughput there); only XLA:CPU needs
    # the partial unroll.
    unroll = 8 if jax.default_backend() == "cpu" else 64

    def extend(carry, _):
        # carry: u32[16, R, 128] — the sliding window w[i-16..i-1]
        s0 = (_rotr(carry[1], 7) ^ _rotr(carry[1], 18)
              ^ (carry[1] >> np.uint32(3)))
        s1 = (_rotr(carry[14], 17) ^ _rotr(carry[14], 19)
              ^ (carry[14] >> np.uint32(10)))
        nxt = carry[0] + s0 + carry[9] + s1
        return jnp.concatenate([carry[1:], nxt[None]]), nxt

    _, w_ext = jax.lax.scan(extend, blk, None, length=48,
                            unroll=min(unroll, 48))
    w_all = jnp.concatenate([blk, w_ext])  # u32[64, R, 128]

    def round_(carry, xs):
        a, b, c, d, e, f, g, h = carry
        k, w = xs
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + k + w
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        return (t1 + s0 + maj, a, b, c, d + t1, e, f, g), None

    out, _ = jax.lax.scan(round_, tuple(state), (jnp.asarray(_K), w_all),
                          unroll=unroll)
    return [s + v for s, v in zip(state, out)]


@jax.jit
def sha256_words(words: jax.Array, nblocks: jax.Array) -> jax.Array:
    """SHA-256 of L pre-padded messages given as big-endian u32 words.

    words:   u32[L, B*16] — SHA-padded messages (B 64-byte blocks each).
    nblocks: i32[L]       — how many blocks of each lane are real.
    L must be a multiple of 128 (lane-tile width). Returns u8[L, 32] digests.
    """
    L, nwords = words.shape
    B = nwords // 16
    R = L // 128
    # Pre-transpose to (B, 16, R, 128) so each scan step slices contiguous
    # (R, 128) tiles — per-word strided extraction inside the scan body sends
    # XLA:CPU's layout/LLVM pipeline into a multi-minute compile.
    wt = jnp.transpose(words.reshape(L, B, 16), (1, 2, 0)).reshape(B, 16, R, 128)
    nb2 = nblocks.reshape(R, 128)

    def step(state, xs):
        j, blk = xs  # blk: u32[16, R, 128]
        new = _compress(state, blk)
        active = j < nb2
        return [jnp.where(active, n, s) for n, s in zip(new, state)], None

    # +0*message words: ties the carry init's varying-manual-axes to the data
    # input so the scan body typechecks under shard_map (device-varying) and
    # plain jit alike.
    zero = wt[0, 0] * 0 + (nb2 * 0).astype(jnp.uint32)
    init = [jnp.uint32(_H0[i]) + zero for i in range(8)]
    xs = (jnp.arange(B, dtype=jnp.int32), wt)
    state, _ = jax.lax.scan(step, init, xs)
    # 8 x u32[R,128] -> big-endian u8[L, 32]
    st = jnp.stack([s.reshape(L) for s in state], axis=1)  # u32[L, 8]
    out = jnp.stack([(st >> np.uint32(s)).astype(jnp.uint8)
                     for s in (24, 16, 8, 0)], axis=-1)
    return out.reshape(L, 32)


@jax.jit
def sha256_lanes(blocks_u8: jax.Array, nblocks: jax.Array) -> jax.Array:
    """SHA-256 of L pre-padded byte messages in parallel.

    blocks_u8: u8[L, B*64] — SHA-padded messages (B 64-byte blocks each).
    nblocks:   i32[L]      — how many blocks of each lane are real.
    L must be a multiple of 128 (lane-tile width). Returns u8[L, 32] digests.
    """
    L, nbytes = blocks_u8.shape
    w8 = blocks_u8.reshape(L, nbytes // 4, 4).astype(jnp.uint32)
    words = ((w8[..., 0] << 24) | (w8[..., 1] << 16)
             | (w8[..., 2] << 8) | w8[..., 3])
    return sha256_words(words, nblocks)


def _pad_bucket(data: np.ndarray, offs: np.ndarray, lens: np.ndarray,
                nblocks: np.ndarray, B: int) -> np.ndarray:
    """Pack + SHA-pad chunks into a u8[L, B*64] lane buffer (host side)."""
    L = len(offs)
    buf = np.zeros((L, B * 64), dtype=np.uint8)
    for i in range(L):
        n = int(lens[i])
        buf[i, :n] = data[int(offs[i]):int(offs[i]) + n]
        buf[i, n] = 0x80
        bits = n * 8
        end = int(nblocks[i]) * 64
        buf[i, end - 8:end] = np.frombuffer(
            np.uint64(bits).byteswap().tobytes(), dtype=np.uint8)
    return buf


def _lane_count(n: int) -> int:
    """Pad lane count to a power of 2, floor 128 (lane-tile width): bounds both
    XLA recompiles (log distinct shapes) and wasted lanes (<2x)."""
    if n <= 128:
        return 128
    return 1 << int(n - 1).bit_length()


def fingerprint_chunks(data: bytes | np.ndarray, cuts: np.ndarray) -> np.ndarray:
    """SHA-256 digest of every chunk [prev_cut, cut) of ``data`` on the TPU.

    Returns u8[n_chunks, 32], in chunk order. Equivalent to
    native.sha256_batch over the same ranges (asserted in tests).

    Chunks are bucketed by power-of-2 padded-block count (bounds lane waste to
    2x) and lane counts are padded to powers of 2 (bounds XLA recompiles to
    log(L) x log(B) distinct shapes).
    """
    a = np.frombuffer(data, dtype=np.uint8) if not isinstance(data, np.ndarray) else data
    cuts = np.asarray(cuts, dtype=np.int64)
    if cuts.size == 0:
        return np.empty((0, 32), dtype=np.uint8)
    offs = np.concatenate([[0], cuts[:-1]])
    lens = cuts - offs
    # +9 = 0x80 marker + 8 length bytes; ceil to 64.
    nblocks = (lens + 9 + 63) // 64
    out = np.empty((len(cuts), 32), dtype=np.uint8)
    order = np.arange(len(cuts))
    B = 1
    while True:
        sel = order[(nblocks <= B) & ((nblocks > B // 2) if B > 1 else True)]
        if sel.size:
            L = _lane_count(sel.size)
            buf = np.zeros((L, B * 64), dtype=np.uint8)
            buf[:sel.size] = _pad_bucket(a, offs[sel], lens[sel], nblocks[sel], B)
            nb = np.zeros(L, dtype=np.int32)
            nb[:sel.size] = nblocks[sel]
            # device_put, not jnp.asarray: the latter takes a slow literal path.
            digests = sha256_lanes(jax.device_put(buf), jax.device_put(nb))
            out[sel] = np.asarray(digests)[:sel.size]
        if B >= int(nblocks.max()):
            break
        B *= 2
    return out
